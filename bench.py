"""Benchmark: fused GLM objective throughput (examples/sec/chip).

Runs the L-BFGS hot kernel — fused margins -> loss derivatives -> gradient
— at an ads-scale shape and prints ONE JSON line. Since round 2 the
benched path is the tiled Pallas kernel pair (photon_ml_tpu.ops.
tiled_sparse, gather/scatter-free); the scatter/gather GLMObjective is
kept as the correctness oracle and its value is cross-checked inline.

Measurement protocol (see PERF_NOTES.md): the axon tunnel makes
block_until_ready unreliable and host round-trips cost ~300ms, so the
kernel is timed with an in-jit fori_loop with a loop-carried dependency,
differencing two loop lengths to cancel the dispatch constant.

The reference publishes no numbers (SURVEY §6, BASELINE.md); vs_baseline
is computed against our own round-1 scatter/gather measurement
(BENCH_r01.json: 1,116,299 examples/s/chip at this exact shape).
"""

import json
import time

import numpy as np

ROUND1_EXAMPLES_PER_SEC = 1_116_299  # BENCH_r01.json, same shape/protocol


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from photon_ml_tpu.data.batch import SparseBatch
    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.tiled_sparse import (
        TiledGLMObjective,
        build_tiled_batch,
    )

    rng = np.random.default_rng(0)
    n, k, d = 1 << 18, 64, 1 << 20  # 262k examples x 64 nnz, 1M features
    indices = rng.integers(0, d, size=(n, k), dtype=np.int64)
    values = rng.normal(size=(n, k)).astype(np.float32)
    labels = (rng.uniform(size=n) > 0.5).astype(np.float32)

    t0 = time.time()
    tb = build_tiled_batch(
        np.repeat(np.arange(n, dtype=np.int64), k),
        indices.reshape(-1),
        values.reshape(-1),
        labels,
        np.zeros(n, np.float32),
        np.ones(n, np.float32),
        d,
    )
    schedule_build_s = time.time() - t0
    obj = TiledGLMObjective(LOGISTIC, d)

    @jax.jit
    def loop(m, w0, tb):
        def body(i, carry):
            w, acc = carry
            v, g = obj.value_and_gradient(w, tb, 0.1)
            return (w - 1e-9 * g, acc + v)

        return lax.fori_loop(0, m, body, (w0, jnp.float32(0.0)))

    w0 = jnp.zeros((d,), jnp.float32)

    def timed(m):
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out = loop(m, w0, tb)
            _ = float(out[1])
            best = min(best, time.perf_counter() - t0)
        return best

    _ = timed(1)  # compile + warm
    iters = 11
    dt = (timed(iters) - timed(1)) / (iters - 1)
    examples_per_sec = n / dt

    # correctness oracle: one scatter/gather evaluation at the same point
    oracle = GLMObjective(LOGISTIC, d)
    sb = SparseBatch(
        indices=jnp.asarray(indices.astype(np.int32)),
        values=jnp.asarray(values),
        labels=jnp.asarray(labels),
        offsets=jnp.zeros((n,), jnp.float32),
        weights=jnp.ones((n,), jnp.float32),
    )
    w_probe = jnp.asarray(
        rng.normal(size=d).astype(np.float32) * 0.01
    )
    v_tiled, _ = jax.jit(obj.value_and_gradient)(w_probe, tb, 0.1)
    v_oracle, _ = jax.jit(oracle.value_and_gradient)(w_probe, sb, 0.1)
    oracle_rel_err = abs(float(v_tiled) - float(v_oracle)) / abs(
        float(v_oracle)
    )

    result = {
        "metric": "fused_value_and_gradient_examples_per_sec_per_chip",
        "value": round(examples_per_sec),
        "unit": "examples/sec/chip",
        "vs_baseline": round(examples_per_sec / ROUND1_EXAMPLES_PER_SEC, 2),
        "detail": {
            "kernel": "tiled_pallas_bf16x2",
            "n": n,
            "nnz_per_row": k,
            "dim": d,
            "ms_per_eval": round(dt * 1e3, 3),
            "schedule_build_s": round(schedule_build_s, 1),
            "oracle_value_rel_err": oracle_rel_err,
            "baseline": "round-1 scatter/gather kernel, same shape",
            "device": str(jax.devices()[0]),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
