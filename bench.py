"""Benchmark: fused GLM objective throughput (examples/sec/chip).

Runs the L-BFGS hot kernel — fused margins -> loss derivatives -> gradient
(photon_ml_tpu.ops.objective) — at an ads-scale shape and prints ONE JSON
line.

Measurement protocol (see PERF_NOTES.md): the axon tunnel makes
block_until_ready unreliable and host round-trips cost ~300ms, so the
kernel is timed with an in-jit fori_loop with a loop-carried dependency,
differencing two loop lengths to cancel the dispatch constant.

The reference publishes no numbers (SURVEY §6, BASELINE.md); `vs_baseline`
is 1.0 until cross-runs of the reference exist.
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from photon_ml_tpu.data.batch import SparseBatch
    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.ops.objective import GLMObjective

    rng = np.random.default_rng(0)
    n, k, d = 1 << 18, 64, 1 << 20  # 262k examples x 64 nnz, 1M features
    batch = SparseBatch(
        indices=jnp.asarray(rng.integers(0, d, size=(n, k), dtype=np.int32)),
        values=jnp.asarray(rng.normal(size=(n, k)).astype(np.float32)),
        labels=jnp.asarray((rng.uniform(size=n) > 0.5).astype(np.float32)),
        offsets=jnp.zeros((n,), jnp.float32),
        weights=jnp.ones((n,), jnp.float32),
    )
    obj = GLMObjective(LOGISTIC, d)

    @jax.jit
    def loop(m, w0):
        def body(i, carry):
            w, acc = carry
            v, g = obj.value_and_gradient(w, batch, 0.1)
            return (w - 1e-9 * g, acc + v)

        return lax.fori_loop(0, m, body, (w0, jnp.float32(0.0)))

    w0 = jnp.zeros((d,), jnp.float32)

    def timed(m):
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out = loop(m, w0)
            _ = float(out[1])
            best = min(best, time.perf_counter() - t0)
        return best

    _ = timed(1)  # compile + warm
    iters = 21
    dt = (timed(iters) - timed(1)) / (iters - 1)
    examples_per_sec = n / dt

    result = {
        "metric": "fused_value_and_gradient_examples_per_sec_per_chip",
        "value": round(examples_per_sec),
        "unit": "examples/sec/chip",
        "vs_baseline": 1.0,
        "detail": {
            "n": n,
            "nnz_per_row": k,
            "dim": d,
            "ms_per_eval": round(dt * 1e3, 3),
            "device": str(jax.devices()[0]),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
