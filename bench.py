"""Benchmark: fused GLM objective throughput (examples/sec/chip).

Default (the driver contract): runs the L-BFGS hot kernel — fused margins
-> loss derivatives -> gradient — at an ads-scale shape and prints ONE
JSON line. Since round 2 the benched path is the tiled Pallas kernel pair
(photon_ml_tpu.ops.tiled_sparse, gather/scatter-free); the scatter/gather
GLMObjective is kept as the correctness oracle and its value is
cross-checked inline.

``--suite``: the BASELINE.md matrix — end-to-end time-to-converge +
quality metrics per config (a1a-shaped logistic grid, Criteo-shaped
TRON/elastic-net, hinge+box, GLMix ~100M coef, GAME ~1B coef), one JSON
line per config plus a trailing summary line; results also written to
BASELINE_RESULTS.json. The public datasets themselves are not in the
image (zero egress), so each config runs on a fixed-seed synthetic
dataset with the SAME shape/sparsity — stated in the output — which
measures the machine, not the corpus.

Measurement protocol (see PERF_NOTES.md): the axon tunnel makes
block_until_ready unreliable and host round-trips cost ~300ms, so the
microbench kernel is timed with an in-jit fori_loop with a loop-carried
dependency, differencing two loop lengths to cancel the dispatch
constant. Suite configs time whole host-visible fits (compile excluded by
a warm run where stated).

The reference publishes no numbers (SURVEY §6, BASELINE.md); vs_baseline
is computed against our own round-1 scatter/gather measurement
(BENCH_r01.json: 1,116,299 examples/s/chip at this exact shape).
"""

import json
import os
import sys
import time

import numpy as np

ROUND1_EXAMPLES_PER_SEC = 1_116_299  # BENCH_r01.json, same shape/protocol


def main():
    # Run the real-chip test tier FIRST, before this process initializes
    # the TPU client: on direct-attached TPUs libtpu is single-process
    # -exclusive, so the pytest child must get the chip to itself.
    tpu_tests = _run_tpu_test_tier()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from photon_ml_tpu.utils.backend import enable_compilation_cache

    enable_compilation_cache()

    if all(d.platform == "cpu" for d in jax.devices()):
        # No accelerator reachable from this host (the axon tunnel can be
        # down for a whole round): the Mosaic kernels cannot execute, so
        # the headline microbench is meaningless here. Emit the sections
        # whose numbers are host-side and transfer (the overlap A/B +
        # streaming-populate accounting) with the device stated, instead
        # of crashing and leaving the round with no artifact.
        result = overlap_ab()
        result["tpu_tests"] = tpu_tests
        result["detail"]["device"] = str(jax.devices()[0])
        # the out-of-core GAME CD A/B is host-side by construction —
        # its numbers (examples_per_s, peak_rss_bytes, objective parity)
        # belong in the round artifact even with the tunnel down
        result["detail"]["streaming_game"] = _streaming_game_config(
            "streaming_game"
        )["detail"]
        # the batched λ-grid A/B runs the scatter kernel on CPU — its
        # parity + compile-count numbers (and the 1-core wall-clock,
        # recorded not gated) belong in the round artifact too
        result["detail"]["grid_batched"] = _grid_batched_config(
            "grid_batched"
        )["detail"]
        # the serving A/B is host+transfer-side too: latency/QPS at the
        # CPU-scaled shapes, plus the zero-recompile contract numbers
        result["detail"]["serving"] = _serving_config("serving")["detail"]
        # overload discipline is host-side by construction (admission,
        # shed, deadline drops, bounded drain): the contract numbers
        # belong in the round artifact even with the tunnel down
        result["detail"]["overload"] = _overload_config(
            "overload"
        )["detail"]
        # pod-scale GAME weak-scaling accounting is bytes + parity +
        # readback discipline — all valid on the virtual CPU mesh; only
        # the throughput-scaling gate is chip-only. Force the 8-device
        # mesh when this process hasn't pinned one (fresh subprocess
        # path; in-process callers already chose their device count).
        if len(jax.devices()) >= 2:
            result["detail"]["pod_game"] = _pod_game_config(
                "pod_game"
            )["detail"]
        else:
            result["detail"]["pod_game"] = {
                "note": (
                    "single visible device: run "
                    "dev-scripts/bench_pod_game.sh (forces the 8-device "
                    "virtual CPU mesh) for the sharded A/B"
                )
            }
        # the shard-routing fleet is processes + sockets + host math —
        # all its contract numbers (conservation, cache hit rate, 0
        # lowerings, degradation) are valid CPU-side; only the QPS
        # scaling gate needs cores
        result["detail"]["shard_routing"] = _shard_routing_config(
            "shard_routing"
        )["detail"]
        result["detail"]["note"] = (
            "CPU-only host (accelerator unreachable); kernel-path "
            "microbench and BASELINE suite skipped — see the last "
            "chip-attached BENCH round for those numbers"
        )
        print(json.dumps(result))
        return result

    from photon_ml_tpu.data.batch import SparseBatch
    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.tiled_sparse import (
        TiledGLMObjective,
        build_tiled_batch,
    )

    rng = np.random.default_rng(0)
    n, k, d = 1 << 18, 64, 1 << 20  # 262k examples x 64 nnz, 1M features
    indices = rng.integers(0, d, size=(n, k), dtype=np.int64)
    values = rng.normal(size=(n, k)).astype(np.float32)
    labels = (rng.uniform(size=n) > 0.5).astype(np.float32)

    rows_flat = np.repeat(np.arange(n, dtype=np.int64), k)
    t0 = time.time()
    tb = build_tiled_batch(
        rows_flat,
        indices.reshape(-1),
        values.reshape(-1),
        labels,
        np.zeros(n, np.float32),
        np.ones(n, np.float32),
        d,
    )
    schedule_build_s = time.time() - t0

    # Persistent schedule-cache cold vs warm at the same shape
    # (ops/schedule_cache.py): cold pays build + artifact store, warm
    # pays content hash + mmap load only — the number the λ-grid /
    # repeated-driver-run story rides on.
    import shutil
    import tempfile

    from photon_ml_tpu.ops import schedule_cache as _sc

    cache_tmp = tempfile.mkdtemp(prefix="photon-tile-cache-bench-")
    try:
        with _sc.cache_scope(cache_tmp):
            t0 = time.perf_counter()
            build_tiled_batch(
                rows_flat, indices.reshape(-1), values.reshape(-1),
                labels, np.zeros(n, np.float32), np.ones(n, np.float32), d,
            )
            schedule_build_s_cold = time.perf_counter() - t0
            t0 = time.perf_counter()
            build_tiled_batch(
                rows_flat, indices.reshape(-1), values.reshape(-1),
                labels, np.zeros(n, np.float32), np.ones(n, np.float32), d,
            )
            schedule_build_s_warm = time.perf_counter() - t0
        schedule_cache_stats = _sc.stats().as_dict()
    finally:
        shutil.rmtree(cache_tmp, ignore_errors=True)
    obj = TiledGLMObjective(LOGISTIC, d)

    def make_loop(o):
        @jax.jit
        def loop(m, w0, tb):
            def body(i, carry):
                w, acc = carry
                v, g = o.value_and_gradient(w, tb, 0.1)
                return (w - 1e-9 * g, acc + v)

            return lax.fori_loop(0, m, body, (w0, jnp.float32(0.0)))

        return loop

    loop = make_loop(obj)
    w0 = jnp.zeros((d,), jnp.float32)
    iters = 11

    def timed(loop_fn, batch, m):
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out = loop_fn(m, w0, batch)
            _ = float(out[1])
            best = min(best, time.perf_counter() - t0)
        return best

    def measure(loop_fn, batch):
        _ = timed(loop_fn, batch, 1)  # compile + warm
        return (
            timed(loop_fn, batch, iters) - timed(loop_fn, batch, 1)
        ) / (iters - 1)

    # best of two full measurements: transient relay contention windows
    # (observed: a 20.6 ms sample minutes before a 16.3 ms one, same
    # binary) must not masquerade as a kernel regression in the one
    # capture the driver keeps
    dt = min(measure(loop, tb), measure(loop, tb))
    examples_per_sec = n / dt

    # Kernel-chapter close-out A/B: the MXU-packed one-hot expansion
    # (onehot="mxu", the round-3 "pack the one-hot build onto the MXU"
    # lever) against the compare build, same schedules, back-to-back —
    # the record PERF_NOTES round 7 carries so it is never re-litigated.
    loop_moh = make_loop(TiledGLMObjective(LOGISTIC, d, onehot="mxu"))
    try:
        dt_moh = min(measure(loop_moh, tb), measure(loop_moh, tb))
    except Exception as e:  # Mosaic lowering may reject the tiny matmul
        dt_moh = None
        moh_error = f"{type(e).__name__}: {e}"[:300]

    # correctness oracle: one scatter/gather evaluation at the same point
    oracle = GLMObjective(LOGISTIC, d)
    sb = SparseBatch(
        indices=jnp.asarray(indices.astype(np.int32)),
        values=jnp.asarray(values),
        labels=jnp.asarray(labels),
        offsets=jnp.zeros((n,), jnp.float32),
        weights=jnp.ones((n,), jnp.float32),
    )
    w_probe = jnp.asarray(
        rng.normal(size=d).astype(np.float32) * 0.01
    )
    v_tiled, _ = jax.jit(obj.value_and_gradient)(w_probe, tb, 0.1)
    v_oracle, _ = jax.jit(oracle.value_and_gradient)(w_probe, sb, 0.1)
    oracle_rel_err = abs(float(v_tiled) - float(v_oracle)) / abs(
        float(v_oracle)
    )

    # Same fused eval under a 1-device mesh: the tiled kernels run
    # UNMODIFIED inside shard_map (per-shard schedules + psum) — the
    # "fast AND distributed simultaneously" property, recorded so the
    # artifact shows no mesh penalty (round 2 silently fell back to the
    # ~10x-slower scatter objective here).
    from functools import partial as _partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from photon_ml_tpu.ops.tiled_sparse import ensure_tiled_sharded
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, make_mesh

    mesh = make_mesh((1,), (DATA_AXIS,), devices=jax.devices()[:1])
    # tb already has the 1-shard layout: pass-through + device_put only
    # (building from sb would re-pull the device batch and rebuild both
    # schedules)
    tb_mesh = ensure_tiled_sharded(tb, d, mesh)
    obj_mesh = obj.with_axis(DATA_AXIS)

    @jax.jit
    def mesh_loop(m, w0_, tb_):
        @_partial(
            shard_map, mesh=mesh, in_specs=(P(), P(DATA_AXIS), P()),
            out_specs=(P(), P()), check_vma=False,
        )
        def vg(w, b, l2):
            return obj_mesh.value_and_gradient(w, b, l2)

        def body(i, carry):
            w, acc = carry
            v, g = vg(w, tb_, jnp.float32(0.1))
            return (w - 1e-9 * g, acc + v)

        return lax.fori_loop(0, m, body, (w0_, jnp.float32(0.0)))

    mesh_dt = min(measure(mesh_loop, tb_mesh), measure(mesh_loop, tb_mesh))

    # Roofline: distance to the machine's ceilings, not to round 1
    # (VERDICT r4 weak #3). Three bounds for THIS schedule geometry:
    # - mxu_floor_ms: pure-MXU time if only the kernel's matmuls ran —
    #   each grid step issues 2 fused full-width bf16 matmul pairs of
    #   [128, 128] x [128, L] (gather + scatter sides), ~197 bf16
    #   TFLOP/s on a v5e-class chip.
    # - dispatched_step_bound_ms: the measured-step cost model from
    #   PERF_NOTES round 4 — ~2.0 us per grid step (MXU + the one-hot
    #   VPU chain Mosaic will not overlap) + ~15 ns per spilled entry.
    #   This is the bound parameter tuning cannot beat; going below it
    #   needs a different expansion algorithm or a Mosaic change.
    # - hbm_bytes_bound_ms: schedule + row traffic at ~819 GB/s.
    steps_total = tb.z_sched.num_steps + tb.g_sched.num_steps
    L = tb.params.chunk
    spills = int(tb.z_sched.spill_vals.shape[0]) + int(
        tb.g_sched.spill_vals.shape[0]
    )
    # per grid step: one gather matmul [128,128]x[128,L] + one scatter
    # matmul [128,L]x[L,128] (bf16x2w fuses the hi/lo split into these
    # full-width tiles), 128*128*L MACs each
    macs_per_step = 2 * 128 * 128 * L
    mxu_floor_ms = steps_total * macs_per_step * 2 / 197e12 * 1e3  # FLOPs
    # measured round-4 dispatched cost: 16.4 ms / 8192 total steps =
    # ~2.0 us per grid step (MXU + the one-hot VPU chain Mosaic will not
    # overlap) — the bound parameter tuning cannot beat
    dispatched_bound_ms = steps_total * 2.0e-3 + spills * 15e-6
    sched_bytes = sum(
        int(np.asarray(a).nbytes)
        for s_ in (tb.z_sched, tb.g_sched)
        for a in (s_.out_pos, s_.in_pos, s_.vals)
    )
    hbm_bytes_bound_ms = sched_bytes / 819e9 * 1e3

    # host-device overlap A/B (CPU-scaled shape; the full config-5 A/B
    # runs via dev-scripts/bench_overlap.sh / `bench.py --overlap-ab --full`)
    overlap_result = overlap_ab()
    streaming_game = _streaming_game_config("streaming_game")["detail"]

    result = {
        "metric": "fused_value_and_gradient_examples_per_sec_per_chip",
        "value": round(examples_per_sec),
        "unit": "examples/sec/chip",
        "vs_baseline": round(examples_per_sec / ROUND1_EXAMPLES_PER_SEC, 2),
        "tpu_tests": tpu_tests,
        "overlap": overlap_result["detail"],
        "streaming_game": streaming_game,
        "detail": {
            "kernel": "tiled_pallas_" + obj.mxu,
            "n": n,
            "nnz_per_row": k,
            "dim": d,
            "ms_per_eval": round(dt * 1e3, 3),
            "ms_per_eval_mxu_onehot": (
                round(dt_moh * 1e3, 3) if dt_moh is not None else moh_error
            ),
            "ms_per_eval_1dev_mesh": round(mesh_dt * 1e3, 3),
            "schedule_build_s": round(schedule_build_s, 1),
            "schedule_build_s_cold": round(schedule_build_s_cold, 2),
            "schedule_build_s_warm": round(schedule_build_s_warm, 2),
            "schedule_cache_warm_speedup": round(
                schedule_build_s_cold / max(schedule_build_s_warm, 1e-9), 1
            ),
            "schedule_cache": schedule_cache_stats,
            "oracle_value_rel_err": oracle_rel_err,
            "baseline": "round-1 scatter/gather kernel, same shape",
            "roofline": {
                "measured_ms": round(dt * 1e3, 3),
                "dispatched_step_bound_ms": round(dispatched_bound_ms, 2),
                "x_off_dispatched_bound": round(
                    dt * 1e3 / dispatched_bound_ms, 2
                ),
                "mxu_floor_ms": round(mxu_floor_ms, 2),
                "hbm_bytes_bound_ms": round(hbm_bytes_bound_ms, 2),
                "grid_steps_per_eval": int(steps_total),
                "spilled_entries_per_eval": spills,
                "model": (
                    "2.0us/grid-step (r4 measured: 16.4ms / 8192 steps) "
                    "+ 15ns/spill; MXU floor at 197 bf16 TFLOP/s; HBM at "
                    "819 GB/s"
                ),
            },
            "device": str(jax.devices()[0]),
        },
    }
    print(json.dumps(result))
    return result


def overlap_ab(full: bool = False):
    """Host-device overlap A/B (parallel/overlap.py): the config-5-shaped
    GAME coordinate-descent step with overlap on vs off, plus the
    streaming cold-populate pipeline accounting (wall vs host-decode vs
    device-consume). ``full`` uses the BASELINE config-5 scale (chip-class
    hosts); the default is the same SHAPE (FE + two multi-bucket RE banks
    through the real CoordinateDescent) scaled for a CPU host.

    What the A/B exercises: deferred readbacks (one batched device_get
    per iteration instead of per-bank tracker + per-coordinate reg-term
    pulls — each ~100 ms over a relay-attached chip), prefetched host
    prep under device solves, and async artifact IO. On a single-core
    CPU-only host the expectation is PARITY (the eliminated costs are
    relay/async-device latencies that do not exist there); the serial
    path must not be faster.
    """
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.game import (
        CoordinateDescent,
        FeatureShardConfiguration,
        FixedEffectCoordinate,
        RandomEffectCoordinate,
        RandomEffectDataConfiguration,
        RandomEffectOptimizationProblem,
        build_game_dataset,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.optim.config import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.optim.problem import create_glm_problem
    from photon_ml_tpu.parallel import overlap
    from photon_ml_tpu.task import TaskType

    rng = np.random.default_rng(0)
    if full:
        n, dg, n_users, n_items = 1 << 17, 1 << 16, 60_000, 40_000
    else:
        n, dg, n_users, n_items = 16_384, 4_096, 2_000, 1_200
    kg, ku = 16, 6
    # Skewed entity frequencies (Zipf-ish) land the RE datasets in
    # MULTIPLE capacity-class buckets — the per-bucket dispatch/readback
    # structure the overlap layer targets (config 5 runs 24 + 16 buckets).
    users = np.minimum(
        (rng.pareto(1.2, size=n) * n_users / 20).astype(np.int64), n_users - 1
    )
    items = np.minimum(
        (rng.pareto(1.2, size=n) * n_items / 20).astype(np.int64), n_items - 1
    )
    gix = rng.integers(0, dg, size=(n, kg))
    gv = rng.normal(size=(n, kg)).astype(np.float32)
    uv = rng.normal(size=(n, ku)).astype(np.float32)
    z = gv.sum(axis=1) * 0.1 + uv.sum(axis=1) * 0.2
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)
    recs = [
        {
            "uid": f"r{i}",
            "response": float(y[i]),
            "userId": f"u{users[i]}",
            "itemId": f"i{items[i]}",
            "features": [
                {"name": str(int(j)), "term": "", "value": float(v)}
                for j, v in zip(gix[i], gv[i])
            ],
            "userFeatures": [
                {"name": f"f{j}", "term": "", "value": float(uv[i][j])}
                for j in range(ku)
            ],
        }
        for i in range(n)
    ]
    shards = [
        FeatureShardConfiguration("globalShard", ["features"], add_intercept=True),
        FeatureShardConfiguration("userShard", ["userFeatures"], add_intercept=True),
    ]
    ds = build_game_dataset(recs, shards, ["userId", "itemId"])
    del recs

    def build_cd():
        red_u = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard")
        )
        red_i = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("itemId", "userShard")
        )
        coords = {
            "fixed": FixedEffectCoordinate(
                name="fixed",
                dataset=ds,
                problem=create_glm_problem(
                    TaskType.LOGISTIC_REGRESSION,
                    ds.shards["globalShard"].dim,
                    config=OptimizerConfig(max_iter=25),
                    regularization=RegularizationContext(
                        RegularizationType.L2
                    ),
                ),
                feature_shard_id="globalShard",
                reg_weight=0.5,
            ),
            "perUser": RandomEffectCoordinate(
                name="perUser", dataset=ds, re_dataset=red_u,
                problem=RandomEffectOptimizationProblem(
                    LOGISTIC, OptimizerConfig(max_iter=15),
                    RegularizationContext(RegularizationType.L2),
                    reg_weight=1.0,
                ),
            ),
            "perItem": RandomEffectCoordinate(
                name="perItem", dataset=ds, re_dataset=red_i,
                problem=RandomEffectOptimizationProblem(
                    LOGISTIC, OptimizerConfig(max_iter=15),
                    RegularizationContext(RegularizationType.L2),
                    reg_weight=1.0,
                ),
            ),
        }
        n_buckets = len(red_u.buckets) + len(red_i.buckets)
        return CoordinateDescent(
            coords, ds, TaskType.LOGISTIC_REGRESSION,
            update_sequence=["fixed", "perUser", "perItem"],
        ), n_buckets

    cd, n_buckets = build_cd()
    with overlap.overlap_scope(True):
        cd.run(1)  # compile + device caches (both modes share programs)

    def step_time(enabled):
        best = float("inf")
        for _ in range(2):
            with overlap.overlap_scope(enabled):
                t0 = time.perf_counter()
                cd.run(1)
                best = min(best, time.perf_counter() - t0)
        return best

    # alternate to keep host-load drift out of the comparison
    t_on = step_time(True)
    t_off = step_time(False)
    t_on = min(t_on, step_time(True))
    t_off = min(t_off, step_time(False))
    with overlap.overlap_scope(True):
        overlap.reset_readback_stats()
        cd.run(1)
        readbacks_on = overlap.readback_stats()
    with overlap.overlap_scope(False):
        overlap.reset_readback_stats()
        cd.run(1)
        readbacks_off = overlap.readback_stats()

    # -- streaming cold-populate pipeline accounting ------------------------
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container
    from photon_ml_tpu.io.input_format import AvroInputDataFormat
    from photon_ml_tpu.io.streaming import (
        StreamingGLMObjective,
        iter_chunks,
        scan_stream,
    )

    tmp = tempfile.mkdtemp(prefix="photon-overlap-bench-")
    try:
        r = np.random.default_rng(1)
        n_files, rows_per_file, ds_d, ks = (
            (8, 125_000, 200_000, 16) if full else (6, 8_000, 20_000, 12)
        )
        for fi in range(n_files):
            sx = r.integers(0, ds_d, size=(rows_per_file, ks))
            sv = r.normal(size=(rows_per_file, ks))
            lab = (r.uniform(size=rows_per_file) > 0.5).astype(float)
            write_container(
                f"{tmp}/p{fi}.avro",
                schemas.TRAINING_EXAMPLE_AVRO,
                [
                    {
                        "uid": f"{fi}-{i}",
                        "label": float(lab[i]),
                        "features": [
                            {"name": str(int(j)), "term": "", "value": float(v)}
                            for j, v in zip(sx[i], sv[i])
                        ],
                        "offset": 0.0,
                        "weight": 1.0,
                    }
                    for i in range(rows_per_file)
                ],
            )
        fmt = AvroInputDataFormat()
        index_map, stats = scan_stream([tmp], fmt)

        def populate_wall(overlapped):
            with overlap.overlap_scope(overlapped):
                sobj = StreamingGLMObjective(
                    [tmp], fmt, index_map, stats,
                    TaskType.LOGISTIC_REGRESSION,
                    rows_per_chunk=16_384, kernel="scatter",
                    prefetch=overlapped,
                )
                w = jnp.zeros((sobj.dim,), jnp.float32)
                t0 = time.perf_counter()
                v, _ = sobj.value_and_gradient(w, 0.1)
                _ = float(v)
                wall = time.perf_counter() - t0
                # device-consume per pass: the cached eval (no decode)
                t0 = time.perf_counter()
                v, _ = sobj.value_and_gradient(w, 0.1)
                _ = float(v)
                consume = time.perf_counter() - t0
            return wall, consume

        populate_wall(True)  # compile the partial program once
        wall_piped, consume_s = populate_wall(True)
        wall_serial, _ = populate_wall(False)
        wall_piped = min(wall_piped, populate_wall(True)[0])
        wall_serial = min(wall_serial, populate_wall(False)[0])
        # host decode+stage alone: drain the chunk iterator, no compute
        t0 = time.perf_counter()
        for _chunk in iter_chunks(
            [tmp], fmt, index_map,
            rows_per_chunk=16_384, nnz_width=stats.max_nnz, pipeline=False,
        ):
            pass
        decode_s = time.perf_counter() - t0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    one_core = os.cpu_count() == 1 if hasattr(os, "cpu_count") else False
    return {
        "metric": "overlap_ab",
        "value": round(t_off / t_on, 3),
        "unit": "x speedup (GAME CD step, overlap on vs off)",
        "detail": {
            "scale": "config-5 full" if full else "config-5-shaped, CPU-scaled",
            "game_step": {
                "rows": n,
                "fe_dim": int(ds.shards["globalShard"].dim),
                "re_entities": [n_users, n_items],
                "re_buckets_total": n_buckets,
                "step_s_overlap_on": round(t_on, 3),
                "step_s_overlap_off": round(t_off, 3),
                "speedup": round(t_off / t_on, 3),
                "readbacks_per_step_on": readbacks_on,
                "readbacks_per_step_off": readbacks_off,
            },
            "streaming_populate": {
                "files": n_files,
                "rows": n_files * rows_per_file,
                "cold_populate_wall_s_pipelined": round(wall_piped, 3),
                "cold_populate_wall_s_serial": round(wall_serial, 3),
                "host_decode_stage_s": round(decode_s, 3),
                "device_consume_s": round(consume_s, 3),
                "bound_max_decode_consume_s": round(
                    max(decode_s, consume_s), 3
                ),
                "bound_sum_s": round(decode_s + consume_s, 3),
                # the acceptance inequality, with a 15%+50ms epsilon:
                # multicore/chip hosts must meet the max() bound; a
                # single-core host can only meet the sum() bound (no
                # second core to run the decode under the consume)
                "wall_within_max_bound": bool(
                    wall_piped
                    <= max(decode_s, consume_s) * 1.15 + 0.05
                ),
                "wall_within_sum_bound": bool(
                    wall_piped <= (decode_s + consume_s) * 1.15 + 0.05
                ),
            },
            "host": {
                "cpu_count": os.cpu_count(),
                "note": (
                    "single-core host: compute/compute overlap is "
                    "physically unavailable; the pipelined wall is bounded "
                    "by decode+consume, and the GAME A/B gate is parity "
                    "(>=1.15x applies on relay/chip-attached hosts where "
                    "the eliminated ~100ms readbacks and ~125ms dispatch "
                    "gaps exist — PERF_NOTES round 5/6)"
                    if one_core
                    else "multi-core host"
                ),
            },
        },
    }


def _run_tpu_test_tier():
    """Run the PHOTON_TPU_TESTS-gated tier (the tiled kernel on the real
    chip) in a subprocess and record pass/fail plus every skip reason the
    CPU suite hides (SURVEY §4: tests must exercise the real execution
    target where one exists). Recorded in the bench JSON so the driver
    artifact carries it each round."""
    import os
    import re
    import subprocess
    import sys

    env = dict(os.environ, PHOTON_TPU_TESTS="1")
    try:
        proc = subprocess.run(
            [
                sys.executable, "-m", "pytest",
                "tests/test_tiled_tpu.py", "-q", "-rs", "-p", "no:cacheprovider",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=1200,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        tail = (proc.stdout or "").strip().splitlines()
        summary = tail[-1] if tail else ""
        skips = sorted(
            set(
                m.group(1).strip()
                for m in re.finditer(
                    r"SKIPPED \[\d+\][^:]*:\d+: (.+)", proc.stdout or ""
                )
            )
        )
        # the full CPU suite's skip GATES (why a test may skip there),
        # collected statically so the artifact documents all of them
        # without a 20-minute suite run here
        gates = set()
        tests_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tests"
        )
        for fn in sorted(os.listdir(tests_dir)):
            if fn.endswith(".py"):
                with open(os.path.join(tests_dir, fn)) as f:
                    src = f.read()
                gates.update(re.findall(r'reason=f?"([^"]+)"', src))
                # pytest.skip("..." "...") — f-strings and implicitly
                # concatenated fragments included
                for m in re.finditer(
                    r'pytest\.skip\(\s*((?:f?"[^"]*"\s*)+)', src
                ):
                    gates.add(
                        "".join(re.findall(r'"([^"]*)"', m.group(1)))
                    )
        return {
            "ok": proc.returncode == 0,
            "summary": summary,
            "skip_reasons": skips,
            "suite_skip_gates": sorted(gates),
        }
    except Exception as e:  # the bench headline must still print
        return {"ok": False, "summary": f"tier failed to run: {e}"}


# ---------------------------------------------------------------------------
# BASELINE.md suite
# ---------------------------------------------------------------------------


def _synth_sparse(rng, n, d, k, *, task="logistic", noise=0.5):
    """Fixed-seed synthetic sparse problem with a planted model."""
    w_true = (rng.normal(size=d) * (rng.uniform(size=d) < 0.2)).astype(
        np.float32
    )
    return _regen_with_model(rng, n, d, k, w_true, task, noise=noise)


def _glm_fit_config(
    name,
    *,
    task,
    optimizer,
    reg_type,
    lambdas,
    n,
    d,
    k,
    n_val=0,
    max_iter=None,
    box_bound=None,
    elastic_net_alpha=None,
    kernel="auto",
    seed=0,
    shape_note="",
):
    """Train a lambda grid end-to-end; report warm time-to-converge +
    validation quality (the BASELINE.json metrics contract)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.evaluation import (
        area_under_roc_curve,
        root_mean_squared_error,
    )
    from photon_ml_tpu.models.glm import compute_margins, compute_means
    from photon_ml_tpu.optim.common import BoxConstraints
    from photon_ml_tpu.task import TaskType
    from photon_ml_tpu.training import train_generalized_linear_model
    from photon_ml_tpu.optim import OptimizerType, RegularizationType

    rng = np.random.default_rng(seed)
    task_t = TaskType.parse(task)
    gen_task = {
        "LOGISTIC_REGRESSION": "logistic",
        "LINEAR_REGRESSION": "linear",
        "POISSON_REGRESSION": "poisson",
        "SMOOTHED_HINGE_LOSS_LINEAR_SVM": "hinge",
    }[task_t.name]
    batch, w_true = _synth_sparse(rng, n, d, k, task=gen_task)
    vbatch = None
    if n_val:
        # held-out set drawn from the SAME planted model
        vbatch, _ = _regen_with_model(
            np.random.default_rng(seed + 1), n_val, d, k, w_true, gen_task
        )
    box = None
    if box_bound is not None:
        box = BoxConstraints(
            lower=jnp.full((d,), -box_bound, jnp.float32),
            upper=jnp.full((d,), box_bound, jnp.float32),
        )

    # Resolve + prebuild the tiled schedule OUTSIDE the timed fit: the
    # schedule is static per dataset (the index-build analog), so
    # time-to-converge should not re-pay it per lambda grid.
    from photon_ml_tpu.optim.problem import resolve_kernel

    kernel = resolve_kernel(kernel, batch)
    schedule_build_s = 0.0
    if kernel == "tiled":
        from photon_ml_tpu.ops.tiled_sparse import tiled_batch_from_sparse

        # untimed: pull the synthetic device-resident batch to host first —
        # a real driver builds schedules from host-loaded data, so the
        # tunnel D2H of this harness's synthetic arrays must not be billed
        # to the schedule build (it dominated: ~20 s of an observed 24 s)
        host_batch = jax.device_get(batch)  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
        t0 = time.perf_counter()
        batch = tiled_batch_from_sparse(host_batch, d)
        schedule_build_s = time.perf_counter() - t0

    kwargs = dict(
        optimizer_type=OptimizerType.parse(optimizer),
        regularization_type=RegularizationType.parse(reg_type),
        regularization_weights=lambdas,
        elastic_net_alpha=elastic_net_alpha,
        max_iter=max_iter,
        box=box,
        kernel=kernel,
    )

    def fit():
        t0 = time.perf_counter()
        models, results = train_generalized_linear_model(
            batch, task_t, d, **kwargs
        )
        # force completion host-side
        for r in results.values():
            _ = int(r.iterations)
        return models, results, time.perf_counter() - t0

    _, _, cold_s = fit()  # compile
    models, results, warm_s = fit()  # time-to-converge, compile excluded

    total_iters = sum(int(r.iterations) for r in results.values())
    quality = {}
    if vbatch is not None:
        lam_best, best = None, None
        for lam, model in models.items():
            margins = compute_margins(model.means, vbatch)
            if task_t == TaskType.LOGISTIC_REGRESSION or (
                task_t == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM
            ):
                score = float(
                    area_under_roc_curve(
                        margins, vbatch.labels, vbatch.weights
                    )
                )
                better = best is None or score > best
            else:
                means = compute_means(task_t, model.means, vbatch)
                score = float(
                    root_mean_squared_error(
                        means, vbatch.labels, vbatch.weights
                    )
                )
                better = best is None or score < best
            if better:
                best, lam_best = score, lam
        quality = {
            "metric": (
                "AUC"
                if task_t
                in (
                    TaskType.LOGISTIC_REGRESSION,
                    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
                )
                else "RMSE"
            ),
            "best_value": best,
            "best_lambda": lam_best,
        }
    return {
        "config": name,
        "metric": "time_to_converge_s",
        "value": round(warm_s, 3),
        "unit": "s (lambda grid, warm)",
        "detail": {
            "task": task_t.name,
            "optimizer": optimizer,
            "regularization": reg_type,
            "lambdas": lambdas,
            "n": n,
            "dim": d,
            "nnz_per_row": k,
            "examples_per_sec": round(n * total_iters / warm_s)
            if warm_s > 0
            else None,
            "total_iterations": total_iters,
            "cold_s": round(cold_s, 3),
            "kernel": kernel,
            "schedule_build_s": round(schedule_build_s, 2),
            "validation": quality,
            "data": shape_note or "fixed-seed synthetic, planted model",
        },
    }


def _feature_sharded_tron_config(name, *, n, d, k, lam=1.0, seed=0):
    """Config 2a on the feature-sharded TILED path under a 1-device
    (data, model) mesh: measures what the sharded TRON composition costs
    on one chip (the distributed-path analog of the headline's
    ms_per_eval_1dev_mesh check) — the tiled Hv factory riding the z/g
    schedules inside shard_map (TRON.scala:259-341 +
    HessianVectorAggregator.scala:137-152). Multi-chip scaling itself is
    the mesh's job (MULTICHIP_WEAK_SCALING.md)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.ops.tiled_sparse import feature_shard_tiled_batch
    from photon_ml_tpu.parallel.distributed import (
        feature_sharded_tiled_fit_tron,
    )
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
    from photon_ml_tpu.task import TaskType

    rng = np.random.default_rng(seed)
    batch, _ = _synth_sparse(rng, n, d, k, task="linear")
    host_batch = jax.device_get(batch)  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
    mesh = make_mesh(
        (1, 1), (DATA_AXIS, MODEL_AXIS), devices=jax.devices()[:1]
    )
    t0 = time.perf_counter()
    sharded, block_dim = feature_shard_tiled_batch(
        host_batch, d, 1, 1, mesh=mesh
    )
    schedule_build_s = time.perf_counter() - t0
    objective = GLMObjective(
        loss_for_task(TaskType.LINEAR_REGRESSION), d
    )
    fit = feature_sharded_tiled_fit_tron(
        objective, mesh, sharded.meta, max_iter=15, tol=1e-5
    )

    def run():
        t0 = time.perf_counter()
        res = fit(
            jnp.zeros((block_dim,), jnp.float32), sharded, jnp.float32(lam)
        )
        iters = int(res.iterations)
        return iters, time.perf_counter() - t0

    _, cold_s = run()
    iters, warm_s = run()
    return {
        "config": name,
        "metric": "time_to_converge_s",
        "value": round(warm_s, 3),
        "unit": "s (one lambda, warm)",
        "detail": {
            "task": "LINEAR_REGRESSION",
            "optimizer": "TRON",
            "path": "feature-sharded tiled (1x1 mesh, shard_map)",
            "n": n,
            "dim": d,
            "nnz_per_row": k,
            "examples_per_sec": round(n * iters / warm_s) if warm_s else None,
            "total_iterations": iters,
            "cold_s": round(cold_s, 3),
            "kernel": "tiled",
            "schedule_build_s": round(schedule_build_s, 2),
            "data": "synthetic at Criteo-sample shape, sharded-path cost check",
        },
    }


def _game_fe_sharded_config(name, *, n=1 << 18, d=1 << 20, k=64, seed=0):
    """Config-4-shaped GAME FIXED EFFECT solved through FixedEffectCoordinate
    under a 1x1 (data, model) mesh — proves the feature-sharded GAME FE
    composition (round-5 wiring: FixedEffectCoordinate._update_model_
    feature_sharded) costs nothing on one chip vs the same coordinate's
    replicated solve. Match: the reference runs the GAME FE distributed by
    construction at huge dimension (cli/game/training/Driver.scala:357-363,
    717-719)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.game.coordinate import FixedEffectCoordinate
    from photon_ml_tpu.game.data import GameDataset, ShardData
    from photon_ml_tpu.optim.config import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.optim.problem import create_glm_problem
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
    from photon_ml_tpu.task import TaskType

    rng = np.random.default_rng(seed)
    batch, _ = _synth_sparse(rng, n, d, k)
    host = jax.device_get(batch)  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
    from photon_ml_tpu.utils.index_map import IdentityIndexMap

    shard = ShardData(
        indices=np.asarray(host.indices),  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
        values=np.asarray(host.values),  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
        index_map=IdentityIndexMap(d),
        intercept_index=None,
    )
    ds = GameDataset(
        uids=[""] * n,
        labels=np.asarray(host.labels),  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
        offsets=np.zeros(n, np.float32),
        weights=np.ones(n, np.float32),
        shards={"global": shard},
        entity_codes={},
        entity_indexes={},
        num_real_rows=n,
    )
    mesh = make_mesh(
        (1, 1), (DATA_AXIS, MODEL_AXIS), devices=jax.devices()[:1]
    )
    out = {}
    for label, m in (("sharded_1x1", mesh), ("replicated", None)):
        coord = FixedEffectCoordinate(
            name="fe",
            dataset=ds,
            problem=create_glm_problem(
                TaskType.LOGISTIC_REGRESSION, d,
                config=OptimizerConfig(max_iter=50),
                regularization=RegularizationContext(RegularizationType.L2),
                kernel="tiled",
            ),
            feature_shard_id="global",
            reg_weight=1.0,
            mesh=m,
        )

        def step(model):
            t0 = time.perf_counter()
            model, res = coord.update_model(model)
            _ = float(jnp.sum(model.model.means))  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
            return model, time.perf_counter() - t0

        model, cold_s = step(coord.initialize_model())
        # one more warm-up: the first warm-started call traces a second
        # program variant (fresh-coefficients vs warm-start shardings)
        model, _ = step(model)
        model, warm_s = step(model)
        out[label] = {"warm_s": round(warm_s, 3), "cold_s": round(cold_s, 3)}
    ratio = out["sharded_1x1"]["warm_s"] / max(out["replicated"]["warm_s"], 1e-9)
    return {
        "config": name,
        "metric": "game_fe_sharded_vs_replicated_warm_ratio",
        "value": round(ratio, 3),
        "unit": "x (1.0 = zero composition cost)",
        "detail": {
            "n": n, "dim": d, "nnz_per_row": k,
            **{f"{k_}_{m}": v for k_, d_ in out.items() for m, v in d_.items()},
            "path": "FixedEffectCoordinate feature-sharded (1x1 mesh) vs "
                    "replicated, tiled kernel both sides",
            "data": "synthetic at BASELINE config-4 FE shape",
        },
    }


def _streaming_config(name, *, n_files=8, rows_per_file=125_000, d=200_000,
                      k=16, seed=0):
    """Streaming (>RAM-shaped) path: full-batch (value, gradient) with
    chunked Avro decode. Measures evaluation 1 (decode + cache populate)
    vs evaluation 2+ (staged-chunk cache, zero Avro decode — the
    persist(MEMORY_AND_DISK) semantics landed round 4) and reports the
    cache speedup. Dataset size is a harness-budget stand-in; the path's
    memory bound is one decoded file + one staged chunk regardless of
    scale (tests/test_streaming.py pins bounded RSS)."""
    import shutil
    import tempfile

    import jax.numpy as jnp

    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container
    from photon_ml_tpu.io.input_format import AvroInputDataFormat
    from photon_ml_tpu.io.streaming import StreamingGLMObjective, scan_stream
    from photon_ml_tpu.task import TaskType

    rng = np.random.default_rng(seed)
    tmp = tempfile.mkdtemp(prefix="photon-stream-bench-")
    try:
        w_true = rng.normal(size=d).astype(np.float32) * 0.2
        gen_t = 0.0
        t0 = time.perf_counter()
        for fi in range(n_files):
            ix = rng.integers(0, d, size=(rows_per_file, k))
            vs = rng.normal(size=(rows_per_file, k)).astype(np.float32)
            z = (w_true[ix] * vs).sum(axis=1)
            y = (rng.uniform(size=rows_per_file) < 1 / (1 + np.exp(-z)))
            recs = [
                {
                    "uid": f"{fi}-{i}",
                    "label": float(y[i]),
                    "features": [
                        {"name": str(int(j)), "term": "", "value": float(v)}
                        for j, v in zip(ix[i], vs[i])
                    ],
                    "offset": 0.0,
                    "weight": 1.0,
                }
                for i in range(rows_per_file)
            ]
            write_container(
                f"{tmp}/part-{fi:03d}.avro",
                schemas.TRAINING_EXAMPLE_AVRO,
                recs,
            )
        gen_t = time.perf_counter() - t0
        fmt = AvroInputDataFormat()
        t0 = time.perf_counter()
        index_map, stats = scan_stream([tmp], fmt)
        scan_s = time.perf_counter() - t0
        obj = StreamingGLMObjective(
            [tmp], fmt, index_map, stats, TaskType.LOGISTIC_REGRESSION
        )
        w = jnp.zeros((obj.dim,), jnp.float32)

        def one_eval():
            t0 = time.perf_counter()
            v, g = obj.value_and_gradient(w, 0.1)
            _ = float(v) + float(jnp.sum(g))  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
            return time.perf_counter() - t0

        eval1_s = one_eval()  # decode + cache populate (+ compile)
        eval_rt_s = min(one_eval() for _ in range(3))  # cached + readback

        # Cached-eval DEVICE rate with the tunnel readback amortized
        # (PERF_NOTES protocol: each host<->device readback costs ~100 ms
        # over the axon relay and would otherwise dominate; a local chip
        # pays ~us). Chained evals keep a real data dependency.
        def eval_chain(m):
            t0 = time.perf_counter()
            w_ = w
            for _ in range(m):
                v, g = obj.value_and_gradient(w_, 0.1)
                w_ = w_ - 1e-9 * g
            _ = float(v) + float(jnp.sum(g))  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
            return time.perf_counter() - t0

        t1 = min(eval_chain(1) for _ in range(2))
        t7 = min(eval_chain(7) for _ in range(2))
        eval2_s = max((t7 - t1) / 6, 1e-9)
        n = stats.num_rows
        return {
            "config": name,
            "metric": "streaming_examples_per_sec_cached_eval",
            "value": round(n / eval2_s),
            "unit": "examples/sec (full value+grad pass)",
            "detail": {
                "n": n,
                "dim": obj.dim,
                "nnz_per_row": k,
                "n_files": n_files,
                "eval1_s_decode": round(eval1_s, 2),
                "eval2_s_cached": round(eval2_s, 3),
                "eval_s_cached_with_readback": round(eval_rt_s, 3),
                "kernel_path": (
                    "tiled_scan" if obj._tiled_chunk_count else "scatter"
                ),
                "cache_speedup": round(eval1_s / eval2_s, 1),
                "scan_s": round(scan_s, 2),
                "examples_per_sec_decode_eval": round(n / eval1_s),
                "data_gen_s": round(gen_t, 1),
                "data": "synthetic Avro written to scratch; streamed per eval",
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _streaming_game_config(name, *, n_files=3, rows_per_file=6000,
                           n_users=400, d_g=24, d_u=8, num_iterations=2,
                           budget_bytes=2 << 20, seed=0):
    """Out-of-core GAME fit A/B (game/streaming.py): streamed coordinate
    descent over spilled chunks vs the in-memory CD on the same files.
    Emits examples_per_s + peak_rss_bytes (the budget contract made
    observable) + the objective parity — the round artifact's
    ``streaming_game`` section. Gates live in
    dev-scripts/bench_streaming_game.sh (host-class-aware: throughput
    >= 0.8x in-memory on multi-core hosts, objective parity everywhere,
    RSS delta bounded)."""
    import shutil
    import tempfile

    from photon_ml_tpu.game.config import (
        FeatureShardConfiguration,
        FixedEffectDataConfiguration,
        ProjectorType,
        RandomEffectDataConfiguration,
    )
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container
    from photon_ml_tpu.optim.config import GLMOptimizationConfiguration
    from photon_ml_tpu.task import TaskType
    from photon_ml_tpu.utils.profiling import peak_rss_bytes

    schema = {
        "name": "GameExample", "type": "record",
        "fields": [
            {"name": "uid", "type": ["null", "string"], "default": None},
            {"name": "response", "type": "double"},
            {"name": "metadataMap",
             "type": ["null", {"type": "map", "values": "string"}],
             "default": None},
            {"name": "features",
             "type": {"type": "array", "items": schemas.FEATURE_AVRO}},
            {"name": "userFeatures",
             "type": {"type": "array", "items": "FeatureAvro"}},
        ],
    }
    rng = np.random.default_rng(seed)
    tmp = tempfile.mkdtemp(prefix="photon-game-stream-bench-")
    try:
        w_g = np.linspace(-1, 1, d_g)
        w_u = np.random.default_rng(7).normal(size=(n_users, d_u)) * 0.5
        t0 = time.perf_counter()
        for fi in range(n_files):
            recs = []
            for i in range(rows_per_file):
                u = int(rng.integers(0, n_users))
                xg = rng.normal(size=d_g)
                xu = rng.normal(size=d_u)
                z = float(xg @ w_g + xu @ w_u[u])
                recs.append({
                    "uid": f"{fi}-{i}",
                    "response": float(
                        1 / (1 + np.exp(-z)) > rng.uniform()
                    ),
                    "metadataMap": {"userId": f"user{u}"},
                    "features": [
                        {"name": f"g{j}", "term": "", "value": float(xg[j])}
                        for j in range(d_g)
                    ],
                    "userFeatures": [
                        {"name": f"u{j}", "term": "", "value": float(xu[j])}
                        for j in range(d_u)
                    ],
                })
            write_container(f"{tmp}/part-{fi:03d}.avro", schema, recs)
            del recs
        gen_s = time.perf_counter() - t0

        shards = [
            FeatureShardConfiguration("globalShard", ["features"]),
            FeatureShardConfiguration("userShard", ["userFeatures"]),
        ]
        fe_data = {"global": FixedEffectDataConfiguration("globalShard")}
        re_data = {
            "per-user": RandomEffectDataConfiguration(
                "userId", "userShard",
                projector_type=ProjectorType.IDENTITY,
            )
        }
        combo = {
            "global": GLMOptimizationConfiguration.parse(
                "20,1e-6,0.5,1,TRON,L2"
            ),
            "per-user": GLMOptimizationConfiguration.parse(
                "20,1e-6,1.0,1,LBFGS,L2"
            ),
        }
        n = n_files * rows_per_file

        # -- streamed fit (FIRST: its RSS delta excludes the in-memory
        # staging below) --------------------------------------------------
        from photon_ml_tpu.game.streaming import train_streaming_game

        rss_before = peak_rss_bytes()
        t0 = time.perf_counter()
        res, extras = train_streaming_game(
            [tmp], shards, fe_data, re_data, combo,
            TaskType.LOGISTIC_REGRESSION,
            num_iterations=num_iterations,
            memory_budget_bytes=budget_bytes,
        )
        stream_s = time.perf_counter() - t0
        rss_after = peak_rss_bytes()

        # -- in-memory reference ------------------------------------------
        from photon_ml_tpu.game.coordinate import (
            FixedEffectCoordinate,
            RandomEffectCoordinate,
        )
        from photon_ml_tpu.game.coordinate_descent import CoordinateDescent
        from photon_ml_tpu.game.data import build_game_dataset_from_files
        from photon_ml_tpu.game.random_effect import (
            RandomEffectOptimizationProblem,
        )
        from photon_ml_tpu.game.random_effect_data import (
            build_random_effect_dataset,
        )
        from photon_ml_tpu.ops.losses import loss_for_task
        from photon_ml_tpu.optim.problem import create_glm_problem

        task = TaskType.LOGISTIC_REGRESSION
        t0 = time.perf_counter()
        ds = build_game_dataset_from_files([tmp], shards, ["userId"])
        red = build_random_effect_dataset(ds, re_data["per-user"])
        coords = {
            "global": FixedEffectCoordinate(
                name="global", dataset=ds,
                problem=create_glm_problem(
                    task, ds.shards["globalShard"].dim,
                    config=combo["global"].optimizer_config,
                    regularization=combo["global"].regularization,
                    intercept_index=(
                        ds.shards["globalShard"].intercept_index
                    ),
                ),
                feature_shard_id="globalShard",
                reg_weight=combo["global"].reg_weight,
            ),
            "per-user": RandomEffectCoordinate(
                name="per-user", dataset=ds, re_dataset=red,
                problem=RandomEffectOptimizationProblem(
                    loss_for_task(task),
                    combo["per-user"].optimizer_config,
                    combo["per-user"].regularization,
                    reg_weight=combo["per-user"].reg_weight,
                ),
            ),
        }
        ref = CoordinateDescent(coords, ds, task).run(num_iterations)
        mem_s = time.perf_counter() - t0

        obj_rel = abs(
            res.objective_history[-1] - ref.objective_history[-1]
        ) / abs(ref.objective_history[-1])
        ex_s = round(n * num_iterations / stream_s)
        ex_m = round(n * num_iterations / mem_s)
        return {
            "config": name,
            "metric": "streaming_game_examples_per_sec",
            "value": ex_s,
            "unit": "examples/sec (full CD pass, streamed)",
            "detail": {
                "n": n,
                "num_iterations": num_iterations,
                "num_chunks": extras["store"].count,
                "rows_per_chunk": extras["rows_per_chunk"],
                "memory_budget_bytes": budget_bytes,
                "examples_per_s": ex_s,
                "in_memory_examples_per_s": ex_m,
                "throughput_ratio": round(ex_s / max(ex_m, 1), 3),
                "stream_fit_s": round(stream_s, 2),
                "in_memory_fit_s": round(mem_s, 2),
                "peak_rss_bytes": rss_after,
                "rss_delta_bytes": rss_after - rss_before,
                "objective_rel_diff": float(obj_rel),
                "data_gen_s": round(gen_s, 1),
                "host": {"cpu_count": os.cpu_count()},
                "data": "synthetic GAME Avro written to scratch",
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _pod_game_config(name, *, n=16384, E=2048, d=32, k=8, iters=3, seed=0):
    """Pod-scale GAME A/B (game/pod.py): entity-hash-sharded RE bank
    update + two-hop routed scoring vs the replicated bucket path on the
    SAME in-memory dataset, at every available shard count.

    Emits the weak-scaling accounting the round artifact carries:
    per-device bank + optimizer-state bytes (replicated vs sharded at
    N = all visible devices), a weak-scaling table where total
    coefficients GROW with N while per-device bytes stay flat, parity
    (bank/score max-abs-diff vs the replicated update), routed-path
    readback count (must be 0 — the overlap.device_get seam), and
    update+score throughput both ways. Gates live in
    dev-scripts/bench_pod_game.sh (host-class-aware: bytes + parity +
    zero-readback everywhere; the throughput-scaling gate is chip-only —
    virtual CPU devices EMULATE collectives on one core, so sharded
    wall-clock on this container measures emulation, not ICI)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.game.config import (
        ProjectorType,
        RandomEffectDataConfiguration,
    )
    from photon_ml_tpu.game.data import EntityIndex, GameDataset, ShardData
    from photon_ml_tpu.game.pod import (
        EntityShardSpec,
        PodRandomEffectProblem,
        ShardedREBank,
        per_device_bytes,
    )
    from photon_ml_tpu.game.random_effect import (
        RandomEffectOptimizationProblem,
        score_random_effect,
    )
    from photon_ml_tpu.game.random_effect_data import (
        build_random_effect_dataset,
    )
    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.optim.config import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.parallel import overlap
    from photon_ml_tpu.parallel.mesh import entity_mesh
    from photon_ml_tpu.utils.index_map import IndexMap, feature_key

    rng = np.random.default_rng(seed)
    codes = rng.integers(0, E, size=n).astype(np.int32)
    ix = rng.integers(0, d, size=(n, k)).astype(np.int32)
    v = rng.normal(size=(n, k)).astype(np.float32)
    lab = (rng.uniform(size=n) > 0.5).astype(np.float32)
    w = np.ones(n, np.float32)
    off = (rng.normal(size=n) * 0.1).astype(np.float32)
    imap = IndexMap.build(
        (feature_key(f"f{i}", "") for i in range(d)), add_intercept=False
    )
    ds = GameDataset(
        uids=[str(i) for i in range(n)],
        labels=lab, offsets=off, weights=w,
        shards={"s": ShardData(ix, v, imap, None)},
        entity_codes={"user": codes},
        entity_indexes={
            "user": EntityIndex.build("user", [f"e{i:06d}" for i in range(E)])
        },
        num_real_rows=n,
    )
    red = build_random_effect_dataset(
        ds,
        RandomEffectDataConfiguration(
            random_effect_type="user", feature_shard_id="s",
            projector_type=ProjectorType.IDENTITY,
        ),
    )
    resid = jnp.asarray(off)

    def make_problem():
        return RandomEffectOptimizationProblem(
            LOGISTIC, OptimizerConfig(max_iter=5),
            RegularizationContext(RegularizationType.L2), reg_weight=1.0,
        )

    def run_replicated():
        problem = make_problem()
        bank = jnp.zeros((red.num_entities, red.local_dim), jnp.float32)
        bank, _, var = problem.update_bank(
            bank, red, residual_offsets=resid, with_variances=True
        )
        scores = score_random_effect(bank, red)
        jax.block_until_ready((bank, var, scores))  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
        t0 = time.perf_counter()
        for _ in range(iters):
            bank, _, var = problem.update_bank(
                bank, red, residual_offsets=resid, with_variances=True
            )
            scores = score_random_effect(bank, red)
        jax.block_until_ready((bank, var, scores))  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
        return bank, var, scores, (time.perf_counter() - t0) / iters

    ref_bank, ref_var, ref_scores, rep_s = run_replicated()
    replicated_state_bytes = int(ref_bank.nbytes) + int(ref_var.nbytes)

    n_dev = len(jax.devices())
    mesh = entity_mesh(n_dev)
    pod = PodRandomEffectProblem(make_problem(), mesh)
    view = pod.pod_view(red)
    bank = pod.init_bank(red)
    bank, _, var = pod.update_bank(
        bank, red, residual_offsets=resid, with_variances=True,
        defer_tracker=True,
    )
    scores = pod.score(bank, red)
    jax.block_until_ready((bank.data, var.data, scores))  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
    overlap.reset_readback_stats()
    t0 = time.perf_counter()
    for _ in range(iters):
        bank, _, var = pod.update_bank(
            bank, red, residual_offsets=resid, with_variances=True,
            defer_tracker=True,
        )
        scores = pod.score(bank, red)
    jax.block_until_ready((bank.data, var.data, scores))  # photon: allow(hidden-host-sync) — timing harness syncs deliberately
    pod_s = (time.perf_counter() - t0) / iters
    routed_readbacks = overlap.readback_stats()

    bank_diff, score_diff = (
        float(x) for x in overlap.device_get((
            jnp.max(jnp.abs(bank.to_global() - ref_bank)),
            jnp.max(jnp.abs(scores - ref_scores)),
        ))
    )
    sharded_state_bytes = per_device_bytes(bank, var)

    # weak scaling: total coefficients GROW with the shard count while
    # per-device bank+optimizer bytes stay ~flat (the "hundreds of
    # billions of coefficients" shape, PAPER.md, at toy scale)
    weak = []
    for ns in (1, 2, 4, 8):
        if ns > n_dev:
            continue
        spec = EntityShardSpec(ns, E * ns)
        m = entity_mesh(ns)
        b = ShardedREBank.zeros(m, spec, d)
        vb = ShardedREBank.zeros(m, spec, d)
        weak.append({
            "shards": ns,
            "entities": E * ns,
            "coefficients": E * ns * d,
            "per_device_state_bytes": per_device_bytes(b, vb),
        })

    return {
        "config": name,
        "metric": "pod_game_per_device_state_bytes",
        "value": sharded_state_bytes,
        "unit": f"bytes/device at {n_dev} entity shards (bank + variances)",
        "detail": {
            "n": n, "entities": E, "dim": d, "n_shards": n_dev,
            "replicated_state_bytes": replicated_state_bytes,
            "sharded_per_device_state_bytes": sharded_state_bytes,
            "bytes_ratio": round(
                sharded_state_bytes / max(replicated_state_bytes, 1), 4
            ),
            "per_device_data_bytes": view.per_device_data_bytes(),
            "bank_max_abs_diff": bank_diff,
            "score_max_abs_diff": score_diff,
            "routed_readbacks": routed_readbacks,
            "replicated_step_s": round(rep_s, 4),
            "sharded_step_s": round(pod_s, 4),
            "throughput_ratio": round(rep_s / max(pod_s, 1e-9), 3),
            "weak_scaling": weak,
            "host": {
                "cpu_count": os.cpu_count(),
                "devices": n_dev,
                "platform": jax.devices()[0].platform,
            },
        },
    }


def _unified_mesh_config(name, *, n=4096, E=512, d=16, k=6, iters=2,
                         seed=0):
    """Unified (grid × entity) mesh A/B (game/unified.py): the whole
    G-member λ-grid over an entity-sharded GAME model as ONE
    jitted/shard_mapped program vs the sequential-composed legacy sweep
    (G per-λ pod CD runs on the same entity mesh).

    Emits the round artifact's contract + wall accounting: per-λ
    objective/bank parity vs the sequential pod oracle, the unified
    sweep's readback count (must equal the CD iteration count — ONE
    batched readback per iteration covers every member), relowerings on
    a warmed same-shape run with DIFFERENT λ values (must be 0), the
    P(grid, entity) per-device bank bytes, and wall-clock both ways.
    Gates live in dev-scripts/bench_unified_mesh.sh (host-class-aware:
    parity + readback/lowering contracts everywhere; the >= 1.2x
    wall-clock gate at G >= 4 is multi-core/chip-only — a 1-core host
    runs every virtual device sequentially, so the one-program win is
    dispatch overhead only and the figure is recorded, not gated)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.game.config import (
        ProjectorType,
        RandomEffectDataConfiguration,
    )
    from photon_ml_tpu.game.coordinate import (
        FixedEffectCoordinate,
        PodRandomEffectCoordinate,
    )
    from photon_ml_tpu.game.coordinate_descent import CoordinateDescent
    from photon_ml_tpu.game.data import EntityIndex, GameDataset, ShardData
    from photon_ml_tpu.game.random_effect import (
        RandomEffectOptimizationProblem,
    )
    from photon_ml_tpu.game.random_effect_data import (
        build_random_effect_dataset,
    )
    from photon_ml_tpu.game.unified import run_game_grid
    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.optim.config import (
        OptimizerConfig,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.optim.problem import create_glm_problem
    from photon_ml_tpu.parallel import overlap
    from photon_ml_tpu.parallel.mesh import entity_mesh
    from photon_ml_tpu.parallel.unified_mesh import resolve_mesh
    from photon_ml_tpu.task import TaskType
    from photon_ml_tpu.utils.index_map import IndexMap, feature_key

    rng = np.random.default_rng(seed)
    codes = rng.integers(0, E, size=n).astype(np.int32)
    ix = rng.integers(0, d, size=(n, k)).astype(np.int32)
    v = rng.normal(size=(n, k)).astype(np.float32)
    lab = (rng.uniform(size=n) > 0.5).astype(np.float32)
    w = np.ones(n, np.float32)
    off = (rng.normal(size=n) * 0.1).astype(np.float32)
    imap = IndexMap.build(
        (feature_key(f"f{i}", "") for i in range(d)), add_intercept=False
    )
    ds = GameDataset(
        uids=[str(i) for i in range(n)],
        labels=lab, offsets=off, weights=w,
        shards={"s": ShardData(ix, v, imap, None)},
        entity_codes={"user": codes},
        entity_indexes={
            "user": EntityIndex.build("user", [f"e{i:06d}" for i in range(E)])
        },
        num_real_rows=n,
    )
    red = build_random_effect_dataset(
        ds,
        RandomEffectDataConfiguration(
            random_effect_type="user", feature_shard_id="s",
            projector_type=ProjectorType.IDENTITY,
        ),
    )
    task = TaskType.LOGISTIC_REGRESSION
    fe_problem = create_glm_problem(
        task, ds.shards["s"].dim, config=OptimizerConfig(max_iter=5)
    )

    def re_problem(lam=1.0):
        return RandomEffectOptimizationProblem(
            LOGISTIC, OptimizerConfig(max_iter=5),
            RegularizationContext(RegularizationType.L2), reg_weight=lam,
        )

    lambdas = [0.1, 0.5, 1.0, 2.0]
    n_dev = len(jax.devices())
    n_ent = 2 if n_dev >= 2 else 1
    plan = resolve_mesh(grid_size=len(lambdas), entity_shards=n_ent)

    def run_unified(lams, num_iterations):
        return run_game_grid(
            plan, ds, red, fe_problem, re_problem(), lams,
            feature_shard_id="s", fe_reg_weight=0.1,
            num_iterations=num_iterations,
        )

    def run_sequential(lams, num_iterations):
        out = []
        for lam in lams:
            coords = {
                "fixed": FixedEffectCoordinate(
                    name="fixed", dataset=ds, problem=fe_problem,
                    feature_shard_id="s", reg_weight=0.1,
                ),
                "per-user": PodRandomEffectCoordinate(
                    name="per-user", dataset=ds, re_dataset=red,
                    problem=re_problem(lam), mesh=entity_mesh(n_ent),
                ),
            }
            out.append(CoordinateDescent(coords, ds, task).run(
                num_iterations
            ))
        return out

    # warm both program families, then time
    run_unified(lambdas, 1)
    run_sequential(lambdas, 1)
    t0 = time.perf_counter()
    res = run_unified(lambdas, iters)
    uni_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    refs = run_sequential(lambdas, iters)
    seq_s = time.perf_counter() - t0

    bank_diff = 0.0
    obj_rel = 0.0
    for gi, ref in enumerate(refs):
        got = np.asarray(res.re_bank.member_global(gi))
        want_bank = np.asarray(ref.model.models["per-user"].bank)
        bank_diff = max(bank_diff, float(np.max(np.abs(got - want_bank))))
        got_obj = np.asarray([h[gi] for h in res.objective_history])
        want_obj = np.asarray(ref.objective_history)
        obj_rel = max(obj_rel, float(np.max(
            np.abs(got_obj - want_obj) / np.maximum(np.abs(want_obj), 1e-9)
        )))

    with overlap.overlap_scope(True):
        overlap.reset_readback_stats()
        run_unified(lambdas, iters)
        readbacks = overlap.readback_stats()

    import jax._src.test_util as jtu
    with jtu.count_jit_and_pmap_lowerings() as count:
        run_unified([0.2, 0.7, 1.5, 3.0], iters)
    relowerings = int(count[0])

    return {
        "config": name,
        "metric": "unified_mesh_speedup",
        "value": round(seq_s / max(uni_s, 1e-9), 3),
        "unit": (
            f"sequential/unified wall ratio, G={len(lambdas)} x "
            f"{n_ent} entity shards x {iters} CD iterations"
        ),
        "detail": {
            "n": n, "entities": E, "dim": d,
            "grid_size": len(lambdas),
            "entity_shards": plan.entity_shards,
            "grid_rows": plan.grid_rows,
            "cd_iterations": iters,
            "unified_wall_s": round(uni_s, 4),
            "sequential_wall_s": round(seq_s, 4),
            "speedup": round(seq_s / max(uni_s, 1e-9), 3),
            "bank_max_abs_diff": bank_diff,
            "objective_max_rel_diff": obj_rel,
            "unified_readbacks": readbacks,
            "relowerings_warm": relowerings,
            "per_device_bank_bytes": res.re_bank.per_device_bytes(),
            "host": {
                "cpu_count": os.cpu_count(),
                "devices": n_dev,
                "platform": jax.devices()[0].platform,
            },
        },
    }


def _reliability_config(name, *, n_chunks=8, rows=65536, k=16,
                        passes=10, seed=0):
    """Reliability-layer overhead A/B (round 11): the spill-read/write
    hot path (staged-chunk cache re-reads, the evaluation-2+ currency of
    every streaming objective) timed with the seams ACTIVE (inject +
    policy lookup + counters per chunk, no plan installed) vs BYPASSED
    (PHOTON_RELIABILITY_BYPASS=1 — io_call degenerates to a direct
    call). Gate (dev-scripts/chaos.sh): overhead < 2% with injection
    disabled — the layer must be free when nothing is failing."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import SparseBatch
    from photon_ml_tpu.io.streaming import _DiskChunkStore
    from photon_ml_tpu.reliability import reliability_metrics
    from photon_ml_tpu.reliability.retry import io_call

    rng = np.random.default_rng(seed)
    store = _DiskChunkStore(rows, k)
    try:
        for _ in range(n_chunks):
            store.append(SparseBatch(
                indices=jnp.asarray(
                    rng.integers(0, 1000, size=(rows, k)).astype(np.int32)
                ),
                values=jnp.asarray(
                    rng.normal(size=(rows, k)).astype(np.float32)
                ),
                labels=jnp.zeros((rows,), jnp.float32),
                offsets=jnp.zeros((rows,), jnp.float32),
                weights=jnp.ones((rows,), jnp.float32),
            ))
        store.finalize()

        def sweep():
            t0 = time.perf_counter()
            n = 0
            for b in store.chunks():
                n += int(b.indices.shape[0])
            return time.perf_counter() - t0

        sweep()  # warm page cache + compile-free path
        sweep_s = min(sweep() for _ in range(passes))
        # A whole-sweep A/B cannot resolve the seam cost here: one
        # io_call is ~5 us and a sweep is ~25 ms of memcpy whose run-to-
        # run variance on a shared 1-core host is +-10% — two orders
        # above the signal. So measure the PER-CALL seam overhead
        # directly (tight no-op loop, seams active minus bypassed) and
        # scale by the seam crossings per sweep; the fraction is derived
        # but every term is measured.
        def noop():
            return None

        M = 20_000

        def per_call_s(env):
            if env:
                os.environ["PHOTON_RELIABILITY_BYPASS"] = "1"
            else:
                os.environ.pop("PHOTON_RELIABILITY_BYPASS", None)
            try:
                best = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    for _ in range(M):
                        io_call("spill_read", noop)
                    best = min(best, (time.perf_counter() - t0) / M)
                return best
            finally:
                os.environ.pop("PHOTON_RELIABILITY_BYPASS", None)

        seam_call_s = per_call_s(False)
        bypass_call_s = per_call_s(True)
        per_call_overhead_s = max(seam_call_s - bypass_call_s, 0.0)
        calls_per_sweep = n_chunks  # one spill_read crossing per chunk
        overhead = per_call_overhead_s * calls_per_sweep / max(
            sweep_s, 1e-9
        )
        return {
            "config": name,
            "metric": "reliability_overhead_frac",
            "value": round(overhead, 5),
            "unit": "fraction of the spill-read sweep (no fault plan)",
            "detail": {
                "n_chunks": n_chunks,
                "rows_per_chunk": rows,
                "sweep_s": round(sweep_s, 4),
                "seam_call_us": round(seam_call_s * 1e6, 2),
                "bypass_call_us": round(bypass_call_s * 1e6, 2),
                "per_call_overhead_us": round(per_call_overhead_s * 1e6, 2),
                "calls_per_sweep": calls_per_sweep,
                "seam_calls": reliability_metrics()["faults"]["calls"],
            },
        }
    finally:
        store.close()


def _grid_batched_config(name, *, n=20_000, d=2_000, k=16,
                         lambdas=(100.0, 30.0, 10.0, 3.0, 1.0, 0.3, 0.1,
                                  0.03),
                         max_iter=40, seed=0):
    """Batched λ-grid A/B (ISSUE 5 / training.train_grid_batched): the
    warm-started sequential regularization path vs ONE vmapped grid
    program over the same data — wall-clock (cold incl. compile AND
    warm), jit lowerings counted per path, per-λ objective parity, and
    the readback count for the whole grid's result scalars. Gates live
    in dev-scripts/bench_grid.sh (host-class-aware: >= 1.3x warm at
    G >= 4 on multi-core/chip hosts; parity-only on a 1-core container,
    where the batched program and the sequential loop serialize onto the
    same core)."""
    import jax._src.test_util as jtu
    import jax.numpy as jnp

    from photon_ml_tpu import training
    from photon_ml_tpu.data.batch import SparseBatch
    from photon_ml_tpu.optim import problem as problem_mod
    from photon_ml_tpu.optim.config import RegularizationType
    from photon_ml_tpu.parallel import overlap
    from photon_ml_tpu.task import TaskType

    rng = np.random.default_rng(seed)
    indices = rng.integers(0, d, size=(n, k)).astype(np.int32)
    values = rng.normal(size=(n, k)).astype(np.float32)
    w_true = np.zeros(d, np.float32)
    w_true[: d // 10] = rng.normal(size=d // 10)
    z = (w_true[indices] * values).sum(axis=1)
    labels = (
        1.0 / (1.0 + np.exp(-z)) > rng.uniform(size=n)
    ).astype(np.float32)
    batch = SparseBatch(
        indices=jnp.asarray(indices),
        values=jnp.asarray(values),
        labels=jnp.asarray(labels),
        offsets=jnp.zeros((n,), jnp.float32),
        weights=jnp.ones((n,), jnp.float32),
    )
    lambdas = [float(x) for x in lambdas]
    kw = dict(
        regularization_type=RegularizationType.L2,
        regularization_weights=lambdas,
        max_iter=max_iter,
    )

    def timed(fn):
        t0 = time.perf_counter()
        models, results = fn()
        # force completion through the SAME single batched fetch the
        # driver uses — wall-clock includes the readback round(s)
        scalars = training.grid_result_scalars(results)
        return time.perf_counter() - t0, scalars

    def run_seq(ls=None):
        return training.train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, warm_start=True,
            **{**kw, "regularization_weights": ls or lambdas},
        )

    def run_bat(ls=None):
        return training.train_grid_batched(
            batch, TaskType.LOGISTIC_REGRESSION, d,
            **{**kw, "regularization_weights": ls or lambdas},
        )

    regrid = [lam * 1.5 for lam in lambdas]  # same shape, new λ values
    out = {}
    for label, fn in (("sequential", run_seq), ("batched", run_bat)):
        problem_mod._FIT_CACHE.clear()
        with jtu.count_jit_and_pmap_lowerings() as cnt:
            cold_s, scalars = timed(fn)
        lowerings = cnt[0]
        warm_s, _ = timed(fn)  # fit program cached: steady-state cost
        # the 1-compile contract, measured: a DIFFERENT grid of the same
        # shape must lower 0 new programs (λ is a traced argument)
        with jtu.count_jit_and_pmap_lowerings() as cnt2:
            fn(regrid)
        overlap.reset_readback_stats()
        _, results = fn()
        training.grid_result_scalars(results)
        out[label] = {
            "cold_s": round(cold_s, 3),
            "warm_s": round(warm_s, 3),
            "jit_lowerings_cold": int(lowerings),
            "jit_lowerings_regrid": int(cnt2[0]),
            "scalar_readback_rounds": overlap.readback_stats(),
            "objectives": {
                str(lam): scalars[lam][1] for lam in lambdas
            },
            "iterations": {
                str(lam): scalars[lam][0] for lam in lambdas
            },
        }
    parity = max(
        abs(out["batched"]["objectives"][key]
            - out["sequential"]["objectives"][key])
        / max(abs(out["sequential"]["objectives"][key]), 1e-12)
        for key in out["sequential"]["objectives"]
    )
    speedup_warm = out["sequential"]["warm_s"] / max(
        out["batched"]["warm_s"], 1e-9
    )
    speedup_cold = out["sequential"]["cold_s"] / max(
        out["batched"]["cold_s"], 1e-9
    )
    return {
        "config": name,
        "metric": "grid_batched_warm_speedup",
        "value": round(speedup_warm, 3),
        "unit": "x (sequential warm wall / batched warm wall)",
        "detail": {
            "n": n, "d": d, "nnz_per_row": k, "G": len(lambdas),
            "max_iter": max_iter,
            "sequential": out["sequential"],
            "batched": out["batched"],
            "speedup_warm": round(speedup_warm, 3),
            "speedup_cold": round(speedup_cold, 3),
            "objective_parity_rel_max": float(parity),
            "host": {"cpu_count": os.cpu_count()},
            "data": "synthetic logistic (planted sparse model)",
        },
    }


def _serving_config(name, *, seed=0):
    """Online scoring service bench (ISSUE 7 / photon_ml_tpu.serving):
    a synthetic GAME bank at config-5-class model shapes (FE 1M dims +
    600k-user RE bank on chip-attached hosts; scaled down on the CPU
    container, stated in the output) served through the real stack —
    device bank, AOT shape ladder, micro-batcher — under two loads:

    - **single-request closed loop**: one request in flight, every
      dispatch shape 1 — the latency floor (p50/p99 reported);
    - **saturating open loop**: N submitter threads, continuous
      batching coalesces to the ladder — the QPS headline.

    Both phases run with jax's lowering counter active: the request
    path must lower ZERO programs after the AOT warmup (the
    fixed-shape contract). Gates live in dev-scripts/bench_serving.sh
    (p99 bound + zero recompiles everywhere; QPS chip-attached only).
    """
    import jax
    import jax._src.test_util as jtu

    from photon_ml_tpu.parallel import overlap
    from photon_ml_tpu.serving import (
        MicroBatcher,
        ScoreRequest,
        ServingMetrics,
        ServingPrograms,
        bank_from_arrays,
    )

    on_chip = any(p.platform != "cpu" for p in jax.devices())
    if on_chip:
        d_fixed, n_users, d_user = 1 << 20, 600_000, 1000
        k_fixed, k_user = 64, 32
        n_closed, n_open, concurrency = 2_000, 20_000, 32
        shape_note = "config-5 FE/RE shapes (1M dims, 600k users x 1000)"
    else:
        d_fixed, n_users, d_user = 1 << 17, 20_000, 64
        k_fixed, k_user = 32, 16
        n_closed, n_open, concurrency = 300, 4_000, 8
        shape_note = "CPU-scaled shapes (131k dims, 20k users x 64)"

    rng = np.random.default_rng(seed)
    t0 = time.perf_counter()
    bank = bank_from_arrays(
        fixed=[(
            "global", "g",
            rng.standard_normal(d_fixed, dtype=np.float32) * 0.1,
        )],
        random=[(
            "per-user", "userId", "u",
            rng.standard_normal((n_users, d_user), dtype=np.float32) * 0.1,
            [f"user{i}" for i in range(n_users)],
        )],
        shard_widths={"g": k_fixed, "u": k_user},
    )
    stage_s = time.perf_counter() - t0
    programs = ServingPrograms()
    t0 = time.perf_counter()
    programs.ensure_compiled(bank)
    warmup_s = time.perf_counter() - t0

    def make_requests(n):
        gi = rng.integers(0, d_fixed, size=(n, k_fixed)).astype(np.int32)
        gv = rng.standard_normal((n, k_fixed), dtype=np.float32)
        ui = rng.integers(0, d_user, size=(n, k_user)).astype(np.int32)
        uv = rng.standard_normal((n, k_user), dtype=np.float32)
        users = rng.integers(0, n_users, size=n)
        # raw ids, like production traffic: the dispatch loop pays the
        # per-batch id->row resolve, so the measured latency includes it
        return [
            ScoreRequest(
                uid=str(i),
                indices={"g": gi[i], "u": ui[i]},
                values={"g": gv[i], "u": uv[i]},
                entity_ids={"userId": f"user{int(users[i])}"},
            )
            for i in range(n)
        ]

    compiles_before = programs.stats()["compile_count"]
    out = {}
    with jtu.count_jit_and_pmap_lowerings() as lowerings:
        # -- closed loop: the single-request latency floor ------------------
        closed_metrics = ServingMetrics()
        reqs = make_requests(n_closed)
        overlap.reset_readback_stats()
        with MicroBatcher(
            lambda: bank, programs, closed_metrics
        ) as batcher:
            for r in reqs:
                batcher.score(r)
        snap = closed_metrics.snapshot()
        out["closed"] = {
            "requests": snap["requests"],
            "p50_ms": snap["latency_p50_ms"],
            "p99_ms": snap["latency_p99_ms"],
            "mean_ms": snap["latency_mean_ms"],
            "qps": snap["qps"],
            "dispatches": snap["dispatches"],
            "readbacks": overlap.readback_stats(),
        }

        # -- open loop: saturating concurrent submitters --------------------
        import threading

        open_metrics = ServingMetrics()
        reqs = make_requests(n_open)
        it = iter(reqs)
        lock = threading.Lock()
        overlap.reset_readback_stats()

        def worker():
            while True:
                with lock:
                    r = next(it, None)
                if r is None:
                    return
                batcher.score(r)

        with MicroBatcher(lambda: bank, programs, open_metrics) as batcher:
            threads = [
                threading.Thread(target=worker)
                for _ in range(concurrency)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            open_wall_s = time.perf_counter() - t0
        snap = open_metrics.snapshot()
        out["open"] = {
            "requests": snap["requests"],
            "concurrency": concurrency,
            "qps": round(n_open / open_wall_s, 1),
            "p50_ms": snap["latency_p50_ms"],
            "p99_ms": snap["latency_p99_ms"],
            "dispatches": snap["dispatches"],
            "readbacks": overlap.readback_stats(),
            "batch_occupancy_mean": snap["batch_occupancy_mean"],
            "pad_waste_frac": snap["pad_waste_frac"],
            "shape_counts": snap["shape_counts"],
        }

    stats = programs.stats()
    return {
        "config": name,
        "metric": "serving_p99_ms_single_request",
        "value": out["closed"]["p99_ms"],
        "unit": "ms (closed-loop p99; open-loop QPS in detail)",
        "detail": {
            "device": str(jax.devices()[0]),
            "host": {"cpu_count": os.cpu_count(), "on_chip": on_chip},
            "shape_note": shape_note,
            "model": {
                "d_fixed": d_fixed, "n_users": n_users, "d_user": d_user,
                "k_fixed": k_fixed, "k_user": k_user,
                "bank_bytes": bank.device_bytes(),
            },
            "ladder": list(programs.ladder),
            "stage_s": round(stage_s, 3),
            "aot_warmup_s": round(warmup_s, 3),
            "aot_programs": stats["compiled_programs"],
            "closed": out["closed"],
            "open": out["open"],
            # the fixed-shape contract, measured over BOTH phases
            "request_path_lowerings": int(lowerings[0]),
            "recompiles_after_warmup": (
                stats["compile_count"] - compiles_before
            ),
            "cold_dispatch_compiles": stats["cold_dispatch_compiles"],
            "data": "synthetic bank + synthetic request trace",
        },
    }


def _overload_config(name, *, seed=0):
    """Serving-under-fire bench (ISSUE 8): an open-loop flood PAST
    capacity through the admission-controlled micro-batcher.

    Unlike ``10_serving``'s closed-loop submitters (which self-pace to
    the service rate), this section fires ``n_flood`` requests with a
    tight ``deadline_ms`` from ``flood_threads`` threads as fast as
    they can — deliberately more offered load than the device can
    absorb. The service's job is NOT to finish them all; it is to

    - give EVERY submitted request exactly one terminal outcome
      (scored, SHED, DEADLINE_EXCEEDED) — counted here, gated by
      ``dev-scripts/bench_overload.sh``;
    - keep the ADMITTED requests' p99 bounded (shedding is what buys
      this: an unbounded queue converts overload into unbounded p99);
    - lower ZERO programs on the request path while overloaded;
    - then drain a parting burst inside ``drain_timeout_s`` with no
      hung futures (the SIGTERM protocol, timed).
    """
    import threading

    import jax
    import jax._src.test_util as jtu

    from photon_ml_tpu.serving import (
        DeadlineExceeded,
        MicroBatcher,
        RequestShed,
        ScoreRequest,
        ServingError,
        ServingMetrics,
        ServingPrograms,
        bank_from_arrays,
    )

    on_chip = any(p.platform != "cpu" for p in jax.devices())
    if on_chip:
        d_fixed, n_users, d_user = 1 << 20, 600_000, 1000
        k_fixed, k_user = 64, 32
        n_flood, flood_threads = 20_000, 64
        deadline_ms, max_queue = 5.0, 8192
        shape_note = "config-5 FE/RE shapes (1M dims, 600k users x 1000)"
    else:
        d_fixed, n_users, d_user = 1 << 15, 2_000, 32
        k_fixed, k_user = 16, 8
        n_flood, flood_threads = 3_000, 16
        deadline_ms, max_queue = 25.0, 2048
        shape_note = "CPU-scaled shapes (32k dims, 2k users x 32)"
    drain_timeout_s = float(
        os.environ.get("PHOTON_OVERLOAD_DRAIN_TIMEOUT_S", "5")
    )
    drain_burst = 256

    rng = np.random.default_rng(seed)
    bank = bank_from_arrays(
        fixed=[(
            "global", "g",
            rng.standard_normal(d_fixed, dtype=np.float32) * 0.1,
        )],
        random=[(
            "per-user", "userId", "u",
            rng.standard_normal((n_users, d_user), dtype=np.float32) * 0.1,
            [f"user{i}" for i in range(n_users)],
        )],
        shard_widths={"g": k_fixed, "u": k_user},
    )
    programs = ServingPrograms()
    programs.ensure_compiled(bank)

    def make_requests(n, deadline):
        gi = rng.integers(0, d_fixed, size=(n, k_fixed)).astype(np.int32)
        gv = rng.standard_normal((n, k_fixed), dtype=np.float32)
        ui = rng.integers(0, d_user, size=(n, k_user)).astype(np.int32)
        uv = rng.standard_normal((n, k_user), dtype=np.float32)
        users = rng.integers(0, n_users, size=n)
        return [
            ScoreRequest(
                uid=str(i),
                indices={"g": gi[i], "u": ui[i]},
                values={"g": gv[i], "u": uv[i]},
                entity_ids={"userId": f"user{int(users[i])}"},
                deadline_ms=deadline,
            )
            for i in range(n)
        ]

    metrics = ServingMetrics()
    compiles_before = programs.stats()["compile_count"]
    outcomes = {}
    out_lock = threading.Lock()

    def note(outcome):
        with out_lock:
            outcomes[outcome] = outcomes.get(outcome, 0) + 1

    with jtu.count_jit_and_pmap_lowerings() as lowerings:
        batcher = MicroBatcher(
            lambda: bank, programs, metrics, max_queue=max_queue
        )
        reqs = make_requests(n_flood, deadline_ms)
        it = iter(reqs)
        it_lock = threading.Lock()
        futures = []
        fut_lock = threading.Lock()

        def flood():
            # TRUE open loop: submit as fast as admission allows, never
            # wait for results — offered load exceeds capacity by
            # construction
            while True:
                with it_lock:
                    r = next(it, None)
                if r is None:
                    return
                try:
                    fut = batcher.submit(r)
                except RequestShed:
                    note("shed")
                    continue
                except ServingError as e:
                    note(f"error:{e.code}")
                    continue
                with fut_lock:
                    futures.append(fut)

        threads = [
            threading.Thread(target=flood) for _ in range(flood_threads)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        flood_submit_s = time.perf_counter() - t0
        for fut in futures:
            try:
                fut.result(timeout=60.0)
                note("ok")
            except DeadlineExceeded:
                note("deadline_exceeded")
            except ServingError as e:
                note(f"error:{e.code}")
        flood_wall_s = time.perf_counter() - t0

        # -- drain phase: a parting burst, then the bounded SIGTERM
        # drain — zero hung futures inside the budget ------------------
        burst = make_requests(drain_burst, None)
        burst_futs = []
        burst_refused = 0
        for r in burst:
            try:
                burst_futs.append(batcher.submit(r))
            except ServingError:
                burst_refused += 1
        report = batcher.drain(drain_timeout_s)
        burst_terminal = sum(1 for f in burst_futs if f.done())

    snap = metrics.snapshot()
    stats = programs.stats()
    terminal = sum(outcomes.values())
    refused = outcomes.get("shed", 0) + outcomes.get("deadline_exceeded", 0)
    shed_rate = round(refused / n_flood, 6)
    return {
        "config": name,
        "metric": "overload_shed_rate",
        "value": shed_rate,
        "unit": "refused/submitted under 0-pacing flood (details gated)",
        "detail": {
            "device": str(jax.devices()[0]),
            "host": {"cpu_count": os.cpu_count(), "on_chip": on_chip},
            "shape_note": shape_note,
            "deadline_ms": deadline_ms,
            "max_queue": max_queue,
            "flood": {
                "submitted": n_flood,
                "threads": flood_threads,
                "submit_wall_s": round(flood_submit_s, 3),
                "wall_s": round(flood_wall_s, 3),
                "outcomes": dict(sorted(outcomes.items())),
                "terminal": terminal,
                "ok": outcomes.get("ok", 0),
                "refused": refused,
                "shed_rate": shed_rate,
                "sheds_by_reason": snap["sheds"],
                "deadline_expired_at_dispatch": snap["deadline_expired"],
                "admitted_p50_ms": snap.get("latency_p50_ms"),
                "admitted_p99_ms": snap.get("latency_p99_ms"),
                "dispatches": snap["dispatches"],
                "batch_occupancy_mean": snap["batch_occupancy_mean"],
            },
            "drain": {
                **report.to_dict(),
                "burst": drain_burst,
                "burst_admitted": len(burst_futs),
                "burst_refused": burst_refused,
                "burst_terminal": burst_terminal,
                "budget_s": drain_timeout_s,
            },
            "request_path_lowerings": int(lowerings[0]),
            "recompiles_after_warmup": (
                stats["compile_count"] - compiles_before
            ),
            "cold_dispatch_compiles": stats["cold_dispatch_compiles"],
            "data": "synthetic bank + synthetic open-loop flood",
        },
    }


SHARD_CHILD_FLAG = "--shard-routing-child"


def _shard_routing_shapes():
    import jax

    on_chip = any(p.platform != "cpu" for p in jax.devices())
    if on_chip:
        return {
            "on_chip": True,
            "E": 200_000, "d_g": 1 << 18, "d_u": 256,
            "k_g": 32, "k_u": 16,
            "n_flood": 8_000, "threads": 32, "n_kill": 2_000,
            "zipf_a": 1.3, "payload_pool": 4,
            "note": "chip-class shapes (256k dims, 200k users x 256)",
        }
    return {
        "on_chip": False,
        "E": 2_000, "d_g": 1 << 14, "d_u": 32,
        "k_g": 16, "k_u": 8,
        "n_flood": int(os.environ.get("PHOTON_ROUTING_FLOOD", "1200")),
        "threads": 8, "n_kill": 400,
        "zipf_a": 1.3, "payload_pool": 4,
        "note": "CPU-scaled shapes (16k dims, 2k users x 32)",
    }


def _shard_routing_ids(E):
    return [f"user{i:06d}" for i in range(E)]


def _shard_routing_arrays(seed, shapes):
    rng = np.random.default_rng(seed)
    fe = rng.standard_normal(shapes["d_g"]).astype(np.float32) * 0.1
    re = (
        rng.standard_normal((shapes["E"], shapes["d_u"]))
        .astype(np.float32) * 0.1
    )
    return fe, re


def _shard_routing_shard_configs():
    from photon_ml_tpu.game.config import FeatureShardConfiguration

    return [
        FeatureShardConfiguration("g", ["features"]),
        FeatureShardConfiguration("u", ["userFeatures"]),
    ]


def _shard_routing_child(cfg_text):
    """One shard-server subprocess for the 14_shard_routing fleet:
    builds its 1/N slice of the SAME deterministic synthetic bank the
    parent knows (seed -> arrays, no artifact on disk), serves the
    routing control plane (topology + two-step swap via a synthetic
    stager keyed by seed), publishes its port, and on SIGTERM drains
    and writes its program-cache stats — the parent gates 0 request-
    path lowerings per shard on exactly that file."""
    import signal
    import threading

    from photon_ml_tpu.reliability import atomic_write_json
    from photon_ml_tpu.serving import (
        ServingModel,
        ServingPrograms,
        ShardServer,
        bank_from_arrays,
    )
    from photon_ml_tpu.utils.index_map import IndexMap

    cfg = json.loads(cfg_text)
    shapes = cfg["shapes"]
    s, n = int(cfg["shard"]), int(cfg["count"])
    ids = _shard_routing_ids(shapes["E"])
    imaps = {
        "g": IndexMap({f"g{j}\t": j for j in range(shapes["d_g"])}),
        "u": IndexMap({f"u{j}\t": j for j in range(shapes["d_u"])}),
    }
    widths = {"g": shapes["k_g"], "u": shapes["k_u"]}

    def build(seed):
        fe, re = _shard_routing_arrays(seed, shapes)
        return bank_from_arrays(
            fixed=[("global", "g", fe)],
            random=[("per-user", "userId", "u", re, ids)],
            shard_widths=widths,
            index_maps=imaps,
            entity_shard=(s, n),
        )

    sm = ServingModel(
        build(cfg["seed"]),
        ServingPrograms(tuple(cfg.get("ladder", (1, 8, 64)))),
        partial=True,
        entity_shard=(s, n),
    )

    def stager(obj):
        return sm.prepare_swap_bank(build(int(obj["model_dir"])))

    srv = ShardServer(
        sm,
        _shard_routing_shard_configs(),
        (s, n),
        stager=stager,
        has_response=False,
    ).start()
    out = cfg["out"]
    os.makedirs(out, exist_ok=True)
    atomic_write_json(
        os.path.join(out, "frontend.json"),
        {"port": srv.port, "pid": os.getpid(), "shard": s, "count": n},  # photon: entropy(discovery artifact; pid names the live shard process)
    )
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_a: stop.set())
    while not stop.wait(timeout=0.2):
        pass
    report = srv.close(drain_timeout_s=5.0)
    atomic_write_json(
        os.path.join(out, "metrics.json"),
        {
            "programs": sm.programs.stats(),
            "serving": srv.metrics.snapshot(),
            "drain": report.to_dict(),
        },
    )


def _shard_routing_config(name, *, seed=0):
    """Planet-scale serving bench (ISSUE 12): aggregate QPS vs shard
    count through the scatter/gather router over REAL shard-server
    subprocesses, under a zipf (head-skewed) open-loop replay.

    Per fleet size N in {1, 2, 4}: spawn N shard-server processes
    (each holding 1/N of the RE bank, partial-score mode), connect the
    router, flood it from ``threads`` submitter threads over a zipf
    entity draw whose payloads repeat (the hot-entity cache's food),
    and record aggregate QPS, fan-out p50/p99, cache hit rate and
    outcome conservation. At N=4 a second, smaller flood runs with one
    shard SIGKILLed mid-fleet: its entities must degrade FE-only
    (named, counted) — never a failed run. Children then SIGTERM-drain
    and report their program caches: the parent records 0 request-path
    lowerings per shard. Gates in dev-scripts/bench_shard_routing.sh
    (scaling gate multi-core/chip only — on a 1-core container N
    processes share one core and the ratio is recorded, not gated).
    """
    import signal
    import subprocess
    import tempfile
    import threading

    from photon_ml_tpu.serving import (
        RoutingPolicy,
        ShardRouter,
        ServingError,
    )

    shapes = _shard_routing_shapes()
    ids = _shard_routing_ids(shapes["E"])
    rng = np.random.default_rng(seed)
    # zipf head draw + a small payload pool per entity: head entities
    # repeat identical (entity, features) pairs — deterministic score
    # paths the cache may legally absorb
    zipf = rng.zipf(shapes["zipf_a"], size=shapes["n_flood"] * 2)
    entity_draw = (zipf - 1) % shapes["E"]
    pool = {}

    def record_for(i, j, variant=0):
        # ``variant`` switches to a disjoint payload universe: the kill
        # leg uses variant=1 so its records MISS the cache by
        # construction and the dead shard's entities must hit the wire
        key = (int(i), int(j) % shapes["payload_pool"], int(variant))
        rec = pool.get(key)
        if rec is None:
            import zlib

            # crc32, not hash(): flood payloads must be identical
            # across the parent and the relaunched child processes
            # (PYTHONHASHSEED differs), or cache-hit accounting drifts
            seed = zlib.crc32(
                f"{key[0]}:{key[1]}:{key[2]}".encode("utf-8")
            )
            prng = np.random.default_rng(seed & 0x7FFFFFFF)
            rec = {
                "uid": f"q{key[0]}-{key[1]}-{key[2]}",
                "metadataMap": {"userId": ids[key[0]]},
                "features": [
                    {"name": f"g{int(g)}", "term": "",
                     "value": float(prng.standard_normal())}
                    for g in prng.integers(
                        0, shapes["d_g"], size=shapes["k_g"] // 2
                    )
                ],
                "userFeatures": [
                    {"name": f"u{int(u)}", "term": "",
                     "value": float(prng.standard_normal())}
                    for u in prng.integers(
                        0, shapes["d_u"], size=shapes["k_u"] // 2
                    )
                ],
                "offset": 0.0,
            }
            pool[key] = rec
        return rec

    base = tempfile.mkdtemp(prefix="photon-shard-routing-")
    child_env = dict(os.environ)
    if not shapes["on_chip"]:
        child_env["JAX_PLATFORMS"] = "cpu"

    def spawn_fleet(n_shards):
        procs = []
        for s in range(n_shards):
            out = os.path.join(base, f"n{n_shards}-shard{s}")
            cfg = json.dumps({
                "shard": s, "count": n_shards, "seed": seed,
                "shapes": shapes, "out": out, "ladder": [1, 8, 64],
            })
            procs.append((out, subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 SHARD_CHILD_FLAG, cfg],
                env=child_env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT,
            )))
        ports = []
        for out, p in procs:
            fj = os.path.join(out, "frontend.json")
            deadline = time.perf_counter() + 180
            while not os.path.exists(fj):
                if p.poll() is not None:
                    raise RuntimeError(
                        f"shard child died during boot ({out})"
                    )
                if time.perf_counter() > deadline:
                    raise RuntimeError("shard child boot timeout")
                time.sleep(0.2)
            ports.append(json.load(open(fj))["port"])
        return procs, ports

    def flood(router, n_requests, offset, threads, variant=0):
        it = iter(range(n_requests))
        it_lock = threading.Lock()
        counts = {}
        c_lock = threading.Lock()

        def note(key):
            with c_lock:
                counts[key] = counts.get(key, 0) + 1

        def worker():
            while True:
                with it_lock:
                    i = next(it, None)
                if i is None:
                    return
                rec = record_for(
                    entity_draw[offset + i],
                    entity_draw[offset + i] + i,
                    variant,
                )
                try:
                    out = router.score_record(rec)
                    note("degraded" if out.degraded else "ok")
                except ServingError as e:
                    note(f"error:{e.code}")

        ts = [threading.Thread(target=worker) for _ in range(threads)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return counts, time.perf_counter() - t0

    fleets = {}
    kill_leg = None
    for n_shards in (1, 2, 4):
        procs, ports = spawn_fleet(n_shards)
        router = ShardRouter(
            [("127.0.0.1", pt) for pt in ports],
            entity_ids={"userId": ids},
            shard_configs=_shard_routing_shard_configs(),
            policy=RoutingPolicy(subrequest_timeout_s=5.0),
            cache_entries=int(os.environ.get(
                "PHOTON_ROUTING_CACHE_ENTRIES", "8192"
            )),
        )
        try:
            router.connect()
            # tiny warmup so the flood never measures ladder selection
            flood(router, 16, 0, 4)
            counts, wall = flood(
                router, shapes["n_flood"], 16, shapes["threads"]
            )
            snap = router.metrics.snapshot()
            cache = router.cache.snapshot()
            terminal = sum(counts.values())
            fleets[str(n_shards)] = {
                "outcomes": dict(sorted(counts.items())),
                "terminal": terminal,
                "submitted": shapes["n_flood"],
                "wall_s": round(wall, 3),
                "qps": round(terminal / wall, 1) if wall > 0 else None,
                "fanout_p50_ms": snap.get("latency_p50_ms"),
                "fanout_p99_ms": snap.get("latency_p99_ms"),
                "fanout_mean": snap["fanout_mean"],
                "subrequests": snap["subrequests"],
                "hedges": snap["hedges"],
                "cache": cache,
                "cache_hit_rate": round(
                    cache["hits"] / max(cache["hits"] + cache["misses"], 1),
                    4,
                ),
            }
            if n_shards == 4:
                # the kill leg: SIGKILL one shard mid-fleet, flood
                # again — its entities degrade (FE-only, named), the
                # run never fails
                procs[3][1].send_signal(signal.SIGKILL)
                procs[3][1].wait(timeout=30)
                counts, wall = flood(
                    router, shapes["n_kill"], shapes["n_flood"] // 2,
                    shapes["threads"], variant=1,
                )
                kill_leg = {
                    "killed_shard": 3,
                    "outcomes": dict(sorted(counts.items())),
                    "terminal": sum(counts.values()),
                    "submitted": shapes["n_kill"],
                    "wall_s": round(wall, 3),
                    "degraded": counts.get("degraded", 0),
                    "errors": sum(
                        v for k, v in counts.items()
                        if k.startswith("error")
                    ),
                    "health": [h.snapshot() for h in router.health],
                }
        finally:
            router.close()
            shard_stats = []
            for out, p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for out, p in procs:
                if p.poll() is None:
                    try:
                        p.wait(timeout=60)
                    except subprocess.TimeoutExpired:
                        p.kill()
                mp = os.path.join(out, "metrics.json")
                if os.path.exists(mp):
                    m = json.load(open(mp))
                    shard_stats.append({
                        "shard": os.path.basename(out),
                        "cold_dispatch_compiles": (
                            m["programs"]["cold_dispatch_compiles"]
                        ),
                        "compiled_programs": (
                            m["programs"]["compiled_programs"]
                        ),
                        "dispatches": m["serving"]["dispatches"],
                    })
            fleets.setdefault(str(n_shards), {})["shards"] = shard_stats

    q1 = fleets["1"]["qps"] or 1.0
    q4 = fleets["4"]["qps"] or 0.0
    scaling = round(q4 / q1, 3)
    return {
        "config": name,
        "metric": "routing_qps_scaling_4x_over_1x",
        "value": scaling,
        "unit": "aggregate QPS ratio N=4 / N=1 (details gated)",
        "detail": {
            "host": {
                "cpu_count": os.cpu_count(),
                "on_chip": shapes["on_chip"],
            },
            "shape_note": shapes["note"],
            "zipf_a": shapes["zipf_a"],
            "fleets": fleets,
            "kill_leg": kill_leg,
            "scaling_4_over_1": scaling,
            "scaling_2_over_1": round(
                (fleets["2"]["qps"] or 0.0) / q1, 3
            ),
            "data": (
                "synthetic sharded banks (subprocess fleet) + zipf "
                "open-loop replay through the router"
            ),
        },
    }


def _obs_config(name, *, seed=0):
    """Unified-telemetry overhead A/B (ISSUE 13): the SAME closed-loop
    request stream through the real micro-batcher with the obs plane
    OFF (tracing disabled, no registry views — the shipped default)
    vs ON (span tracing + live metrics registry views + flight
    recorder), alternating passes, median-of-passes per arm.

    The contract being priced: tracing must stay affordable enough to
    leave on in production. Gates in dev-scripts/bench_obs.sh:
    <2% request-path overhead on this host class (multi-core/chip; the
    1-core container number is recorded honestly), 0 request-path
    lowerings in BOTH arms, readbacks == dispatches unchanged, and
    trace COMPLETENESS — every dispatch of the traced arm produced a
    serving.dispatch span, every request a serving.score span."""
    import jax
    import jax._src.test_util as jtu

    from photon_ml_tpu.obs.flight_recorder import reset_flight_recorder
    from photon_ml_tpu.obs.registry import MetricsRegistry
    from photon_ml_tpu.obs.trace import tracer, tracing_scope
    from photon_ml_tpu.parallel import overlap
    from photon_ml_tpu.serving import (
        MicroBatcher,
        ScoreRequest,
        ServingMetrics,
        ServingPrograms,
        bank_from_arrays,
    )

    on_chip = any(p.platform != "cpu" for p in jax.devices())
    if on_chip:
        d_fixed, n_users, d_user = 1 << 18, 100_000, 128
        k_fixed, k_user = 32, 16
        n_req, passes = 2_000, 3
    else:
        d_fixed, n_users, d_user = 1 << 15, 5_000, 32
        k_fixed, k_user = 16, 8
        n_req, passes = 400, 5

    rng = np.random.default_rng(seed)
    bank = bank_from_arrays(
        fixed=[(
            "global", "g",
            rng.standard_normal(d_fixed, dtype=np.float32) * 0.1,
        )],
        random=[(
            "per-user", "userId", "u",
            rng.standard_normal((n_users, d_user), dtype=np.float32) * 0.1,
            [f"user{i}" for i in range(n_users)],
        )],
        shard_widths={"g": k_fixed, "u": k_user},
    )
    programs = ServingPrograms()
    programs.ensure_compiled(bank)

    def make_requests(trace_ids: bool):
        gi = rng.integers(0, d_fixed, size=(n_req, k_fixed)).astype(np.int32)
        gv = rng.standard_normal((n_req, k_fixed), dtype=np.float32)
        ui = rng.integers(0, d_user, size=(n_req, k_user)).astype(np.int32)
        uv = rng.standard_normal((n_req, k_user), dtype=np.float32)
        users = rng.integers(0, n_users, size=n_req)
        return [
            ScoreRequest(
                uid=str(i),
                indices={"g": gi[i], "u": ui[i]},
                values={"g": gv[i], "u": uv[i]},
                entity_ids={"userId": f"user{int(users[i])}"},
                # the traced arm carries wire context like frontend
                # traffic does, so the per-request span path is priced
                trace_id=f"t-{i}" if trace_ids else None,
                parent_span=f"s-{i}" if trace_ids else None,
            )
            for i in range(n_req)
        ]

    def one_pass(obs_on: bool) -> float:
        reqs = make_requests(trace_ids=obs_on)
        metrics = ServingMetrics()
        registry = None
        if obs_on:
            registry = MetricsRegistry()
            registry.register_view("serving", metrics.snapshot)
        with tracing_scope(obs_on):
            with MicroBatcher(lambda: bank, programs, metrics) as mb:
                t0 = time.perf_counter()
                for r in reqs:
                    mb.score(r)
                wall = time.perf_counter() - t0
            if obs_on:
                registry.snapshot()  # one live scrape per pass
        return wall, metrics.snapshot()

    # The deterministic micro (see below) is measured BOTH here — on
    # the warm but still-clean heap — and again after the A/B: the
    # min is the operation's cost, the spread is allocator state.
    def span_record_micro(n_micro=20_000, reps=3) -> float:
        import gc

        from photon_ml_tpu.obs.trace import record_span as _rs

        gc.collect()
        best = float("inf")
        for _ in range(reps):
            with tracing_scope(True):
                t0 = time.perf_counter()
                for _i in range(n_micro):
                    _rs(
                        "serving.dispatch", 0.0, 1.0, shape=8,
                        occupancy=8, generation=1, partial=False,
                        traces=[("t", "s", False)] * 8,
                    )
                best = min(
                    best, (time.perf_counter() - t0) / n_micro * 1e6
                )
            tracer().clear()
        return best

    # warmup (both paths touched once, excluded from the medians)
    one_pass(False)
    one_pass(True)
    span_record_us = span_record_micro()

    walls = {False: [], True: []}
    snaps = {False: None, True: None}
    reset_flight_recorder()
    tracer().clear()
    overlap.reset_readback_stats()
    readbacks_before = overlap.readback_stats()
    with jtu.count_jit_and_pmap_lowerings() as lowerings:
        for _ in range(passes):
            for arm in (False, True):  # alternating, same stream shape
                wall, snap = one_pass(arm)
                walls[arm].append(wall)
                snaps[arm] = snap
    readbacks = overlap.readback_stats() - readbacks_before

    # trace completeness over the traced passes (expansion happens
    # HERE, off the request path — the hot loop recorded one span per
    # dispatch carrying its traced-request contexts)
    from photon_ml_tpu.obs.flight_recorder import flight_recorder
    from photon_ml_tpu.obs.trace import expand_spans

    spans = expand_spans(tracer().snapshot())
    dispatch_spans = [s for s in spans if s.name == "serving.dispatch"]
    score_spans = [s for s in spans if s.name == "serving.score"]
    conservation = flight_recorder().check_conservation()

    # Paired estimator: the container's absolute speed drifts far more
    # across the run than the effect under test, so each off-pass is
    # compared only to the on-pass that ran right after it (alternating
    # arms above) and the MEDIAN pairwise ratio is the overhead.
    ratios = sorted(
        on / off for off, on in zip(walls[False], walls[True])
    )
    overhead = ratios[len(ratios) // 2] - 1.0
    off_s = float(min(walls[False]))
    on_s = float(min(walls[True]))

    # Deterministic twin of the A/B: the obs plane's ENTIRE
    # request-path addition is one record_span per dispatch (+ one
    # tuple per traced request); measure that call in isolation and
    # divide by the measured per-request wall. On hosts whose
    # scheduling noise exceeds the effect (this 1-core container
    # swings +-20% pass to pass), bench_obs.sh gates THIS number —
    # the A/B stays recorded honestly either way.
    span_record_us = min(span_record_us, span_record_micro())
    per_request_us = off_s / n_req * 1e6
    implied_overhead = span_record_us / per_request_us
    traced_dispatches = passes * snaps[True]["dispatches"]
    return {
        "config": name,
        "metric": "obs_request_path_overhead_frac",
        "value": round(overhead, 5),
        "unit": "frac (tracing+metrics on vs off, closed loop)",
        "detail": {
            "device": str(jax.devices()[0]),
            "host": {"cpu_count": os.cpu_count(), "on_chip": on_chip},
            "requests_per_pass": n_req,
            "passes_per_arm": passes,
            "off_wall_s": [round(w, 4) for w in walls[False]],
            "on_wall_s": [round(w, 4) for w in walls[True]],
            "pairwise_ratios": [round(r, 4) for r in ratios],
            "off_qps": round(n_req / off_s, 1),
            "on_qps": round(n_req / on_s, 1),
            "span_record_us_per_dispatch": round(span_record_us, 3),
            "per_request_us": round(per_request_us, 2),
            "implied_overhead_frac": round(implied_overhead, 5),
            "request_path_lowerings": int(lowerings[0]),
            "readbacks": readbacks,
            "dispatches": (
                passes * (
                    snaps[False]["dispatches"] + snaps[True]["dispatches"]
                )
            ),
            "traced_dispatches": traced_dispatches,
            "dispatch_spans": len(dispatch_spans),
            "score_spans": len(score_spans),
            "traced_requests": passes * n_req,
            "conservation": conservation,
            "data": "synthetic bank + synthetic closed-loop trace",
        },
    }


def _fleet_obs_config(name, *, seed=0):
    """Fleet-observability overhead A/B (ISSUE 15): the SAME closed-loop
    routed request stream through a REAL 2-shard TCP fleet with the
    fleet-obs plane OFF (tracing disabled, no collector — the shipped
    default) vs ON (span tracing + the live FleetCollector draining
    every member's ring over fresh connections + router conservation
    attribution), alternating passes.

    The contract being priced: the collector must stay affordable
    enough to leave on against a production fleet. Gates in
    dev-scripts/bench_fleet_obs.sh: <2% request-path overhead on
    multi-core/chip hosts (the 1-core container number is recorded
    honestly under a noise ceiling), 0 request-path lowerings in BOTH
    arms, fleet conservation balanced (router admitted == Σ
    shard-attributed + router-local over per-member books), and merge
    COMPLETENESS — every traced request's router.request root reached
    the collector and the stitched fleet trace verifies."""
    import jax
    import jax._src.test_util as jtu

    from photon_ml_tpu.game.config import FeatureShardConfiguration
    from photon_ml_tpu.obs.fleet import (
        FleetCollector,
        fleet_check_conservation,
        verify_fleet_trace,
    )
    from photon_ml_tpu.obs.flight_recorder import FlightRecorder
    from photon_ml_tpu.obs.trace import start_span, tracer, tracing_scope
    from photon_ml_tpu.serving import (
        RoutingPolicy,
        ServingModel,
        ServingPrograms,
        ShardRouter,
        ShardServer,
        bank_from_arrays,
    )
    from photon_ml_tpu.utils.index_map import IndexMap

    on_chip = any(p.platform != "cpu" for p in jax.devices())
    if on_chip:
        E, d_g, d_u = 4096, 1 << 14, 64
        n_req, passes = 1_000, 3
    else:
        E, d_g, d_u = 128, 256, 16
        n_req, passes = 300, 5
    k = 8
    rng = np.random.default_rng(seed)
    ids = sorted(f"user{i:06d}" for i in range(E))
    fe_w = rng.standard_normal(d_g).astype(np.float32)
    re_w = rng.standard_normal((E, d_u)).astype(np.float32)
    imaps = {
        "g": IndexMap({f"g{j}\t": j for j in range(d_g)}),
        "u": IndexMap({f"u{j}\t": j for j in range(d_u)}),
    }
    shard_cfgs = [
        FeatureShardConfiguration("g", ["features"]),
        FeatureShardConfiguration("u", ["userFeatures"]),
    ]
    shard_books = [FlightRecorder(1 << 14) for _ in range(2)]
    servers = []
    for s in range(2):
        bank = bank_from_arrays(
            fixed=[("global", "g", fe_w)],
            random=[("per-user", "userId", "u", re_w, ids)],
            shard_widths={"g": k, "u": k},
            index_maps=imaps,
            entity_shard=(s, 2),
        )
        sm = ServingModel(
            bank, ServingPrograms((1, 8)), partial=True,
            entity_shard=(s, 2),
        )
        servers.append(ShardServer(
            sm, shard_cfgs, (s, 2), has_response=False,
            recorder=shard_books[s],
        ).start())
    router_book = FlightRecorder(1 << 14)
    router = ShardRouter(
        [("127.0.0.1", srv.port) for srv in servers],
        entity_ids={"userId": ids},
        shard_configs=shard_cfgs,
        policy=RoutingPolicy(subrequest_timeout_s=10.0),
        cache_entries=0,  # price the WIRE path, not cache replay
        recorder=router_book,
    )
    router.connect()
    # one remote member is the whole in-process fleet's tracer (every
    # span reaches the collector exactly once, over real TCP), so the
    # poll path carries the full span stream
    collector = FleetCollector(
        [("fleet", "127.0.0.1", servers[0].port)],
        poll_s=0.05,
    )

    def make_records(n):
        out = []
        gj = rng.integers(0, d_g, size=(n, 3))
        uj = rng.integers(0, d_u, size=(n, 2))
        gv = rng.standard_normal((n, 3))
        uv = rng.standard_normal((n, 2))
        for i in range(n):
            out.append({
                "uid": f"q{i}",
                "metadataMap": {"userId": ids[i % E]},
                "features": [
                    {"name": f"g{int(gj[i, j])}", "term": "",
                     "value": float(gv[i, j])}
                    for j in range(3)
                ],
                "userFeatures": [
                    {"name": f"u{int(uj[i, j])}", "term": "",
                     "value": float(uv[i, j])}
                    for j in range(2)
                ],
            })
        return out

    records = make_records(n_req)

    def one_pass(obs_on: bool) -> float:
        if obs_on:
            collector.start()
        with tracing_scope(obs_on):
            t0 = time.perf_counter()
            for rec in records:
                router.score_record(rec)
            wall = time.perf_counter() - t0
        if obs_on:
            # drain the tail so completeness is exact per pass
            collector.stop(final_poll=True)
        return wall

    try:
        tracer().clear()
        one_pass(False)  # warmup: every program + connection touched
        one_pass(True)
        tracer().clear()
        router_book.reset()
        for b in shard_books:
            b.reset()
        # fresh collector for the measured phase: the warmup pass's
        # spans must not ride the completeness accounting
        collector = FleetCollector(
            [("fleet", "127.0.0.1", servers[0].port)],
            poll_s=0.05,
        )
        walls = {False: [], True: []}
        with jtu.count_jit_and_pmap_lowerings() as lowerings:
            for _ in range(passes):
                for arm in (False, True):
                    walls[arm].append(one_pass(arm))
        # -- merge completeness + fleet conservation -----------------------
        stitched = collector.stitched_spans()
        verdict = verify_fleet_trace(stitched)
        roots = [
            s for s in stitched if s["name"] == "router.request"
        ]
        conservation = fleet_check_conservation(
            router_book.check_conservation(),
            {
                f"shard{i}": {
                    "conservation": shard_books[i].check_conservation(),
                    "complete": True,
                    "shard_indices": [i],
                }
                for i in range(2)
            },
        )
        status = collector.member_status()["fleet"]
    finally:
        router.close()
        for srv in servers:
            srv.close()
    ratios = sorted(
        on / off for off, on in zip(walls[False], walls[True])
    )
    overhead = ratios[len(ratios) // 2] - 1.0
    off_s = float(min(walls[False]))
    per_request_us = off_s / n_req * 1e6
    # Deterministic twin of the A/B: the fleet plane's entire
    # request-path addition in the ROUTER process is two conservation
    # notes + two span record/ends per request (the collector runs on
    # its own thread; its cost rides the A/B only). Priced in
    # isolation — best of several repetitions, because the cost is
    # deterministic and the min strips scheduler interference — and
    # divided by the measured per-request wall.
    import gc

    micro_rec = FlightRecorder(1 << 12)
    n_micro = 20_000
    gc.collect()
    conservation_us = float("inf")
    span_us = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n_micro):
            micro_rec.note_admitted()
            micro_rec.note_terminal("ok", generation=1,
                                    attribution="shard:0")
        conservation_us = min(
            conservation_us, (time.perf_counter() - t0) / n_micro * 1e6
        )
        with tracing_scope(True):
            t0 = time.perf_counter()
            for _ in range(n_micro):
                start_span("router.request", uid="q").end()
                start_span("router.subrequest", shard=0).end()
            span_us = min(
                span_us, (time.perf_counter() - t0) / n_micro * 1e6
            )
        tracer().clear()
    implied = (conservation_us + span_us) / per_request_us
    return {
        "config": name,
        "metric": "fleet_obs_request_path_overhead_frac",
        "value": round(overhead, 5),
        "unit": "frac (fleet tracing+collector+attribution on vs off)",
        "detail": {
            "device": str(jax.devices()[0]),
            "host": {"cpu_count": os.cpu_count(), "on_chip": on_chip},
            "shards": 2,
            "requests_per_pass": n_req,
            "passes_per_arm": passes,
            "off_wall_s": [round(w, 4) for w in walls[False]],
            "on_wall_s": [round(w, 4) for w in walls[True]],
            "pairwise_ratios": [round(r, 4) for r in ratios],
            "off_qps": round(n_req / off_s, 1),
            "per_request_us": round(per_request_us, 2),
            "conservation_note_us": round(conservation_us, 3),
            "span_pair_us": round(span_us, 3),
            "implied_overhead_frac": round(implied, 5),
            "request_path_lowerings": int(lowerings[0]),
            "collector": {
                "polls": status["polls"],
                "errors": status["errors"],
                "spans": status["spans"],
                "ring_dropped": status["ring_dropped"],
                "clock_offset_uncertainty_s": (
                    status["clock_offset_uncertainty_s"]
                ),
            },
            "traced_requests": passes * n_req,
            "router_request_roots": len(roots),
            "stitch_ok": verdict["ok"],
            "stitch_violations": verdict["violations"][:5],
            "score_leaves": verdict["score_leaves"],
            "conservation": conservation,
            "data": "synthetic 2-shard TCP fleet, closed-loop router",
        },
    }


def _wire_config(name, *, seed=0):
    """photon-wire A/B (ISSUE 17): the SAME closed-loop routed request
    stream through a REAL 2-shard TCP fleet over the JSON-lines data
    plane vs the length-prefixed binary plane (negotiated at
    ``connect()``), paired-alternating passes per house rules.

    The contract being priced: binary framing + raw-float codecs must
    cut per-request marshalling cost WITHOUT perturbing a single bit of
    the routed margins. Gates in dev-scripts/bench_wire.sh: bitwise
    parity between arms on every pass, binary micro codec cost below
    the JSON micro cost (best-of-reps, measured pre+post the A/B), 0
    request-path lowerings in BOTH arms, fleet conservation balanced
    over the shared ledger, and the binary trace drain COMPLETE (every
    traced request's router.request root reached the collector, 0 ring
    drops). The QPS speedup gate is multi-core/chip-only; the 1-core
    container ratio is recorded honestly.

    A writer-coalescing burst leg pipelines a flood of score frames on
    ONE connection per protocol and reports the walls plus the
    ``coalesced_responses`` counter delta (responses that shared a
    sendall with a predecessor) — gated > 0 in bench_wire.sh."""
    import gc
    import socket

    import jax
    import jax._src.test_util as jtu

    from photon_ml_tpu.game.config import FeatureShardConfiguration
    from photon_ml_tpu.obs.fleet import (
        FleetCollector,
        fleet_check_conservation,
    )
    from photon_ml_tpu.obs.flight_recorder import FlightRecorder
    from photon_ml_tpu.obs.trace import tracer, tracing_scope
    from photon_ml_tpu.serving import (
        PartialScore,
        RoutingPolicy,
        ServingModel,
        ServingPrograms,
        ShardRouter,
        ShardServer,
        bank_from_arrays,
    )
    from photon_ml_tpu.serving import wire
    from photon_ml_tpu.serving.programs import term_entries
    from photon_ml_tpu.utils.index_map import IndexMap

    on_chip = any(p.platform != "cpu" for p in jax.devices())
    if on_chip:
        E, d_g, d_u = 4096, 1 << 14, 64
        n_req, passes = 1_000, 3
    else:
        E, d_g, d_u = 128, 256, 16
        n_req, passes = 300, 5
    # shard widths sized for criteo-width rows (26 + 13 features)
    widths = {"g": 32, "u": 16}
    rng = np.random.default_rng(seed)
    ids = sorted(f"user{i:06d}" for i in range(E))
    fe_w = rng.standard_normal(d_g).astype(np.float32)
    re_w = rng.standard_normal((E, d_u)).astype(np.float32)
    imaps = {
        "g": IndexMap({f"g{j}\t": j for j in range(d_g)}),
        "u": IndexMap({f"u{j}\t": j for j in range(d_u)}),
    }
    shard_cfgs = [
        FeatureShardConfiguration("g", ["features"]),
        FeatureShardConfiguration("u", ["userFeatures"]),
    ]
    shard_books = [FlightRecorder(1 << 14) for _ in range(2)]
    servers = []
    for s in range(2):
        bank = bank_from_arrays(
            fixed=[("global", "g", fe_w)],
            random=[("per-user", "userId", "u", re_w, ids)],
            shard_widths=widths,
            index_maps=imaps,
            entity_shard=(s, 2),
        )
        sm = ServingModel(
            bank, ServingPrograms((1, 8)), partial=True,
            entity_shard=(s, 2),
        )
        servers.append(ShardServer(
            sm, shard_cfgs, (s, 2), has_response=False,
            recorder=shard_books[s],
        ).start())
    term_names = tuple(e[1] for e in term_entries(bank.spec))
    # ONE shared router ledger: both arms' requests land in the same
    # book, so the fleet conservation join prices the TOTAL stream
    router_book = FlightRecorder(1 << 14)

    def make_router(wire_mode):
        return ShardRouter(
            [("127.0.0.1", srv.port) for srv in servers],
            entity_ids={"userId": ids},
            shard_configs=shard_cfgs,
            policy=RoutingPolicy(subrequest_timeout_s=10.0),
            cache_entries=0,  # price the WIRE path, not cache replay
            recorder=router_book,
            wire=wire_mode,
        )

    routers = {"json": make_router("json"), "binary": make_router("binary")}
    negotiated = {}
    for arm, r in routers.items():
        negotiated[arm] = r.connect()["wire"]
    assert negotiated == {"json": "json", "binary": "binary"}, negotiated

    # criteo-width records (39 features/row, the paper's serving
    # shape): the wire plane is priced on realistic rows, where
    # per-float text encode/decode is the marshalling tall pole
    n_g_feat, n_u_feat = 26, 13

    def make_records(n):
        out = []
        gj = rng.integers(0, d_g, size=(n, n_g_feat))
        uj = rng.integers(0, d_u, size=(n, n_u_feat))
        gv = rng.standard_normal((n, n_g_feat))
        uv = rng.standard_normal((n, n_u_feat))
        for i in range(n):
            out.append({
                "uid": f"q{i}",
                "metadataMap": {"userId": ids[i % E]},
                "features": [
                    {"name": f"g{int(gj[i, j])}", "term": "",
                     "value": float(gv[i, j])}
                    for j in range(n_g_feat)
                ],
                "userFeatures": [
                    {"name": f"u{int(uj[i, j])}", "term": "",
                     "value": float(uv[i, j])}
                    for j in range(n_u_feat)
                ],
            })
        return out

    records = make_records(n_req)

    def one_pass(arm):
        router = routers[arm]
        lats = []
        scores = []
        t0 = time.perf_counter()
        for rec in records:
            t = time.perf_counter()
            scores.append(float(router.score_record(rec)))
            lats.append(time.perf_counter() - t)
        wall = time.perf_counter() - t0
        return wall, lats, scores

    # -- deterministic marshalling micro (best-of-reps, pre AND post the
    # A/B per the estimator house rules: the codec cost is
    # deterministic, the min strips scheduler interference, and
    # measuring again after the flood catches state-dependent drift) ---
    micro_req = records[0]
    # the response micro prices EXACTLY what this fleet exchanges: a
    # gather answer with this bank's term entries, carrying f32-exact
    # doubles (what scores ARE) whose shortest-round-trip reprs are
    # long — the per-float text cost the JSON path pays on every answer
    micro_partial = PartialScore.from_vector(
        float(np.float32(0.128437)), term_names,
        rng.standard_normal(len(term_names)).astype(np.float32),
        generation=1,
    )
    micro_head = {
        "uid": "q0", "status": "ok", "partial": True, "generation": 1,
        "degraded": False,
    }
    micro_resp_bin = dict(micro_head)
    micro_resp_bin["_wire_partial"] = micro_partial
    n_micro = 5_000

    def micro_codec():
        """us per request+response encode/decode round-trip, per arm.
        The JSON response is built from the PartialScore per iteration
        — the frontend materializes the terms dict on every gather
        answer; the binary arm ships the vector straight through."""
        gc.collect()
        best = {"json": float("inf"), "binary": float("inf")}
        buf = bytearray()
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n_micro):
                line = json.dumps(micro_req).encode() + b"\n"
                json.loads(line)
                r = dict(micro_head)
                nm, vec = micro_partial.term_vector()
                r["fe"] = micro_partial.fe
                r["terms"] = dict(zip(nm, vec.tolist()))
                rline = json.dumps(r).encode() + b"\n"
                json.loads(rline)
            best["json"] = min(
                best["json"], (time.perf_counter() - t0) / n_micro * 1e6
            )
            dec = wire.FrameDecoder()
            t0 = time.perf_counter()
            for _ in range(n_micro):
                del buf[:]
                wire.append_score_request(buf, micro_req)
                wire.append_response(buf, micro_resp_bin)
                for mtype, payload in dec.feed(bytes(buf)):
                    wire.decode_message(mtype, payload)
            best["binary"] = min(
                best["binary"], (time.perf_counter() - t0) / n_micro * 1e6
            )
        return best

    try:
        micro_pre = micro_codec()
        tracer().clear()
        for arm in ("json", "binary"):
            one_pass(arm)  # warmup: every program + connection touched
        router_book.reset()
        for b in shard_books:
            b.reset()
        walls = {"json": [], "binary": []}
        lats = {"json": [], "binary": []}
        scores = {"json": [], "binary": []}
        collector = FleetCollector(
            [("fleet", "127.0.0.1", servers[0].port)],
            poll_s=0.05,
            wire="binary",
        )
        with jtu.count_jit_and_pmap_lowerings() as lowerings:
            for _ in range(passes):
                for arm in ("json", "binary"):
                    w, ls, sc = one_pass(arm)
                    walls[arm].append(w)
                    lats[arm].extend(ls)
                    scores[arm].append(sc)
            # -- binary trace drain: cursor-keyed span batches ride
            # MSG_TRACE_RESPONSE frames into the live collector --------
            collector.start()
            with tracing_scope(True):
                for rec in records:
                    routers["binary"].score_record(rec)
            collector.stop(final_poll=True)
        # bitwise parity: every pass of each arm must reproduce pass 0
        # of the JSON arm EXACTLY (float equality, no tolerance)
        ref = scores["json"][0]
        parity_ok = all(
            scores[arm][p] == ref
            for arm in ("json", "binary")
            for p in range(passes)
        )
        roots = [
            s for s in collector.stitched_spans()
            if s["name"] == "router.request"
        ]
        status = collector.member_status()["fleet"]
        conservation = fleet_check_conservation(
            router_book.check_conservation(),
            {
                f"shard{i}": {
                    "conservation": shard_books[i].check_conservation(),
                    "complete": True,
                    "shard_indices": [i],
                }
                for i in range(2)
            },
        )
        # -- writer-coalescing burst: ONE connection pipelines a flood
        # of score frames at shard 0 and drains every response; the
        # writer thread must batch the backlog into few sendalls
        # (coalesced_responses counts responses that shared a syscall).
        # Runs OUTSIDE the lowerings counter: a pipelined burst forms
        # batch shapes the closed-loop A/B never did. --------------------
        n_burst = 200
        burst_payload = {}
        buf = bytearray()
        for rec in records[:n_burst]:
            wire.append_score_request(buf, rec)
        burst_payload["binary"] = bytes(buf)
        burst_payload["json"] = "".join(
            json.dumps(rec, separators=(",", ":")) + "\n"
            for rec in records[:n_burst]
        ).encode()

        def one_burst(arm):
            sock = socket.create_connection(
                ("127.0.0.1", servers[0].port), timeout=60
            )
            try:
                t0 = time.perf_counter()
                sock.sendall(burst_payload[arm])
                if arm == "binary":
                    dec = wire.FrameDecoder()
                    got = 0
                    while got < n_burst:
                        got += len(dec.feed(sock.recv(1 << 16)))
                else:
                    f = sock.makefile("rb")
                    for _ in range(n_burst):
                        f.readline()
                return time.perf_counter() - t0
            finally:
                sock.close()

        coalesced0 = servers[0].metrics.snapshot()["frontend"].get(
            "coalesced_responses", 0
        )
        burst_walls = {"json": [], "binary": []}
        for arm in ("json", "binary"):
            one_burst(arm)  # warmup: the burst batch shapes compile here
        for _ in range(3):
            for arm in ("json", "binary"):
                burst_walls[arm].append(one_burst(arm))
        coalesced = servers[0].metrics.snapshot()["frontend"].get(
            "coalesced_responses", 0
        ) - coalesced0
        micro_post = micro_codec()
    finally:
        for r in routers.values():
            r.close()
        for srv in servers:
            srv.close()
    micro = {
        arm: min(micro_pre[arm], micro_post[arm])
        for arm in ("json", "binary")
    }
    ratios = sorted(
        j / b for j, b in zip(walls["json"], walls["binary"])
    )
    speedup = ratios[len(ratios) // 2]
    per_req = {arm: float(min(walls[arm])) / n_req * 1e6
               for arm in ("json", "binary")}

    def p99(samples):
        return float(np.percentile(np.asarray(samples), 99) * 1e6)

    return {
        "config": name,
        "metric": "wire_json_over_binary_wall_ratio",
        "value": round(speedup, 4),
        "unit": "x (routed closed-loop, JSON wall / binary wall)",
        "detail": {
            "device": str(jax.devices()[0]),
            "host": {"cpu_count": os.cpu_count(), "on_chip": on_chip},
            "shards": 2,
            "requests_per_pass": n_req,
            "passes_per_arm": passes,
            "negotiated": negotiated,
            "json_wall_s": [round(w, 4) for w in walls["json"]],
            "binary_wall_s": [round(w, 4) for w in walls["binary"]],
            "pairwise_ratios": [round(r, 4) for r in ratios],
            "json_qps": round(n_req / min(walls["json"]), 1),
            "binary_qps": round(n_req / min(walls["binary"]), 1),
            "json_p99_us": round(p99(lats["json"]), 1),
            "binary_p99_us": round(p99(lats["binary"]), 1),
            "per_request_us": {
                arm: round(v, 2) for arm, v in per_req.items()
            },
            "micro_codec_us": {
                arm: round(micro[arm], 3) for arm in ("json", "binary")
            },
            "micro_codec_us_pre": {
                arm: round(micro_pre[arm], 3)
                for arm in ("json", "binary")
            },
            "micro_codec_us_post": {
                arm: round(micro_post[arm], 3)
                for arm in ("json", "binary")
            },
            "implied_marshalling_frac": {
                arm: round(micro[arm] / per_req[arm], 5)
                for arm in ("json", "binary")
            },
            "bitwise_parity": parity_ok,
            "request_path_lowerings": int(lowerings[0]),
            "burst": {
                "pipelined_requests": n_burst,
                "json_wall_s": [round(w, 4) for w in burst_walls["json"]],
                "binary_wall_s": [
                    round(w, 4) for w in burst_walls["binary"]
                ],
                "json_best_us_per_req": round(
                    min(burst_walls["json"]) / n_burst * 1e6, 2
                ),
                "binary_best_us_per_req": round(
                    min(burst_walls["binary"]) / n_burst * 1e6, 2
                ),
                "coalesced_responses": int(coalesced),
            },
            "trace": {
                "traced_requests": n_req,
                "router_request_roots": len(roots),
                "collector_spans": status["spans"],
                "ring_dropped": status["ring_dropped"],
                "errors": status["errors"],
            },
            "conservation": conservation,
            "data": "synthetic 2-shard TCP fleet, closed-loop router",
        },
    }


def _retrain_config(name, *, n_files=8, rows_per_file=4000, d=2000,
                    k=12, max_iter=30, seed=0):
    """Incremental retrain vs full retrain (ISSUE 10, ROADMAP metric):
    after a parent generation trains and publishes, data is appended at
    1% and 10% of the base rows and the model retrains two ways —

    - FULL: fresh uncached scan of every partition + cold solve from
      zeros (what an hourly cron without the registry pays);
    - INCREMENTAL: per-partition stats cache (only the NEW partition is
      re-read — counted) + drift-safe warm start from the parent
      generation's coefficients.

    Reported per fraction: wall-clock both ways, speedup, the
    partitions-scanned counters, and iteration counts. The correctness
    pins ride along: scanned == new-partitions-only, and the no-drift
    warm-start alignment is BITWISE the parent coefficients
    (warm_start_bitwise). Speedup gates are host-class-aware in
    dev-scripts/bench_retrain.sh (the 1-core CPU container measures the
    counters, not throughput)."""
    import shutil
    import tempfile

    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container
    from photon_ml_tpu.io.input_format import AvroInputDataFormat
    from photon_ml_tpu.io.model_io import save_glm_models_avro
    from photon_ml_tpu.io.streaming import scan_stream
    from photon_ml_tpu.registry import (
        ModelRegistry,
        align_coefficients,
        cached_scan_stream,
    )
    from photon_ml_tpu.task import TaskType
    from photon_ml_tpu.training import train_streaming_glm
    from photon_ml_tpu.utils.index_map import feature_key

    rng = np.random.default_rng(seed)
    tmp = tempfile.mkdtemp(prefix="photon-retrain-bench-")
    try:
        w_true = rng.normal(size=d).astype(np.float32) * 0.3
        train_dir = os.path.join(tmp, "train")
        os.makedirs(train_dir)

        def write_part(fi, rows):
            ix = rng.integers(0, d, size=(rows, k))
            vs = rng.normal(size=(rows, k)).astype(np.float32)
            z = (w_true[ix] * vs).sum(axis=1)
            y = rng.uniform(size=rows) < 1 / (1 + np.exp(-z))
            recs = [
                {
                    "uid": f"{fi}-{i}",
                    "label": float(y[i]),
                    "features": [
                        {"name": str(int(j)), "term": "",
                         "value": float(v)}
                        for j, v in zip(ix[i], vs[i])
                    ],
                    "offset": 0.0,
                    "weight": 1.0,
                }
                for i in range(rows)
            ]
            write_container(
                os.path.join(train_dir, f"part-{fi:03d}.avro"),
                schemas.TRAINING_EXAMPLE_AVRO, recs,
            )

        for fi in range(n_files):
            write_part(fi, rows_per_file)
        base_rows = n_files * rows_per_file
        fmt = AvroInputDataFormat()
        cache_dir = os.path.join(tmp, "scan-cache")

        def fit(index_map, stats, initial=None):
            models, results, _ = train_streaming_glm(
                [train_dir], TaskType.LOGISTIC_REGRESSION,
                regularization_weights=[1.0], max_iter=max_iter,
                fmt=fmt, index_map=index_map, stats=stats,
                initial=initial, prefetch=False,
            )
            (model,) = models.values()
            (result,) = results.values()
            return model, int(result.iterations)

        # parent generation: cold scan (primes the cache) + cold solve
        imap, stats, cs0 = cached_scan_stream([train_dir], fmt, cache_dir)
        parent_model, parent_iters = fit(imap, stats)
        parent_means = {
            key: float(np.asarray(parent_model.means)[i])
            for key, i in imap.items()
        }
        # publish through the REAL registry so the bench exercises the
        # lease/stage/commit path too
        cand = os.path.join(tmp, "candidate")
        os.makedirs(cand)
        save_glm_models_avro(
            {1.0: parent_model}, os.path.join(cand, "model.avro"), imap
        )
        registry = ModelRegistry(os.path.join(tmp, "registry"))
        t0 = time.perf_counter()
        gen1 = registry.publish(cand, data_ranges={"train_dir": train_dir})
        publish_s = time.perf_counter() - t0

        # no-drift alignment bitwise pin (the warm-start parity gate)
        aligned = align_coefficients(parent_means, imap)
        warm_bitwise = bool(
            np.array_equal(aligned, np.asarray(parent_model.means))
        )

        phases = {}
        next_fi = n_files
        for frac in (0.01, 0.10):
            rows_new = max(int(base_rows * frac), 1)
            write_part(next_fi, rows_new)
            next_fi += 1

            t0 = time.perf_counter()
            imap_f, stats_f = scan_stream([train_dir], fmt)
            _model_f, iters_full = fit(imap_f, stats_f)
            full_s = time.perf_counter() - t0

            t0 = time.perf_counter()
            imap_i, stats_i, cs = cached_scan_stream(
                [train_dir], fmt, cache_dir
            )
            initial = align_coefficients(parent_means, imap_i)
            _model_i, iters_inc = fit(imap_i, stats_i, initial=initial)
            inc_s = time.perf_counter() - t0

            phases[f"{int(frac * 100)}pct"] = {
                "rows_appended": rows_new,
                "full_s": round(full_s, 2),
                "incremental_s": round(inc_s, 2),
                "speedup": round(full_s / max(inc_s, 1e-9), 2),
                "iters_full": iters_full,
                "iters_incremental": iters_inc,
                "partitions": cs.partitions,
                "partitions_scanned": cs.scanned,
                "partitions_cached": cs.cached,
            }
        return {
            "config": name,
            "metric": "retrain_speedup_10pct",
            "value": phases["10pct"]["speedup"],
            "unit": "x (full retrain / incremental retrain)",
            "detail": {
                "n_base_rows": base_rows,
                "dim": d,
                "nnz_per_row": k,
                "parent_iters": parent_iters,
                "publish_s": round(publish_s, 3),
                "published_generation": gen1.generation,
                "warm_start_bitwise": warm_bitwise,
                "scan0_scanned": cs0.scanned,
                **phases,
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _regen_with_model(rng, n, d, k, w_true, gen_task, noise=0.5):
    """Draw a dataset from a GIVEN planted model (shared generator for the
    train set and its held-out split)."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import SparseBatch

    indices = rng.integers(0, d, size=(n, k), dtype=np.int64)
    values = rng.normal(size=(n, k)).astype(np.float32)
    z = (w_true[indices] * values).sum(axis=1)
    if gen_task in ("logistic", "hinge"):
        p = 1.0 / (1.0 + np.exp(-z / max(noise, 1e-6)))
        labels = (rng.uniform(size=n) < p).astype(np.float32)
    elif gen_task == "linear":
        labels = (z + noise * rng.normal(size=n)).astype(np.float32)
    elif gen_task == "poisson":
        lam = np.exp(np.clip(z * 0.1, None, 3.0))
        labels = rng.poisson(lam).astype(np.float32)
    else:
        raise ValueError(gen_task)
    batch = SparseBatch(
        indices=jnp.asarray(indices.astype(np.int32)),
        values=jnp.asarray(values),
        labels=jnp.asarray(labels),
        offsets=jnp.zeros((n,), jnp.float32),
        weights=jnp.ones((n,), jnp.float32),
    )
    return batch, w_true


def _synth_re_buckets(
    rng, n_entities, d_local, samples_per_entity, k, chunk
):
    """Synthetic bucketed random-effect data (RandomEffectBucket layout)
    with a planted per-entity model, split into `chunk`-entity buckets so
    transient optimizer state stays bounded."""
    from types import SimpleNamespace

    from photon_ml_tpu.game.random_effect_data import RandomEffectBucket

    buckets = []
    for start in range(0, n_entities, chunk):
        e = min(chunk, n_entities - start)
        s = samples_per_entity
        idx = rng.integers(0, d_local, size=(e, s, k), dtype=np.int32)
        val = rng.normal(size=(e, s, k)).astype(np.float32)
        w_ent = rng.normal(size=(e, 1, d_local)).astype(np.float32) * 0.5
        z = np.take_along_axis(
            np.broadcast_to(w_ent, (e, s, d_local)), idx, axis=2
        )
        z = (z * val).sum(axis=2)
        p = 1.0 / (1.0 + np.exp(-z))
        labels = (rng.uniform(size=(e, s)) < p).astype(np.float32)
        buckets.append(
            RandomEffectBucket(
                entity_codes=np.arange(start, start + e, dtype=np.int32),
                row_index=np.full((e, s), -1, np.int32),
                indices=idx,
                values=val,
                labels=labels,
                offsets=np.zeros((e, s), np.float32),
                weights=np.ones((e, s), np.float32),
            )
        )
    return SimpleNamespace(buckets=buckets)


def _re_bank_update(problem, bank, dataset):
    t0 = time.perf_counter()
    bank, tracker = problem.update_bank(bank, dataset)
    _ = np.asarray(bank[0, 0])  # force
    return bank, tracker, time.perf_counter() - t0


def _glmix_config(
    name,
    *,
    n_fixed,
    d_fixed,
    k_fixed,
    n_users,
    d_user,
    samples_per_user,
    k_user,
    n_items=0,
    d_item=0,
    samples_per_item=0,
    k_item=0,
    re_max_iter=30,
    re_history=5,
    chunk=25_000,
    kernel="auto",
    seed=0,
):
    """Fixed effect + entity banks: one full coordinate-descent-style pass
    (FE solve, then each RE bank update), coefficients counted honestly."""
    import jax.numpy as jnp

    from photon_ml_tpu.game.random_effect import (
        RandomEffectOptimizationProblem,
    )
    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.optim.config import (
        OptimizerConfig,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
    )
    from photon_ml_tpu.task import TaskType
    from photon_ml_tpu.training import train_generalized_linear_model

    rng = np.random.default_rng(seed)
    batch, _ = _synth_sparse(rng, n_fixed, d_fixed, k_fixed)

    from photon_ml_tpu.optim.problem import resolve_kernel

    kernel = resolve_kernel(kernel, batch)
    if kernel == "tiled":
        from photon_ml_tpu.ops.tiled_sparse import tiled_batch_from_sparse

        batch = tiled_batch_from_sparse(batch, d_fixed)

    def fixed_fit():
        t0 = time.perf_counter()
        _, results = train_generalized_linear_model(
            batch,
            TaskType.LOGISTIC_REGRESSION,
            d_fixed,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0],
            max_iter=50,
            kernel=kernel,
        )
        iters = int(next(iter(results.values())).iterations)
        return iters, time.perf_counter() - t0

    fe_iters, _ = fixed_fit()  # compile
    fe_iters, fe_s = fixed_fit()

    re_specs = [("user", n_users, d_user, samples_per_user, k_user)]
    if n_items:
        re_specs.append(("item", n_items, d_item, samples_per_item, k_item))

    re_results = {}
    total_re_coefs = 0
    config = OptimizerConfig(
        OptimizerType.LBFGS,
        max_iter=re_max_iter,
        tolerance=1e-5,
        lbfgs_history=re_history,
    )
    for re_name, n_e, d_l, s_e, k_e in re_specs:
        data = _synth_re_buckets(rng, n_e, d_l, s_e, k_e, chunk)
        problem = RandomEffectOptimizationProblem(
            loss=LOGISTIC,
            config=config,
            regularization=RegularizationContext(RegularizationType.L2),
            reg_weight=1.0,
        )
        bank = jnp.zeros((n_e, d_l), jnp.float32)
        bank, _, _ = _re_bank_update(problem, bank, data)  # compile
        bank = jnp.zeros((n_e, d_l), jnp.float32)
        bank, tracker, re_s = _re_bank_update(problem, bank, data)
        total_re_coefs += n_e * d_l
        re_results[re_name] = {
            "entities": n_e,
            "local_dim": d_l,
            "entities_per_sec": round(n_e / re_s),
            "seconds": round(re_s, 3),
            "iterations_mean": round(tracker.iterations_mean, 2),
        }

    total_coefs = d_fixed + total_re_coefs
    step_s = fe_s + sum(r["seconds"] for r in re_results.values())
    return {
        "config": name,
        "metric": "coordinate_step_s",
        "value": round(step_s, 3),
        "unit": "s (FE solve + all RE bank updates, warm)",
        "detail": {
            "total_coefficients": total_coefs,
            "fixed_effect": {
                "n": n_fixed,
                "dim": d_fixed,
                "iterations": fe_iters,
                "seconds": round(fe_s, 3),
                "examples_per_sec": round(n_fixed * fe_iters / fe_s)
                if fe_s > 0
                else None,
            },
            "random_effects": re_results,
            "data": "fixed-seed synthetic, planted per-entity models",
        },
    }



def _mf_config(
    name,
    *,
    n_rows=138_493,
    n_cols=26_744,
    K=32,
    n_ratings=2_000_000,
    num_inner_iterations=1,
    seed=0,
):
    """Matrix factorization through the REAL MatrixFactorizationCoordinate
    at MovieLens-20M entity counts (ratings subsampled 10x to bound the
    one-time host-side structure build): one update_model call = row +
    col ALS half-steps including the on-device latent-view gathers. The
    BASELINE.json config-5 "+ MF" term."""
    import jax.numpy as jnp

    from photon_ml_tpu.game.coordinate import MatrixFactorizationCoordinate
    from photon_ml_tpu.game.data import EntityIndex, GameDataset
    from photon_ml_tpu.game.random_effect import (
        RandomEffectOptimizationProblem,
    )
    from photon_ml_tpu.ops.losses import LINEAR
    from photon_ml_tpu.optim.config import (
        OptimizerConfig,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
    )

    rng = np.random.default_rng(seed)
    n = n_ratings
    rows = rng.integers(0, n_rows, size=n).astype(np.int32)
    cols = rng.integers(0, n_cols, size=n).astype(np.int32)
    row_true = rng.normal(0, 0.4, size=(n_rows, K)).astype(np.float32)
    col_true = rng.normal(0, 0.4, size=(n_cols, K)).astype(np.float32)
    ratings = (
        (row_true[rows] * col_true[cols]).sum(axis=1)
        + 0.3 * rng.normal(size=n)
    ).astype(np.float32)

    def eindex(prefix, count):
        ids = [f"{prefix}{i}" for i in range(count)]
        return EntityIndex(prefix, ids, {v: i for i, v in enumerate(ids)})

    dataset = GameDataset(
        uids=[""] * n,
        labels=ratings,
        offsets=np.zeros(n, np.float32),
        weights=np.ones(n, np.float32),
        shards={},
        entity_codes={"userId": rows, "itemId": cols},
        entity_indexes={
            "userId": eindex("u", n_rows), "itemId": eindex("i", n_cols)
        },
        num_real_rows=n,
    )
    coord = MatrixFactorizationCoordinate(
        name="mf",
        dataset=dataset,
        row_effect_type="userId",
        col_effect_type="itemId",
        num_latent_factors=K,
        problem=RandomEffectOptimizationProblem(
            loss=LINEAR,
            config=OptimizerConfig(
                OptimizerType.LBFGS, max_iter=20, tolerance=1e-5,
                lbfgs_history=5,
            ),
            regularization=RegularizationContext(RegularizationType.L2),
            reg_weight=1.0,
        ),
        num_inner_iterations=num_inner_iterations,
    )
    model = coord.initialize_model()
    t0 = time.perf_counter()
    model, _ = coord.update_model(model)  # structure build + compile
    _ = np.asarray(model.row_latent[0, 0])
    first_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    model, _ = coord.update_model(model)  # warm: the per-CD-iteration cost
    _ = np.asarray(model.row_latent[0, 0])
    warm_s = time.perf_counter() - t0
    return {
        "config": name,
        "metric": "mf_als_step_s",
        "value": round(warm_s, 3),
        "unit": "s (one full ALS step through the MF coordinate, warm)",
        "detail": {
            "latent_factors": K,
            "total_latent_parameters": (n_rows + n_cols) * K,
            "ratings": n,
            "first_step_s": round(first_s, 3),
            "includes": (
                "on-device latent-view gathers from the partner side's "
                "current factors (structure cached, values_override path)"
            ),
            "data": (
                "fixed-seed synthetic at MovieLens-20M entity counts "
                "(138,493 users x 26,744 movies), planted latent factors"
            ),
        },
    }


def suite(only=None):
    """BASELINE.md matrix. One JSON line per config + summary.

    ``only``: config-name prefix filter (``--only 3`` re-measures just
    config 3); filtered runs MERGE into BASELINE_RESULTS.json instead of
    rewriting it.
    """
    import os

    import jax

    from photon_ml_tpu.utils.backend import enable_compilation_cache

    enable_compilation_cache()
    device = str(jax.devices()[0])
    results = []

    def want(name):
        return only is None or name.startswith(only)

    # 1: a1a logistic grid (README.md:217-256 tutorial shape: n=1605
    # train / 30956 test, d=123; lambdas from run_photon_ml_driver.sh).
    if want("1_a1a_logistic"):
        results.append(
            _glm_fit_config(
                "1_a1a_logistic",
                task="LOGISTIC_REGRESSION",
                optimizer="LBFGS",
                reg_type="L2",
                lambdas=[0.1, 1.0, 10.0, 100.0],
                n=1605,
                d=123,
                k=14,
                n_val=30_956,
                max_iter=50,
                kernel="scatter",  # tiny dim: schedule build not worth it
                shape_note="synthetic with a1a's exact shape (1605x123, ~14 nnz)",
            )
        )
        print(json.dumps(results[-1]), flush=True)

    # 2: Criteo-shaped linear TRON + poisson elastic-net (39 raw features
    # hashed to 1M dims, k=39 nnz).
    if want("2a_criteo_linear_tron"):
        results.append(
            _glm_fit_config(
                "2a_criteo_linear_tron",
                task="LINEAR_REGRESSION",
                optimizer="TRON",
                reg_type="L2",
                lambdas=[1.0],
                n=1 << 18,
                d=1 << 20,
                k=40,
                n_val=1 << 15,
                shape_note="synthetic at Criteo-sample shape (262k x 1M, 40 nnz)",
            )
        )
        print(json.dumps(results[-1]), flush=True)
    if want("2a_feature_sharded_tron"):
        results.append(
            _feature_sharded_tron_config(
                "2a_feature_sharded_tron",
                n=1 << 18,
                d=1 << 20,
                k=40,
            )
        )
        print(json.dumps(results[-1]), flush=True)
    if want("2b_criteo_poisson_elastic_net"):
        results.append(
            _glm_fit_config(
                "2b_criteo_poisson_elastic_net",
                task="POISSON_REGRESSION",
                optimizer="LBFGS",
                reg_type="ELASTIC_NET",
                elastic_net_alpha=0.5,
                lambdas=[0.1, 1.0],
                n=1 << 18,
                d=1 << 20,
                k=40,
                n_val=1 << 15,
                max_iter=50,
                shape_note="synthetic at Criteo-sample shape (262k x 1M, 40 nnz)",
            )
        )
        print(json.dumps(results[-1]), flush=True)

    # 3: smoothed-hinge SVM with per-coefficient box constraints.
    if want("3_hinge_box"):
        results.append(
            _glm_fit_config(
                "3_hinge_box",
                task="SMOOTHED_HINGE_LOSS_LINEAR_SVM",
                optimizer="LBFGS",
                reg_type="L2",
                lambdas=[1.0],
                n=1 << 18,
                d=1 << 17,
                k=32,
                n_val=1 << 15,
                max_iter=50,
                box_bound=0.5,
                shape_note="synthetic (262k x 131k, 32 nnz), box [-0.5, 0.5]",
            )
        )
        print(json.dumps(results[-1]), flush=True)

    # 4fs: config-4-shaped GAME FE under a 1x1 (data, model) mesh — the
    # feature-sharded GAME fixed effect composition cost check.
    if want("4fs_game_fe_sharded"):
        results.append(_game_fe_sharded_config("4fs_game_fe_sharded"))
        print(json.dumps(results[-1]), flush=True)

    # 4: GLMix fixed + per-user RE, ~101M coefficients.
    if want("4_glmix_100m"):
        results.append(
            _glmix_config(
                "4_glmix_100m",
                n_fixed=1 << 18,
                d_fixed=1 << 20,
                k_fixed=64,
                n_users=100_000,
                d_user=1000,
                samples_per_user=16,
                k_user=32,
            )
        )
        print(json.dumps(results[-1]), flush=True)

    # 5: full GAME fixed + user RE + item RE, ~1B coefficients.
    if want("5_game_1b"):
        results.append(
            _glmix_config(
                "5_game_1b",
                n_fixed=1 << 18,
                d_fixed=1 << 20,
                k_fixed=64,
                n_users=600_000,
                d_user=1000,
                samples_per_user=16,
                k_user=32,
                n_items=400_000,
                d_item=1000,
                samples_per_item=16,
                k_item=32,
            )
        )
        print(json.dumps(results[-1]), flush=True)

    if want("5b_movielens_mf"):
        results.append(_mf_config("5b_movielens_mf"))
        print(json.dumps(results[-1]), flush=True)

    # 6: streaming (>RAM-shaped) input path with the staged-chunk cache.
    if want("6_streaming"):
        results.append(_streaming_config("6_streaming"))
        print(json.dumps(results[-1]), flush=True)

    # 7: out-of-core GAME coordinate descent (streamed CD A/B vs
    # in-memory on the same files; budget-bounded RSS).
    if want("7_streaming_game"):
        results.append(_streaming_game_config("7_streaming_game"))
        print(json.dumps(results[-1]), flush=True)

    # 8: batched λ-grid training (one vmapped grid program vs the
    # warm-started sequential path; compile counts + per-λ parity).
    if want("8_grid_batched"):
        results.append(_grid_batched_config("8_grid_batched"))
        print(json.dumps(results[-1]), flush=True)

    # 9: reliability-layer overhead (round 11): seams active vs bypassed
    # on the spill-read hot path; <2% gate in dev-scripts/chaos.sh.
    if want("9_reliability"):
        results.append(_reliability_config("9_reliability"))
        print(json.dumps(results[-1]), flush=True)

    # 10: online scoring service (round 12): single-request latency +
    # saturating QPS over a device-resident bank at config-5 shapes;
    # gates in dev-scripts/bench_serving.sh.
    if want("10_serving"):
        results.append(_serving_config("10_serving"))
        print(json.dumps(results[-1]), flush=True)

    # 11: serving under fire (ISSUE 8): open-loop flood past capacity
    # through admission control — shed rate, admitted p99, bounded
    # drain; gates in dev-scripts/bench_overload.sh.
    if want("11_overload"):
        results.append(_overload_config("11_overload"))
        print(json.dumps(results[-1]), flush=True)

    # 12: pod-scale GAME (ISSUE 9): entity-sharded RE banks + two-hop
    # routed residuals vs the replicated path — per-device state bytes,
    # parity, zero routed readbacks, weak-scaling table; gates in
    # dev-scripts/bench_pod_game.sh.
    if want("12_pod_game"):
        results.append(_pod_game_config("12_pod_game"))
        print(json.dumps(results[-1]), flush=True)

    # 13: continuous retraining (ISSUE 10): incremental retrain
    # (per-partition stats cache + registry warm start) vs full retrain
    # at 1%/10% appended data — the ROADMAP metric; gates in
    # dev-scripts/bench_retrain.sh.
    if want("13_retrain"):
        results.append(_retrain_config("13_retrain"))
        print(json.dumps(results[-1]), flush=True)

    # 14: planet-scale serving (ISSUE 12): aggregate QPS vs shard count
    # through the scatter/gather router over subprocess shard-servers
    # under a zipf flood, + the SIGKILL-one-shard degradation leg;
    # gates in dev-scripts/bench_shard_routing.sh.
    if want("14_shard_routing"):
        results.append(_shard_routing_config("14_shard_routing"))
        print(json.dumps(results[-1]), flush=True)

    # 15: unified telemetry (ISSUE 13): tracing/metrics on-vs-off
    # request-path overhead A/B + trace completeness + conservation;
    # gates in dev-scripts/bench_obs.sh.
    if want("15_observability"):
        results.append(_obs_config("15_observability"))
        print(json.dumps(results[-1]), flush=True)

    # 16: fleet observability (ISSUE 15): collector/tracing/attribution
    # on-vs-off over a real 2-shard TCP fleet + merge completeness +
    # fleet conservation; gates in dev-scripts/bench_fleet_obs.sh.
    if want("16_fleet_observability"):
        results.append(_fleet_obs_config("16_fleet_observability"))
        print(json.dumps(results[-1]), flush=True)

    # 17: photon-wire (ISSUE 17): binary data plane vs JSON-lines over
    # a real 2-shard TCP fleet — paired A/B, bitwise parity, micro
    # codec cost, binary trace drain; gates in dev-scripts/bench_wire.sh.
    if want("17_wire"):
        results.append(_wire_config("17_wire"))
        print(json.dumps(results[-1]), flush=True)

    # 18: unified mesh (ISSUE 20): the whole λ-grid over an
    # entity-sharded GAME model as ONE shard_mapped program vs G
    # sequential pod CD sweeps — parity, 1-readback/iteration,
    # 0-relowering, per-device bank bytes, wall both ways; gates in
    # dev-scripts/bench_unified_mesh.sh.
    if want("18_unified_mesh"):
        results.append(_unified_mesh_config("18_unified_mesh"))
        print(json.dumps(results[-1]), flush=True)

    path = "BASELINE_RESULTS.json"
    merged = {}
    if only is not None and os.path.exists(path):
        with open(path) as f:
            for r in json.load(f).get("results", []):
                merged[r["config"]] = r
    for r in results:
        merged[r["config"]] = r
    from photon_ml_tpu.reliability import atomic_write_json, reliability_metrics

    atomic_write_json(
        path,
        {
            "device": device,
            "results": list(merged.values()),
            # fault-injection/retry accounting rides in the round
            # artifact so BENCH rounds record reliability overhead
            "reliability": reliability_metrics(),
        },
    )
    summary = {
        "metric": "baseline_suite",
        "value": len(results),
        "unit": "configs",
        "vs_baseline": 1.0,
        "detail": {"device": device, "configs": [r["config"] for r in results]},
    }
    print(json.dumps(summary))


if __name__ == "__main__":
    if SHARD_CHILD_FLAG in sys.argv:
        # one shard-server subprocess of the 14_shard_routing fleet
        # (spawned by _shard_routing_config; never run by hand)
        _shard_routing_child(sys.argv[sys.argv.index(SHARD_CHILD_FLAG) + 1])
    elif "--overlap-ab" in sys.argv:
        print(json.dumps(overlap_ab(full="--full" in sys.argv)))
    elif "--grid-batched" in sys.argv:
        # dev-scripts/bench_grid.sh entry: the batched λ-grid A/B as one
        # JSON line (gates applied by the script)
        print(json.dumps(_grid_batched_config("grid_batched")))
    elif "--serving" in sys.argv:
        # dev-scripts/bench_serving.sh entry: the online-scoring bench
        # as one JSON line (gates applied by the script)
        print(json.dumps(_serving_config("serving")))
    elif "--overload" in sys.argv:
        # dev-scripts/bench_overload.sh entry: the serving-under-fire
        # flood as one JSON line (gates applied by the script)
        print(json.dumps(_overload_config("overload")))
    elif "--reliability" in sys.argv:
        # dev-scripts/chaos.sh entry: the seam-overhead A/B as one JSON
        # line (the <2% gate is applied by the script)
        print(json.dumps(_reliability_config("reliability")))
    elif "--streaming-game" in sys.argv:
        # dev-scripts/bench_streaming_game.sh entry: the streamed GAME
        # CD A/B as one JSON line (gates applied by the script)
        print(json.dumps(_streaming_game_config("streaming_game")))
    elif "--unified-mesh" in sys.argv:
        # dev-scripts/bench_unified_mesh.sh entry: the unified-mesh A/B
        # as one JSON line (gates applied by the script)
        print(json.dumps(_unified_mesh_config("unified_mesh")))
    elif "--pod-game" in sys.argv:
        # dev-scripts/bench_pod_game.sh entry: the entity-sharded GAME
        # A/B as one JSON line (gates applied by the script)
        print(json.dumps(_pod_game_config("pod_game")))
    elif "--retrain" in sys.argv:
        # dev-scripts/bench_retrain.sh entry: incremental vs full
        # retrain as one JSON line (gates applied by the script)
        print(json.dumps(_retrain_config("retrain")))
    elif "--shard-routing" in sys.argv:
        # dev-scripts/bench_shard_routing.sh entry: the scatter/gather
        # fleet bench as one JSON line (gates applied by the script)
        print(json.dumps(_shard_routing_config("shard_routing")))
    elif "--wire" in sys.argv:
        # dev-scripts/bench_wire.sh entry: the binary-vs-JSON wire A/B
        # as one JSON line (gates applied by the script)
        print(json.dumps(_wire_config("wire")))
    elif "--fleet-obs" in sys.argv:
        # dev-scripts/bench_fleet_obs.sh entry: the fleet-collector
        # overhead A/B as one JSON line (gates applied by the script)
        print(json.dumps(_fleet_obs_config("fleet_obs")))
    elif "--obs" in sys.argv:
        # dev-scripts/bench_obs.sh entry: the telemetry overhead A/B
        # as one JSON line (gates applied by the script)
        print(json.dumps(_obs_config("obs")))
    elif "--suite" in sys.argv:
        only = None
        if "--only" in sys.argv:
            i = sys.argv.index("--only") + 1
            if i >= len(sys.argv):
                sys.exit("--only requires a config-name prefix")
            only = sys.argv[i]
        suite(only=only)
    else:
        main()
