// Native tiled-schedule builder for the Pallas sparse GLM kernels.
//
// Replaces the numpy schedule build in photon_ml_tpu/ops/tiled_sparse.py
// (_build_schedule_np) on the hot path: numpy's stable argsort of the
// 16.7M-entry tile keys holds the GIL and costs ~3-4 s per pass at the ads
// shape; tile ids take only num_out_blocks x num_in_blocks distinct values,
// so a stable COUNTING sort does the whole grouping in two O(n) passes
// (~0.15 s). The schedule semantics are identical to the numpy builder —
// its tests are the oracle (tests/test_tiled_sparse.py).
//
// Entry layout contract (mirrors _Schedule in tiled_sparse.py):
//   step_out[G], step_in[G], step_init[G]   int32
//   o_pos[G8*L], i_pos[G8*L]                int32 (window-local positions)
//   sv[G8*L]                                float32 (0 for padding slots)
// where G8 = ceil(G/8)*8 and the caller zero-initializes the outputs.
//
// Two-call protocol (stateless, no handle lifetime to manage):
//   ts_plan(...)  -> 0 + (steps, spilled) (or <0: numpy fallback)
//   ts_fill(...)  -> 0 ok / <0 error; fills the caller's arrays
//
// The pass is role-symmetric: the z-pass calls with (out=rows, in=feats),
// the gradient pass with (out=feats, in=rows) — same code path.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct TileDims {
  int64_t n_in_blocks;
  int64_t n_tiles;
};

// Counting sort is only a win while the tile space is comparable to the
// entry count; past 4x entries (min 1M) the histogram dominates and the
// caller's numpy builder (comparison sort) is the right tool. Also keeps
// the per-call allocations bounded (~8 bytes/tile x 4 vectors).
int64_t max_tiles(int64_t n) {
  int64_t floor_tiles = int64_t(1) << 20;
  int64_t rel = 4 * n;
  return rel > floor_tiles ? rel : floor_tiles;
}

// Returns n_tiles <= 0 when any coordinate is negative or an out
// coordinate falls outside the declared output-block space — the caller
// then falls back to the numpy builder's Python-level error instead of
// this code indexing the histogram out of bounds.
TileDims tile_dims(const int64_t* out_coord, const int64_t* in_coord,
                   int64_t n, int64_t win, int64_t num_out_blocks) {
  int64_t max_in = 0;
  bool bad = false;
  for (int64_t i = 0; i < n; ++i) {
    if (in_coord[i] > max_in) max_in = in_coord[i];
    if (in_coord[i] < 0 || out_coord[i] < 0 ||
        out_coord[i] / win >= num_out_blocks) {
      bad = true;
    }
  }
  TileDims d;
  d.n_in_blocks = n ? (max_in / win + 1) : 1;
  d.n_tiles = bad ? -1 : num_out_blocks * d.n_in_blocks;
  return d;
}

}  // namespace

// Spill rule shared by the planning and fill passes (mirrors
// _build_schedule_np): a tile of c entries keeps n_chunks full chunks and
// routes `spill` tail entries to the caller's scatter path. `cap` <= 0
// disables spilling.
struct TilePlan {
  int64_t n_chunks;
  int64_t spill;
};

static TilePlan tile_plan(int64_t c, int64_t chunk, int64_t cap) {
  TilePlan p;
  int64_t full = c / chunk;
  int64_t rem = c % chunk;
  if (cap > 0 && c <= cap) {
    p.n_chunks = 0;
    p.spill = c;
  } else if (cap > 0 && rem > 0 && rem <= cap && full >= 1) {
    p.n_chunks = full;
    p.spill = rem;
  } else {
    p.n_chunks = full + (rem ? 1 : 0);
    p.spill = 0;
  }
  return p;
}

extern "C" {

// Plan a schedule: *steps = grid steps (data chunks + zero-entry init
// steps for output blocks with none), *spilled = spill entry count.
// Returns 0, or -1 when the tile space is too large for a counting sort
// (caller falls back to the numpy builder).
int64_t ts_plan(const int64_t* out_coord, const int64_t* in_coord,
                int64_t n, int64_t win, int64_t chunk, int64_t cap,
                int64_t num_out_blocks, int64_t* steps_out,
                int64_t* spilled_out) try {
  TileDims d = tile_dims(out_coord, in_coord, n, win, num_out_blocks);
  if (d.n_tiles <= 0 || d.n_tiles > max_tiles(n)) return -1;
  std::vector<int64_t> counts(static_cast<size_t>(d.n_tiles), 0);
  for (int64_t i = 0; i < n; ++i) {
    int64_t t = (out_coord[i] / win) * d.n_in_blocks + in_coord[i] / win;
    ++counts[static_cast<size_t>(t)];
  }
  int64_t steps = 0;
  int64_t spilled = 0;
  for (int64_t ob = 0; ob < num_out_blocks; ++ob) {
    bool present = false;
    const int64_t* row = counts.data() + ob * d.n_in_blocks;
    for (int64_t ib = 0; ib < d.n_in_blocks; ++ib) {
      if (!row[ib]) continue;
      TilePlan p = tile_plan(row[ib], chunk, cap);
      spilled += p.spill;
      if (p.n_chunks) {
        present = true;
        steps += p.n_chunks;
      }
    }
    if (!present) ++steps;  // zero-entry init step
  }
  *steps_out = steps;
  *spilled_out = spilled;
  return 0;
} catch (...) {
  // bad_alloc etc. must not cross the ctypes boundary (std::terminate);
  // <0 routes the caller to the numpy fallback
  return -1;
}

// Fill a schedule. Outputs must be zero-initialized by the caller and sized
// step_out/step_in/step_init: [G]; o_pos/i_pos/sv: [ceil(G/8)*8 * chunk];
// sp_out/sp_in/sp_vals: [expected_spill]. Returns 0, or -1 on tile-space
// overflow / plan mismatch.
int64_t ts_fill(const int64_t* out_coord, const int64_t* in_coord,
                const float* vals, int64_t n, int64_t win, int64_t chunk,
                int64_t cap, int64_t num_out_blocks, int64_t expected_steps,
                int64_t expected_spill,
                int32_t* step_out, int32_t* step_in, int32_t* step_init,
                int32_t* o_pos, int32_t* i_pos, float* sv,
                int32_t* sp_out, int32_t* sp_in, float* sp_vals) try {
  TileDims d = tile_dims(out_coord, in_coord, n, win, num_out_blocks);
  if (d.n_tiles <= 0 || d.n_tiles > max_tiles(n)) return -1;
  std::vector<int64_t> counts(static_cast<size_t>(d.n_tiles), 0);
  for (int64_t i = 0; i < n; ++i) {
    int64_t t = (out_coord[i] / win) * d.n_in_blocks + in_coord[i] / win;
    ++counts[static_cast<size_t>(t)];
  }

  // Walk tiles in (out block, in block) order, assigning each non-empty
  // tile its run of chunk steps (per the spill rule) and each OUT BLOCK
  // with no chunked tile one init step; record where each tile's KEPT
  // entries start in step space (step_base), how many it keeps (kept), and
  // where its spilled tail lands in the spill arrays (spill_base).
  std::vector<int64_t> step_base(static_cast<size_t>(d.n_tiles), 0);
  std::vector<int64_t> kept(static_cast<size_t>(d.n_tiles), 0);
  std::vector<int64_t> spill_base(static_cast<size_t>(d.n_tiles), 0);
  int64_t step = 0;
  int64_t spilled = 0;
  for (int64_t ob = 0; ob < num_out_blocks; ++ob) {
    bool first_of_block = true;
    for (int64_t ib = 0; ib < d.n_in_blocks; ++ib) {
      size_t t = static_cast<size_t>(ob * d.n_in_blocks + ib);
      int64_t c = counts[t];
      if (!c) continue;
      TilePlan p = tile_plan(c, chunk, cap);
      kept[t] = c - p.spill;
      spill_base[t] = spilled;
      spilled += p.spill;
      if (!p.n_chunks) continue;
      step_base[t] = step;
      if (step + p.n_chunks > expected_steps) return -1;  // plan mismatch
      for (int64_t j = 0; j < p.n_chunks; ++j) {
        step_out[step] = static_cast<int32_t>(ob);
        step_in[step] = static_cast<int32_t>(ib);
        step_init[step] = (first_of_block && j == 0) ? 1 : 0;
        ++step;
      }
      first_of_block = false;
    }
    if (first_of_block) {  // no chunked entries in this output block
      if (step >= expected_steps) return -1;  // plan mismatch
      step_out[step] = static_cast<int32_t>(ob);
      step_in[step] = 0;
      step_init[step] = 1;
      ++step;
    }
  }
  if (step != expected_steps || spilled != expected_spill) return -1;

  // Stable scatter: each entry lands at its tile's running cursor; the
  // first `kept` go to chunk slots, the tail to the spill arrays (both
  // orderings match the numpy builder exactly).
  std::vector<int64_t> cursor(static_cast<size_t>(d.n_tiles), 0);
  for (int64_t i = 0; i < n; ++i) {
    int64_t ob = out_coord[i] / win;
    int64_t ib = in_coord[i] / win;
    size_t t = static_cast<size_t>(ob * d.n_in_blocks + ib);
    int64_t q = cursor[t]++;
    if (q < kept[t]) {
      int64_t row = step_base[t] + q / chunk;
      int64_t slot = row * chunk + q % chunk;
      o_pos[slot] = static_cast<int32_t>(out_coord[i] % win);
      i_pos[slot] = static_cast<int32_t>(in_coord[i] % win);
      sv[slot] = vals[i];
    } else {
      int64_t s = spill_base[t] + (q - kept[t]);
      sp_out[s] = static_cast<int32_t>(out_coord[i]);
      sp_in[s] = static_cast<int32_t>(in_coord[i]);
      sp_vals[s] = vals[i];
    }
  }
  return 0;
} catch (...) {
  return -1;
}

}  // extern "C"
