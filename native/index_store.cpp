// Memory-mapped feature index store — the TPU-native PalDB replacement.
//
// Reference behavior being replaced: photon-ml's off-heap PalDB stores
// (util/PalDBIndexMap.scala:43-130 — partitioned name->index and
// index->name stores with offset arrays, distributed via SparkFiles) built
// by FeatureIndexingJob.scala:59-136. At >200k-feature vocabularies an
// in-heap dict is too slow/large on the JVM; here the same concern applies
// to the Python host process feeding TPUs, so the store is a flat mmap
// file with an open-addressing hash table — O(1) bidirectional lookup,
// zero deserialization, shareable across host processes.
//
// File layout (little-endian, 8-byte aligned sections):
//   [0]  magic  "PIDX" (4 bytes) + version u32
//   [8]  num_keys u64
//   [16] num_buckets u64       (power of two, ~2x keys)
//   [24] entries_offset u64    (start of entry region)
//   [32] reverse_offset u64    (start of reverse offset array)
//   [40] bucket table: u64[num_buckets], 0 = empty, else offset of entry
//   [entries_offset]  entries: u32 key_len, key bytes, padding to 4,
//                     u32 local_index  (repeated)
//   [reverse_offset]  u64[num_keys]: entry offset by local index
//
// Exposed with a plain C ABI for ctypes.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>
#include <string>

namespace {

constexpr uint32_t kMagic = 0x58444950;  // "PIDX"
constexpr uint32_t kVersion = 1;

inline uint64_t fnv1a(const char* data, uint32_t len) {
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= 1099511628211ULL;
  }
  return h;
}

inline uint64_t round_up(uint64_t x, uint64_t m) { return (x + m - 1) / m * m; }

struct Header {
  uint32_t magic;
  uint32_t version;
  uint64_t num_keys;
  uint64_t num_buckets;
  uint64_t entries_offset;
  uint64_t reverse_offset;
};

struct Store {
  int fd = -1;
  const char* base = nullptr;
  size_t size = 0;
  const Header* header = nullptr;
  const uint64_t* buckets = nullptr;
  const uint64_t* reverse = nullptr;
};

}  // namespace

extern "C" {

// Build a store file from `n` keys (keys[i] has byte length key_lens[i]),
// local indices 0..n-1. Returns 0 on success, negative errno-style code on
// failure. Duplicate keys are rejected (-2).
int pidx_build(const char* path, const char* const* keys,
               const uint32_t* key_lens, uint64_t n) {
  uint64_t num_buckets = 16;
  while (num_buckets < 2 * n) num_buckets <<= 1;

  // entry region layout
  std::vector<uint64_t> entry_offsets(n);
  uint64_t entries_size = 0;
  for (uint64_t i = 0; i < n; ++i) {
    entry_offsets[i] = entries_size;
    entries_size += round_up(4 + key_lens[i], 4) + 4;
  }
  const uint64_t header_size = sizeof(Header);
  const uint64_t buckets_off = header_size;
  const uint64_t entries_off = round_up(buckets_off + 8 * num_buckets, 8);
  const uint64_t reverse_off = round_up(entries_off + entries_size, 8);
  const uint64_t total = reverse_off + 8 * n;

  std::vector<char> buf(total, 0);
  Header* h = reinterpret_cast<Header*>(buf.data());
  h->magic = kMagic;
  h->version = kVersion;
  h->num_keys = n;
  h->num_buckets = num_buckets;
  h->entries_offset = entries_off;
  h->reverse_offset = reverse_off;

  uint64_t* buckets = reinterpret_cast<uint64_t*>(buf.data() + buckets_off);
  uint64_t* reverse = reinterpret_cast<uint64_t*>(buf.data() + reverse_off);

  for (uint64_t i = 0; i < n; ++i) {
    const uint64_t eoff = entries_off + entry_offsets[i];
    char* e = buf.data() + eoff;
    const uint32_t len = key_lens[i];
    std::memcpy(e, &len, 4);
    std::memcpy(e + 4, keys[i], len);
    const uint32_t local = static_cast<uint32_t>(i);
    std::memcpy(e + round_up(4 + len, 4), &local, 4);
    reverse[i] = eoff;

    uint64_t b = fnv1a(keys[i], len) & (num_buckets - 1);
    for (;;) {
      if (buckets[b] == 0) {
        buckets[b] = eoff;
        break;
      }
      // duplicate check
      const char* other = buf.data() + buckets[b];
      uint32_t olen;
      std::memcpy(&olen, other, 4);
      if (olen == len && std::memcmp(other + 4, keys[i], len) == 0) return -2;
      b = (b + 1) & (num_buckets - 1);
    }
  }

  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  const size_t written = std::fwrite(buf.data(), 1, total, f);
  std::fclose(f);
  return written == total ? 0 : -1;
}

// Open (mmap) a store; returns an opaque handle or nullptr.
void* pidx_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(Header)) {
    ::close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  Store* s = new Store();
  s->fd = fd;
  s->base = static_cast<const char*>(base);
  s->size = st.st_size;
  s->header = reinterpret_cast<const Header*>(s->base);
  if (s->header->magic != kMagic || s->header->version != kVersion) {
    munmap(base, st.st_size);
    ::close(fd);
    delete s;
    return nullptr;
  }
  s->buckets = reinterpret_cast<const uint64_t*>(s->base + sizeof(Header));
  s->reverse =
      reinterpret_cast<const uint64_t*>(s->base + s->header->reverse_offset);
  return s;
}

void pidx_close(void* handle) {
  Store* s = static_cast<Store*>(handle);
  if (!s) return;
  munmap(const_cast<char*>(s->base), s->size);
  ::close(s->fd);
  delete s;
}

uint64_t pidx_size(void* handle) {
  return static_cast<Store*>(handle)->header->num_keys;
}

// key -> local index, or -1.
int64_t pidx_get_index(void* handle, const char* key, uint32_t len) {
  const Store* s = static_cast<Store*>(handle);
  const uint64_t mask = s->header->num_buckets - 1;
  uint64_t b = fnv1a(key, len) & mask;
  for (;;) {
    const uint64_t eoff = s->buckets[b];
    if (eoff == 0) return -1;
    const char* e = s->base + eoff;
    uint32_t elen;
    std::memcpy(&elen, e, 4);
    if (elen == len && std::memcmp(e + 4, key, len) == 0) {
      uint32_t local;
      std::memcpy(&local, e + round_up(4 + elen, 4), 4);
      return static_cast<int64_t>(local);
    }
    b = (b + 1) & mask;
  }
}

// local index -> key bytes; returns key length or -1 (buffer too small: the
// required length is returned and nothing is copied when out_len is
// insufficient — call again with a larger buffer).
int64_t pidx_get_key(void* handle, uint64_t local_index, char* out,
                     uint32_t out_len) {
  const Store* s = static_cast<Store*>(handle);
  if (local_index >= s->header->num_keys) return -1;
  const char* e = s->base + s->reverse[local_index];
  uint32_t len;
  std::memcpy(&len, e, 4);
  if (len <= out_len) std::memcpy(out, e + 4, len);
  return len;
}

// Batched lookup for hot loops: keys packed back-to-back with an offsets
// array (offsets[i]..offsets[i+1]); writes indices[i] (or -1).
void pidx_get_indices(void* handle, const char* packed,
                      const uint64_t* offsets, uint64_t n, int64_t* out) {
  for (uint64_t i = 0; i < n; ++i) {
    const uint64_t off = offsets[i];
    out[i] = pidx_get_index(handle, packed + off,
                            static_cast<uint32_t>(offsets[i + 1] - off));
  }
}

}  // extern "C"
