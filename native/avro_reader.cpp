// Native Avro container decoder for photon-ml-tpu.
//
// Role: the reference's data path is JVM Avro readers distributed by
// Spark (avro/AvroUtils.scala:54+, avro/data/DataProcessingUtils.scala:
// 57-143); this build's portable fallback is the pure-Python codec in
// photon_ml_tpu/io/avro_codec.py, which tops out around ~100k
// records/s. This decoder is the native equivalent: it interprets a
// compact schema "plan" compiled by Python (no JSON parsing here) and
// materializes ONLY the requested columns:
//   - numeric scalar fields  -> float64 columns [n]
//   - string scalar fields   -> interned-id int32 columns [n]
//   - metadataMap lookups    -> interned-id int32 columns [n] per key
//   - feature bags (array of {name, term, value} records)
//       -> row_ptr[n+1] + interned "name\tterm" key ids + float64 values
// Interned strings are shared across all columns of one file via a
// single open-addressing table; Python remaps ids to global index maps.
//
// Plan bytecode (uint32 stream), one op per schema node:
//   0 NULL | 1 BOOL | 2 INT | 3 LONG | 4 FLOAT | 5 DOUBLE
//   6 BYTES | 7 STRING
//   8 UNION    [nbranches, {branch_len_u32s, branch_ops...} x n]
//   9 RECORD   [nfields, field ops inline x n]
//  10 ARRAY    [item_len_u32s, item ops]
//  11 MAP      [value_len_u32s, value ops]
//  16 CAP_NUM  [slot, numeric/union ops]      capture one double / record
//  17 CAP_STR  [slot, string/union ops]       capture one interned id
//  18 CAP_BAG  [slot, nfields, {role, field_len_u32s, field ops} x n]
//              role: 0 skip, 1 name, 2 term, 3 value
//  19 CAP_MAP  [slot_base, map value ops must be string]
//              captures requested keys (passed via pavro_decode) into
//              int32 columns slot_base + key_index
//
// Build: g++ -O2 -shared -fPIC -std=c++17 avro_reader.cpp -o ... -lz

#include <zlib.h>

#include <locale.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

thread_local std::string g_error;

struct Decoder {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  bool need(size_t n) {
    if (static_cast<size_t>(end - p) < n) {
      ok = false;
      return false;
    }
    return true;
  }
  int64_t read_long() {
    uint64_t acc = 0;
    int shift = 0;
    while (true) {
      if (!need(1)) return 0;
      uint8_t b = *p++;
      acc |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) {
        ok = false;
        return 0;
      }
    }
    return static_cast<int64_t>((acc >> 1) ^ (~(acc & 1) + 1));
  }
  float read_float() {
    if (!need(4)) return 0.f;
    float v;
    std::memcpy(&v, p, 4);
    p += 4;
    return v;
  }
  double read_double() {
    if (!need(8)) return 0.0;
    double v;
    std::memcpy(&v, p, 8);
    p += 8;
    return v;
  }
  bool read_bytes(const uint8_t** out, int64_t* len) {
    int64_t n = read_long();
    if (!ok || n < 0 || !need(static_cast<size_t>(n))) {
      ok = false;
      return false;
    }
    *out = p;
    *len = n;
    p += n;
    return true;
  }
};

// string interner: name\tterm keys and entity ids
struct Interner {
  std::unordered_map<std::string, int32_t> map;
  std::string blob;                 // concatenated strings
  std::vector<uint64_t> offsets;    // size + 1 entries

  Interner() { offsets.push_back(0); }

  int32_t intern(const char* s, size_t n) {
    std::string key(s, n);
    auto it = map.find(key);
    if (it != map.end()) return it->second;
    int32_t id = static_cast<int32_t>(map.size());
    map.emplace(std::move(key), id);
    blob.append(s, n);
    offsets.push_back(blob.size());
    return id;
  }
};

struct Bag {
  std::vector<int64_t> row_ptr{0};
  std::vector<int32_t> key_ids;
  std::vector<double> values;
};

struct Result {
  int64_t nrecords = 0;
  std::vector<std::vector<double>> f64;   // CAP_NUM slots
  std::vector<std::vector<int32_t>> i32;  // CAP_STR / CAP_MAP slots
  std::vector<Bag> bags;                  // CAP_BAG slots
  Interner intern;
  std::vector<uint8_t> decompressed;      // block scratch kept alive
};

enum Op : uint32_t {
  OP_NULL = 0,
  OP_BOOL = 1,
  OP_INT = 2,
  OP_LONG = 3,
  OP_FLOAT = 4,
  OP_DOUBLE = 5,
  OP_BYTES = 6,
  OP_STRING = 7,
  OP_UNION = 8,
  OP_RECORD = 9,
  OP_ARRAY = 10,
  OP_MAP = 11,
  CAP_NUM = 16,
  CAP_STR = 17,
  CAP_BAG = 18,
  CAP_MAP = 19,
};

enum Want { W_NONE = 0, W_NUM = 1, W_STR = 2 };

// LC_NUMERIC-proof strtod: the embedding process may have set a
// comma-decimal locale (GUI toolkits do), which must not change how
// JVM-written Avro decodes.
static double c_strtod(const char* s, char** end = nullptr) {
  static locale_t loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  if (!loc) return strtod(s, end);  // newlocale failed: best effort
  return strtod_l(s, end, loc);
}

struct Sink {
  int want = W_NONE;
  bool have = false;
  double num = NAN;
  const uint8_t* str = nullptr;
  int64_t str_len = 0;
  // scratch for rendering a numeric/boolean union branch under a string
  // sink (metronome-style ids: uid/userId may arrive as int or long)
  char buf[40];

  void set_rendered(int n) {
    str = reinterpret_cast<const uint8_t*>(buf);
    str_len = n;
    have = true;
  }

  // Python-str parity for float branches: shortest decimal that
  // round-trips, positional vs scientific chosen by Python's repr rule
  // (exponent only when |v| >= 1e16 or 0 < |v| < 1e-4), trailing ".0"
  // for integral positional values, "nan"/"inf"/"-inf" specials, and a
  // decimal point immune to the process locale.
  void render_double(double v) {
    if (std::isnan(v)) {
      set_rendered(snprintf(buf, sizeof(buf), "nan"));
      return;
    }
    if (std::isinf(v)) {
      set_rendered(snprintf(buf, sizeof(buf), v > 0 ? "inf" : "-inf"));
      return;
    }
    double av = std::fabs(v);
    bool want_exp = v != 0.0 && (av >= 1e16 || av < 1e-4);
    char fallback[40];
    int fallback_n = -1;
    for (int prec = 1; prec <= 17; ++prec) {
      int n = snprintf(buf, sizeof(buf), "%.*g", prec, v);
      for (char* c = buf; *c; ++c)
        if (*c == ',') *c = '.';
      if (c_strtod(buf) != v) continue;
      bool has_e = strpbrk(buf, "eE") != nullptr;
      if (has_e != want_exp) {
        // shortest form round-trips but in the wrong notation (e.g. %g
        // gives "2e+01" for 20.0); remember it, keep looking for a
        // notation-matching precision
        if (fallback_n < 0) {
          memcpy(fallback, buf, n + 1);
          fallback_n = n;
        }
        continue;
      }
      if (!strpbrk(buf, ".eE"))
        n += snprintf(buf + n, sizeof(buf) - n, ".0");
      set_rendered(n);
      return;
    }
    if (fallback_n >= 0) {
      memcpy(buf, fallback, fallback_n + 1);
      if (!strpbrk(buf, ".eE"))
        fallback_n += snprintf(buf + fallback_n, sizeof(buf) - fallback_n, ".0");
      set_rendered(fallback_n);
    }
  }
};

struct Plan {
  const uint32_t* ops;
  uint64_t len;
  std::vector<std::string> map_keys;
};

struct Exec {
  Decoder& d;
  const Plan& plan;
  Result& r;
  bool ok = true;

  void fail() { ok = false; d.ok = false; }

  // Execute ops starting at ip (advancing it); feed scalar into sink.
  void exec(uint64_t& ip, Sink* sink) {
    if (!ok || !d.ok || ip >= plan.len) {
      fail();
      return;
    }
    uint32_t op = plan.ops[ip++];
    switch (op) {
      case OP_NULL:
        if (sink && sink->want == W_NUM) { /* stays NaN */ }
        return;
      case OP_BOOL: {
        if (!d.need(1)) { fail(); return; }
        uint8_t b = *d.p++;
        if (sink && sink->want == W_NUM) {
          sink->num = b ? 1.0 : 0.0;
          sink->have = true;
        } else if (sink && sink->want == W_STR) {
          sink->set_rendered(
              snprintf(sink->buf, sizeof(sink->buf), b ? "True" : "False"));
        }
        return;
      }
      case OP_INT:
      case OP_LONG: {
        int64_t v = d.read_long();
        if (sink && sink->want == W_NUM) {
          sink->num = static_cast<double>(v);
          sink->have = true;
        } else if (sink && sink->want == W_STR) {
          sink->set_rendered(snprintf(sink->buf, sizeof(sink->buf), "%lld",
                                      static_cast<long long>(v)));
        }
        return;
      }
      case OP_FLOAT: {
        float v = d.read_float();
        if (sink && sink->want == W_NUM) {
          sink->num = v;
          sink->have = true;
        } else if (sink && sink->want == W_STR) {
          sink->render_double(v);
        }
        return;
      }
      case OP_DOUBLE: {
        double v = d.read_double();
        if (sink && sink->want == W_NUM) {
          sink->num = v;
          sink->have = true;
        } else if (sink && sink->want == W_STR) {
          sink->render_double(v);
        }
        return;
      }
      case OP_BYTES:
      case OP_STRING: {
        const uint8_t* s;
        int64_t n;
        if (!d.read_bytes(&s, &n)) { fail(); return; }
        if (sink && sink->want == W_STR) {
          sink->str = s;
          sink->str_len = n;
          sink->have = true;
        } else if (sink && sink->want == W_NUM && n > 0) {
          // a numeric field whose union carries a string branch (the
          // metronome label union): parse iff the whole token is a
          // number, with Python-float() parity (no hex literals)
          std::string tmp(reinterpret_cast<const char*>(s),
                          static_cast<size_t>(n));
          // float() strips surrounding whitespace
          size_t b = tmp.find_first_not_of(" \t\n\r\f\v");
          size_t e = tmp.find_last_not_of(" \t\n\r\f\v");
          if (b != std::string::npos) {
            tmp = tmp.substr(b, e - b + 1);
            if (tmp.find('x') == std::string::npos &&
                tmp.find('X') == std::string::npos) {
              char* end = nullptr;
              double v = c_strtod(tmp.c_str(), &end);
              if (end == tmp.c_str() + tmp.size()) {
                sink->num = v;
                sink->have = true;
              }
            }
          }
        }
        return;
      }
      case OP_UNION: {
        uint32_t nb = plan.ops[ip++];
        int64_t branch = d.read_long();
        if (!d.ok || branch < 0 || branch >= static_cast<int64_t>(nb)) {
          fail();
          return;
        }
        // walk to the chosen branch, exec it, then skip the rest
        for (uint32_t b = 0; b < nb; ++b) {
          uint32_t blen = plan.ops[ip++];
          if (static_cast<int64_t>(b) == branch) {
            uint64_t bip = ip;
            exec(bip, sink);
            ip += blen;
          } else {
            ip += blen;
          }
        }
        return;
      }
      case OP_RECORD: {
        uint32_t nf = plan.ops[ip++];
        for (uint32_t i = 0; i < nf && ok; ++i) exec(ip, nullptr);
        return;
      }
      case OP_ARRAY: {
        uint32_t ilen = plan.ops[ip++];
        uint64_t item_ip = ip;
        while (ok) {
          int64_t n = d.read_long();
          if (!d.ok) { fail(); return; }
          if (n == 0) break;
          if (n < 0) {
            d.read_long();  // block byte size, unused
            n = -n;
          }
          for (int64_t i = 0; i < n && ok; ++i) {
            uint64_t iip = item_ip;
            exec(iip, nullptr);
          }
        }
        ip += ilen;
        return;
      }
      case OP_MAP: {
        uint32_t vlen = plan.ops[ip++];
        uint64_t val_ip = ip;
        while (ok) {
          int64_t n = d.read_long();
          if (!d.ok) { fail(); return; }
          if (n == 0) break;
          if (n < 0) {
            d.read_long();
            n = -n;
          }
          for (int64_t i = 0; i < n && ok; ++i) {
            const uint8_t* ks;
            int64_t kn;
            if (!d.read_bytes(&ks, &kn)) { fail(); return; }
            uint64_t vip = val_ip;
            exec(vip, nullptr);
          }
        }
        ip += vlen;
        return;
      }
      case CAP_NUM: {
        uint32_t slot = plan.ops[ip++];
        Sink s;
        s.want = W_NUM;
        exec(ip, &s);
        if (!ok) return;
        r.f64[slot].push_back(s.num);
        return;
      }
      case CAP_STR: {
        uint32_t slot = plan.ops[ip++];
        Sink s;
        s.want = W_STR;
        exec(ip, &s);
        if (!ok) return;
        int32_t id = -1;
        if (s.have)
          id = r.intern.intern(reinterpret_cast<const char*>(s.str),
                               static_cast<size_t>(s.str_len));
        r.i32[slot].push_back(id);
        return;
      }
      case CAP_BAG: {
        uint32_t slot = plan.ops[ip++];
        uint32_t nf = plan.ops[ip++];
        uint64_t fields_ip = ip;
        // pre-scan field table to find the end
        uint64_t scan = ip;
        for (uint32_t i = 0; i < nf; ++i) {
          scan += 1;  // role
          uint32_t flen = plan.ops[scan];
          scan += 1 + flen;
        }
        Bag& bag = r.bags[slot];
        while (ok) {
          int64_t n = d.read_long();
          if (!d.ok) { fail(); return; }
          if (n == 0) break;
          if (n < 0) {
            d.read_long();
            n = -n;
          }
          for (int64_t i = 0; i < n && ok; ++i) {
            // one bag item: record with nf fields
            std::string key;
            bool saw_name = false;
            double value = NAN;
            uint64_t fip = fields_ip;
            for (uint32_t f = 0; f < nf && ok; ++f) {
              uint32_t role = plan.ops[fip++];
              uint32_t flen = plan.ops[fip++];
              uint64_t body = fip;
              if (role == 1 || role == 2) {
                Sink s;
                s.want = W_STR;
                exec(body, &s);
                if (role == 1) {
                  key.assign(reinterpret_cast<const char*>(s.str),
                             s.have ? static_cast<size_t>(s.str_len) : 0);
                  saw_name = true;
                } else {
                  key.push_back('\t');
                  if (s.have)
                    key.append(reinterpret_cast<const char*>(s.str),
                               static_cast<size_t>(s.str_len));
                }
              } else if (role == 3) {
                Sink s;
                s.want = W_NUM;
                exec(body, &s);
                value = s.num;
              } else {
                exec(body, nullptr);
              }
              fip += flen;
            }
            if (!ok) return;
            if (saw_name && key.find('\t') == std::string::npos)
              key.push_back('\t');  // name-only schema: key = name + TAB
            bag.key_ids.push_back(
                r.intern.intern(key.data(), key.size()));
            bag.values.push_back(value);
          }
        }
        bag.row_ptr.push_back(static_cast<int64_t>(bag.key_ids.size()));
        ip = scan;
        return;
      }
      case CAP_MAP: {
        uint32_t slot_base = plan.ops[ip++];
        uint32_t vlen = plan.ops[ip++];
        uint64_t val_ip = ip;
        size_t nk = plan.map_keys.size();
        std::vector<int32_t> found(nk, -1);
        while (ok) {
          int64_t n = d.read_long();
          if (!d.ok) { fail(); return; }
          if (n == 0) break;
          if (n < 0) {
            d.read_long();
            n = -n;
          }
          for (int64_t i = 0; i < n && ok; ++i) {
            const uint8_t* ks;
            int64_t kn;
            if (!d.read_bytes(&ks, &kn)) { fail(); return; }
            Sink s;
            s.want = W_STR;
            uint64_t vip = val_ip;
            exec(vip, &s);
            if (!ok) return;
            for (size_t k = 0; k < nk; ++k) {
              if (plan.map_keys[k].size() == static_cast<size_t>(kn) &&
                  std::memcmp(plan.map_keys[k].data(), ks,
                              static_cast<size_t>(kn)) == 0 &&
                  s.have) {
                found[k] = r.intern.intern(
                    reinterpret_cast<const char*>(s.str),
                    static_cast<size_t>(s.str_len));
              }
            }
          }
        }
        for (size_t k = 0; k < nk; ++k)
          r.i32[slot_base + k].push_back(found[k]);
        ip += vlen;
        return;
      }
      default:
        fail();
        return;
    }
  }
};

bool inflate_raw(const uint8_t* src, size_t n, std::vector<uint8_t>& out) {
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) return false;
  zs.next_in = const_cast<Bytef*>(src);
  zs.avail_in = static_cast<uInt>(n);
  out.clear();
  out.resize(n * 4 + 4096);
  size_t total = 0;
  int rc;
  do {
    if (total == out.size()) out.resize(out.size() * 2);
    zs.next_out = out.data() + total;
    zs.avail_out = static_cast<uInt>(out.size() - total);
    rc = inflate(&zs, Z_NO_FLUSH);
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      return false;
    }
    total = out.size() - zs.avail_out;
  } while (rc != Z_STREAM_END && zs.avail_in > 0);
  inflateEnd(&zs);
  out.resize(total);
  return rc == Z_STREAM_END;
}

// counts how many scalar columns a plan allocates so Result can presize
void plan_extents(const uint32_t* ops, uint64_t len, uint32_t* nf64,
                  uint32_t* ni32, uint32_t* nbags, uint32_t n_map_keys) {
  for (uint64_t i = 0; i < len; ++i) {
    switch (ops[i]) {
      case CAP_NUM:
        *nf64 = std::max(*nf64, ops[i + 1] + 1);
        break;
      case CAP_STR:
        *ni32 = std::max(*ni32, ops[i + 1] + 1);
        break;
      case CAP_BAG:
        *nbags = std::max(*nbags, ops[i + 1] + 1);
        break;
      case CAP_MAP:
        *ni32 = std::max(*ni32, ops[i + 1] + n_map_keys);
        break;
      default:
        break;
    }
  }
}

}  // namespace

extern "C" {

const char* pavro_last_error() { return g_error.c_str(); }

// Decode one container file (bytes provided by the caller via mmap/read)
// using the compiled plan. Returns a Result* or null.
void* pavro_decode(const uint8_t* data, uint64_t size, const uint32_t* plan_ops,
                   uint64_t plan_len, const char** map_keys,
                   uint32_t n_map_keys) {
  if (size < 4 || std::memcmp(data, "Obj\x01", 4) != 0) {
    g_error = "not an Avro container file";
    return nullptr;
  }
  Plan plan{plan_ops, plan_len, {}};
  for (uint32_t i = 0; i < n_map_keys; ++i) plan.map_keys.push_back(map_keys[i]);

  Decoder hd{data + 4, data + size};
  // header metadata map<string, bytes>; find avro.codec
  std::string codec = "null";
  while (true) {
    int64_t n = hd.read_long();
    if (!hd.ok) {
      g_error = "bad container header";
      return nullptr;
    }
    if (n == 0) break;
    if (n < 0) {
      hd.read_long();
      n = -n;
    }
    for (int64_t i = 0; i < n; ++i) {
      const uint8_t *ks, *vs;
      int64_t kn, vn;
      if (!hd.read_bytes(&ks, &kn) || !hd.read_bytes(&vs, &vn)) {
        g_error = "bad container header";
        return nullptr;
      }
      if (kn == 10 && std::memcmp(ks, "avro.codec", 10) == 0)
        codec.assign(reinterpret_cast<const char*>(vs),
                     static_cast<size_t>(vn));
    }
  }
  if (codec != "null" && codec != "deflate") {
    g_error = "unsupported codec: " + codec;
    return nullptr;
  }
  if (!hd.need(16)) {
    g_error = "truncated container";
    return nullptr;
  }
  const uint8_t* sync = hd.p;
  hd.p += 16;

  auto* r = new Result();
  uint32_t nf64 = 0, ni32 = 0, nbags = 0;
  plan_extents(plan_ops, plan_len, &nf64, &ni32, &nbags, n_map_keys);
  r->f64.resize(nf64);
  r->i32.resize(ni32);
  r->bags.resize(nbags);

  while (hd.p < data + size) {
    int64_t count = hd.read_long();
    int64_t bsize = hd.read_long();
    if (!hd.ok || bsize < 0 || !hd.need(static_cast<size_t>(bsize) + 16)) {
      g_error = "truncated block";
      delete r;
      return nullptr;
    }
    const uint8_t* block = hd.p;
    size_t block_len = static_cast<size_t>(bsize);
    hd.p += bsize;
    if (std::memcmp(hd.p, sync, 16) != 0) {
      g_error = "sync marker mismatch";
      delete r;
      return nullptr;
    }
    hd.p += 16;

    if (codec == "deflate") {
      if (!inflate_raw(block, block_len, r->decompressed)) {
        g_error = "deflate error";
        delete r;
        return nullptr;
      }
      block = r->decompressed.data();
      block_len = r->decompressed.size();
    }
    Decoder bd{block, block + block_len};
    for (int64_t i = 0; i < count; ++i) {
      // per-record default-fill bookkeeping: remember column lengths
      std::vector<size_t> lf(r->f64.size()), li(r->i32.size());
      for (size_t s = 0; s < r->f64.size(); ++s) lf[s] = r->f64[s].size();
      for (size_t s = 0; s < r->i32.size(); ++s) li[s] = r->i32[s].size();
      std::vector<size_t> lb(r->bags.size());
      for (size_t s = 0; s < r->bags.size(); ++s)
        lb[s] = r->bags[s].row_ptr.size();

      Exec ex{bd, plan, *r};
      uint64_t ip = 0;
      ex.exec(ip, nullptr);
      if (!ex.ok || !bd.ok) {
        g_error = "record decode error";
        delete r;
        return nullptr;
      }
      r->nrecords += 1;
      for (size_t s = 0; s < r->f64.size(); ++s)
        if (r->f64[s].size() == lf[s]) r->f64[s].push_back(NAN);
      for (size_t s = 0; s < r->i32.size(); ++s)
        if (r->i32[s].size() == li[s]) r->i32[s].push_back(-1);
      for (size_t s = 0; s < r->bags.size(); ++s)
        if (r->bags[s].row_ptr.size() == lb[s])
          r->bags[s].row_ptr.push_back(
              static_cast<int64_t>(r->bags[s].key_ids.size()));
    }
  }
  return r;
}

int64_t pavro_nrecords(void* h) { return static_cast<Result*>(h)->nrecords; }

int64_t pavro_col_f64(void* h, uint32_t slot, const double** out) {
  auto* r = static_cast<Result*>(h);
  if (slot >= r->f64.size()) return -1;
  *out = r->f64[slot].data();
  return static_cast<int64_t>(r->f64[slot].size());
}

int64_t pavro_col_i32(void* h, uint32_t slot, const int32_t** out) {
  auto* r = static_cast<Result*>(h);
  if (slot >= r->i32.size()) return -1;
  *out = r->i32[slot].data();
  return static_cast<int64_t>(r->i32[slot].size());
}

int64_t pavro_bag(void* h, uint32_t slot, const int64_t** row_ptr,
                  const int32_t** key_ids, const double** values,
                  int64_t* nnz) {
  auto* r = static_cast<Result*>(h);
  if (slot >= r->bags.size()) return -1;
  Bag& b = r->bags[slot];
  *row_ptr = b.row_ptr.data();
  *key_ids = b.key_ids.data();
  *values = b.values.data();
  *nnz = static_cast<int64_t>(b.key_ids.size());
  return static_cast<int64_t>(b.row_ptr.size());
}

int64_t pavro_strings(void* h, const char** blob, const uint64_t** offsets) {
  auto* r = static_cast<Result*>(h);
  *blob = r->intern.blob.data();
  *offsets = r->intern.offsets.data();
  return static_cast<int64_t>(r->intern.offsets.size() - 1);
}

void pavro_free(void* h) { delete static_cast<Result*>(h); }

}  // extern "C"
