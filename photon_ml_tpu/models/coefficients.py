"""Model coefficients: means + optional per-coefficient variances.

Reference: photon-ml .../model/Coefficients.scala:33 (Coefficients(means,
variancesOption)) and supervised/model/CoefficientSummary.scala.

A NamedTuple pytree: flows through jit/vmap/shard_map; a *bank* of entity
models is simply a Coefficients whose arrays carry a leading entity axis.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

Array = jnp.ndarray


class Coefficients(NamedTuple):
    means: Array  # [d] (or [entities, d] for banks)
    variances: Optional[Array] = None  # same shape as means, or None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def l2_norm(self) -> Array:
        return jnp.linalg.norm(self.means, axis=-1)

    def l1_norm(self) -> Array:
        return jnp.sum(jnp.abs(self.means), axis=-1)

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32) -> "Coefficients":
        return Coefficients(means=jnp.zeros((dim,), dtype))


class CoefficientSummary(NamedTuple):
    """Running summary of one coefficient across bootstrap replicates
    (CoefficientSummary.scala): min/max/mean/variance estimates."""

    count: Array
    mean: Array
    m2: Array  # sum of squared deviations (Welford)
    min: Array
    max: Array

    @staticmethod
    def empty(dtype=jnp.float32) -> "CoefficientSummary":
        return CoefficientSummary(
            count=jnp.zeros((), dtype),
            mean=jnp.zeros((), dtype),
            m2=jnp.zeros((), dtype),
            min=jnp.full((), jnp.inf, dtype),
            max=jnp.full((), -jnp.inf, dtype),
        )

    def accumulate(self, x: Array) -> "CoefficientSummary":
        count = self.count + 1.0
        delta = x - self.mean
        mean = self.mean + delta / count
        m2 = self.m2 + delta * (x - mean)
        return CoefficientSummary(
            count=count,
            mean=mean,
            m2=m2,
            min=jnp.minimum(self.min, x),
            max=jnp.maximum(self.max, x),
        )

    @property
    def variance(self) -> Array:
        return jnp.where(self.count > 1, self.m2 / (self.count - 1.0), 0.0)
