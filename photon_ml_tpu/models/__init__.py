"""Model classes: GLM coefficients + per-family wrappers (GAME models live
in photon_ml_tpu.game)."""

from photon_ml_tpu.models.coefficients import CoefficientSummary, Coefficients
from photon_ml_tpu.models.glm import (
    GeneralizedLinearModel,
    compute_margins,
    compute_means,
    compute_scores,
    create_model,
    linear_regression_model,
    logistic_regression_model,
    poisson_regression_model,
    smoothed_hinge_svm_model,
)

__all__ = [
    "CoefficientSummary",
    "Coefficients",
    "GeneralizedLinearModel",
    "compute_margins",
    "compute_means",
    "compute_scores",
    "create_model",
    "linear_regression_model",
    "logistic_regression_model",
    "poisson_regression_model",
    "smoothed_hinge_svm_model",
]
