"""Generalized linear model classes.

Reference: photon-ml .../supervised/model/GeneralizedLinearModel.scala
(computeScore = features.coef at :47, computeMeanFunctionWithOffset at
:56-66), supervised/classification/{LogisticRegressionModel,
SmoothedHingeLossLinearSVMModel}.scala (predictClassWithThreshold),
supervised/regression/{LinearRegressionModel,PoissonRegressionModel}.scala.

Scoring is a pure function of (coefficients, batch) so it runs inside jit
and under any sharding; the model classes are thin host-side wrappers that
carry the task type and expose the reference's API surface.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch, SparseBatch, sparse_dot
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.task import TaskType

Array = jnp.ndarray


def compute_scores(coef: Array, batch: Batch) -> Array:
    """Raw margins WITHOUT offsets: features . coef
    (GeneralizedLinearModel.computeScore)."""
    if isinstance(batch, SparseBatch):
        return sparse_dot(batch, coef)
    return batch.features @ coef


def compute_margins(coef: Array, batch: Batch) -> Array:
    """Margins including offsets: features . coef + offset."""
    return compute_scores(coef, batch) + batch.offsets


def compute_means(task: TaskType, coef: Array, batch: Batch) -> Array:
    """Mean response with offsets (computeMeanFunctionWithOffset):
    sigmoid / identity / exp / raw margin per task."""
    return loss_for_task(task).mean(compute_margins(coef, batch))


@dataclass(frozen=True)
class GeneralizedLinearModel:
    """task + coefficients; subclasses fix the task type for API parity."""

    task: TaskType
    coefficients: Coefficients

    @property
    def means(self) -> Array:
        return self.coefficients.means

    def score(self, batch: Batch) -> Array:
        return compute_scores(self.means, batch)

    def mean(self, batch: Batch) -> Array:
        return compute_means(self.task, self.means, batch)

    def update_coefficients(self, coefficients: Coefficients) -> "GeneralizedLinearModel":
        return replace(self, coefficients=coefficients)

    def predict_class(self, batch: Batch, threshold: float = 0.5) -> Array:
        """Binary 0/1 prediction (predictClassWithThreshold); only valid for
        classification tasks."""
        if not self.task.is_classification:
            raise ValueError(f"{self.task} is not a classification task")
        if self.task == TaskType.LOGISTIC_REGRESSION:
            return (self.mean(batch) > threshold).astype(jnp.float32)
        # SVM: threshold on the raw margin at 0 (probability threshold 0.5
        # maps to margin 0 for the hinge model).
        return (compute_margins(self.means, batch) > 0.0).astype(jnp.float32)


def logistic_regression_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(TaskType.LOGISTIC_REGRESSION, coefficients)


def linear_regression_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(TaskType.LINEAR_REGRESSION, coefficients)


def poisson_regression_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(TaskType.POISSON_REGRESSION, coefficients)


def smoothed_hinge_svm_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM, coefficients
    )


def create_model(task: TaskType, coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(task, coefficients)
