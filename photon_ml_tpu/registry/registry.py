"""Model registry: generation-numbered lineage with validation-gated,
crash-atomic promotion.

Production retraining (ROADMAP "Continuous retraining") republishes a
model every hour into live scoring. The failure modes that matter are
not exotic: a publisher killed mid-copy must never leave a generation
that loaders half-see; two cron ticks overlapping must not interleave
writes; a candidate that failed its validation gates must be
IMPOSSIBLE to load, not merely discouraged. The registry makes each of
those structural:

Layout (one directory per registry)::

    <root>/
        lease.json                  # single-writer lease (exclusive create)
        generations/
            g000001/
                manifest.json       # lineage manifest (see below)
                model/...           # the model artifact, verbatim
                COMMIT              # commit marker: visible iff present
            .staging-<token>/       # in-flight publish (never listed)
        refused/
            <token>/manifest.json   # gate-failed candidates (+ verdict)
        quarantine/
            g000002/...             # rolled-back generations (+ reason)

**Visibility contract.** A generation exists for loaders iff its
directory name parses, ``COMMIT`` is present, and the manifest reads
back. The publish order is: stage everything into a token-unique
``.staging-*`` dir, ``os.replace`` it to its final name, then write
``COMMIT`` atomically. A ``kill -9`` at ANY step therefore leaves
either no trace (staging dirs are invisible) or an uncommitted
directory (invisible: no ``COMMIT``) — never a partial generation.
Every step crosses the ``registry.publish`` fault seam, so the chaos
tests pin exactly that.

**Resume.** A publisher restarted after a crash finds either nothing
(stage again) or an uncommitted generation directory. If its content
signature matches the candidate being published, it is ADOPTED (only
the marker is written — the resumed publish is bitwise the
uninterrupted one); a mismatching uncommitted dir is quarantined and
the publish proceeds fresh.

**Single writer.** ``lease.json`` is taken with an exclusive create
(O_EXCL). A second concurrent publisher fails with
:class:`RegistryLeaseHeld` without having written anything. A lease
whose owner process is dead (the kill-mid-publish case) is broken and
re-taken; a live owner's lease never is.

**Manifests are timestamp-free.** Everything recorded (parent, data
ranges, content signatures, gate verdicts) is a pure function of the
publish inputs, so a resumed publish produces a bitwise-identical
generation directory — the invariant the chaos arm diffs.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import uuid
from dataclasses import dataclass, field
from hashlib import blake2b
from typing import Dict, List, Optional

from photon_ml_tpu.reliability.artifacts import atomic_write_json
from photon_ml_tpu.reliability.retry import io_call, quarantine_artifact

__all__ = [
    "PUBLISH_SEAM",
    "GenerationInfo",
    "ModelRegistry",
    "RegistryLeaseHeld",
    "RefusedCandidate",
    "content_signature",
]

logger = logging.getLogger(__name__)

PUBLISH_SEAM = "registry.publish"

MANIFEST = "manifest.json"
COMMIT = "COMMIT"
MODEL_SUBDIR = "model"
LEASE = "lease.json"
GEN_PREFIX = "g"
GEN_DIGITS = 6


def _flight(kind: str, **fields) -> None:
    """Publication-protocol transitions land in the process flight
    recorder (obs/): lease acquire/release, commit, refusal,
    quarantine — the ordered sequence a kill-mid-publish post-mortem
    reads back."""
    from photon_ml_tpu.obs.flight_recorder import flight_recorder

    flight_recorder().record(kind, **fields)


class RegistryLeaseHeld(RuntimeError):
    """A live publisher holds the registry lease: this publisher loses
    cleanly, having written nothing."""

    def __init__(self, holder: Dict[str, object]):
        super().__init__(
            f"registry lease held by pid {holder.get('pid')} "
            f"on {holder.get('host')} (token {holder.get('token')})"
        )
        self.holder = holder


class RefusedCandidate(RuntimeError):
    """Publish refused by a failed validation gate: the named terminal
    verdict is recorded under ``refused/`` and the candidate is never
    visible to loaders."""

    def __init__(self, verdict: str, refused_dir: str):
        super().__init__(
            f"candidate refused by validation gate {verdict}; manifest "
            f"recorded at {refused_dir}"
        )
        self.verdict = verdict
        self.refused_dir = refused_dir


def content_signature(model_dir: str) -> str:
    """Deterministic digest of a model artifact: blake2b over the sorted
    relative paths and the full bytes of every file. Two directories
    compare equal iff they are bitwise-equal trees — the adopt-or-
    quarantine decision on crash resume, and the lineage record that
    ties a generation to its exact artifact."""
    h = blake2b(digest_size=16)
    for root, dirs, files in sorted(os.walk(model_dir)):
        dirs.sort()
        for name in sorted(files):
            path = os.path.join(root, name)
            rel = os.path.relpath(path, model_dir)
            h.update(rel.encode("utf-8"))
            h.update(b"\0")
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            h.update(b"\0")
    return h.hexdigest()


def _gen_name(generation: int) -> str:
    return f"{GEN_PREFIX}{generation:0{GEN_DIGITS}d}"


def _parse_gen(name: str) -> Optional[int]:
    if not name.startswith(GEN_PREFIX):
        return None
    digits = name[len(GEN_PREFIX):]
    if not digits.isdigit():
        return None
    return int(digits)


@dataclass
class GenerationInfo:
    """One committed generation as loaders see it."""

    generation: int
    path: str          # the generation directory
    model_dir: str     # the model artifact inside it
    manifest: Dict[str, object] = field(default_factory=dict)

    @property
    def parent(self) -> Optional[int]:
        p = self.manifest.get("parent")
        return int(p) if p is not None else None

    @property
    def signature(self) -> str:
        return str(self.manifest.get("signature", ""))

    @property
    def gate_verdict(self) -> str:
        gates = self.manifest.get("gates") or {}
        return str(gates.get("verdict", "UNGATED"))


class _Lease:
    """Exclusive-create writer lease with dead-owner takeover."""

    def __init__(self, root: str):
        self.path = os.path.join(root, LEASE)
        self.token = uuid.uuid4().hex
        self.held = False

    @staticmethod
    def _owner_alive(holder: Dict[str, object]) -> bool:
        import socket

        if str(holder.get("host")) != socket.gethostname():
            # cross-host liveness is unknowable from here: treat the
            # lease as live (a foreign publisher loses rather than two
            # hosts interleaving writes)
            return True
        try:
            pid = int(holder.get("pid", -1))
        except (TypeError, ValueError):
            return False
        if pid <= 0:
            return False
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True
        return True

    def _try_create(self) -> bool:  # photon: entropy(lease identity payload; pid+host name the holder, uniqueness is the point)
        import socket

        payload = json.dumps({
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "token": self.token,
        }).encode("utf-8")
        try:
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        try:
            os.write(fd, payload)
        finally:
            os.close(fd)
        return True

    def acquire(self) -> None:
        def _acquire():
            if self._try_create():
                return
            try:
                with open(self.path) as f:
                    holder = json.load(f)
            except (OSError, ValueError):
                # torn lease file (killed mid-write): the owner is gone
                # by construction — break it
                holder = {}
            if holder and self._owner_alive(holder):
                raise RegistryLeaseHeld(holder)
            # dead owner (kill-mid-publish): break the lease and retake.
            # The unlink+create race between two breakers resolves to
            # exactly one winner via O_EXCL.
            try:
                os.unlink(self.path)
            except FileNotFoundError:
                pass
            if not self._try_create():
                with open(self.path) as f:
                    raise RegistryLeaseHeld(json.load(f))

        io_call(PUBLISH_SEAM, _acquire, detail=self.path)
        self.held = True
        _flight("registry.lease", action="acquire", path=self.path)

    def release(self) -> None:
        if not self.held:
            return
        self.held = False

        def _release():
            try:
                with open(self.path) as f:
                    holder = json.load(f)
            except (OSError, ValueError):
                return
            if holder.get("token") == self.token:
                try:
                    os.unlink(self.path)
                except FileNotFoundError:
                    pass

        io_call(PUBLISH_SEAM, _release, detail=self.path)
        _flight("registry.lease", action="release", path=self.path)


class ModelRegistry:
    """The registry over one root directory. Loaders (`latest`,
    `list_generations`, `generation`) need no lease and see only
    committed generations; `publish`/`quarantine_generation`/`gc` are
    writer operations behind the single-writer lease."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.generations_dir = os.path.join(self.root, "generations")
        self.refused_dir = os.path.join(self.root, "refused")
        self.quarantine_dir = os.path.join(self.root, "quarantine")

    # -- loader side ---------------------------------------------------------

    def _read_generation(self, name: str) -> Optional[GenerationInfo]:
        gen = _parse_gen(name)
        if gen is None:
            return None
        path = os.path.join(self.generations_dir, name)
        if not os.path.isfile(os.path.join(path, COMMIT)):
            return None  # uncommitted: invisible by contract
        try:
            with open(os.path.join(path, MANIFEST)) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None  # unreadable manifest: not loadable
        return GenerationInfo(
            generation=gen,
            path=path,
            model_dir=os.path.join(path, MODEL_SUBDIR),
            manifest=manifest,
        )

    def list_generations(self) -> List[GenerationInfo]:
        """Committed generations, ascending. Staging dirs, uncommitted
        dirs, refused candidates and quarantined generations are all
        invisible here — this IS the loader's view."""
        if not os.path.isdir(self.generations_dir):
            return []
        out = []
        for name in sorted(os.listdir(self.generations_dir)):
            info = self._read_generation(name)
            if info is not None:
                out.append(info)
        return out

    def latest(self) -> Optional[GenerationInfo]:
        gens = self.list_generations()
        return gens[-1] if gens else None

    def generation(self, generation: int) -> Optional[GenerationInfo]:
        return self._read_generation(_gen_name(generation))

    def lineage(self, generation: Optional[int] = None) -> List[int]:
        """Parent chain of ``generation`` (default: latest), newest
        first, following manifest ``parent`` pointers through committed
        generations."""
        info = (
            self.latest() if generation is None
            else self.generation(generation)
        )
        chain: List[int] = []
        seen = set()
        while info is not None and info.generation not in seen:
            chain.append(info.generation)
            seen.add(info.generation)
            if info.parent is None:
                break
            info = self.generation(info.parent)
        return chain

    # -- writer side ---------------------------------------------------------

    def _ensure_layout(self) -> None:
        for d in (
            self.root, self.generations_dir, self.refused_dir,
            self.quarantine_dir,
        ):
            os.makedirs(d, exist_ok=True)

    def _uncommitted(self) -> List[str]:
        if not os.path.isdir(self.generations_dir):
            return []
        out = []
        for name in sorted(os.listdir(self.generations_dir)):
            if _parse_gen(name) is None:
                continue
            path = os.path.join(self.generations_dir, name)
            if not os.path.isfile(os.path.join(path, COMMIT)):
                out.append(path)
        return out

    def _next_generation(self) -> int:
        best = 0
        if os.path.isdir(self.generations_dir):
            for name in os.listdir(self.generations_dir):
                gen = _parse_gen(name)
                if gen is not None:
                    best = max(best, gen)
        if os.path.isdir(self.quarantine_dir):
            # a quarantined generation's number is burned: reusing it
            # would let a watcher confuse the replacement for the bad one
            for name in os.listdir(self.quarantine_dir):
                gen = _parse_gen(name.split(".")[0])
                if gen is not None:
                    best = max(best, gen)
        return best + 1

    def publish(
        self,
        model_dir: str,
        *,
        parent: Optional[int] = None,
        data_ranges: Optional[Dict[str, object]] = None,
        gate_report: Optional[Dict[str, object]] = None,
        extra: Optional[Dict[str, object]] = None,
    ) -> GenerationInfo:
        """Publish ``model_dir`` as the next generation.

        A ``gate_report`` with ``verdict != "PASS"`` records the named
        terminal verdict under ``refused/`` and raises
        :class:`RefusedCandidate` — the candidate directory never
        enters ``generations/``. Otherwise the candidate stages,
        renames, and commits, each step behind the
        ``registry.publish`` seam (see the module docstring for the
        crash contract). Returns the committed GenerationInfo.
        """
        self._ensure_layout()
        if not os.path.isdir(model_dir):
            raise ValueError(f"model directory {model_dir} does not exist")
        lease = _Lease(self.root)
        lease.acquire()
        try:
            signature = content_signature(model_dir)
            if gate_report is not None and gate_report.get("verdict") != "PASS":
                return self._refuse(
                    signature, parent, data_ranges, gate_report, extra
                )

            # idempotent republish: a publisher killed AFTER its commit
            # (before lease release) reruns the same command — the
            # already-committed identical candidate IS the publish
            latest = self.latest()
            if latest is not None and latest.signature == signature:
                return latest

            # crash resume: an uncommitted generation whose signature
            # matches this candidate is adopted (commit only — bitwise
            # the uninterrupted publish); a mismatch is quarantined
            adopt: Optional[str] = None
            for path in self._uncommitted():
                try:
                    with open(os.path.join(path, MANIFEST)) as f:
                        m = json.load(f)
                except (OSError, ValueError):
                    m = {}
                if m.get("signature") == signature and adopt is None:
                    adopt = path
                else:
                    io_call(
                        PUBLISH_SEAM, quarantine_artifact, path,
                        PUBLISH_SEAM, detail=path,
                    )
            if adopt is not None:
                gen = _parse_gen(os.path.basename(adopt))
                self._commit(adopt, gen, signature)
                return self._read_generation(os.path.basename(adopt))

            gen = self._next_generation()
            manifest = {
                "generation": gen,
                "parent": parent,
                "signature": signature,
                "data_ranges": data_ranges or {},
                "gates": gate_report or {"verdict": "UNGATED"},
                **(extra or {}),
            }
            staging = os.path.join(
                self.generations_dir, f".staging-{lease.token}"
            )

            def _stage():
                if os.path.isdir(staging):
                    shutil.rmtree(staging)
                os.makedirs(staging)
                shutil.copytree(
                    model_dir, os.path.join(staging, MODEL_SUBDIR)
                )
                atomic_write_json(os.path.join(staging, MANIFEST), manifest)

            io_call(PUBLISH_SEAM, _stage, detail=staging)
            final = os.path.join(self.generations_dir, _gen_name(gen))

            def _rename():
                if os.path.isdir(final):
                    # a racing/crashed publisher left this name behind
                    # uncommitted with a DIFFERENT signature (the
                    # matching case was adopted above): quarantine it
                    quarantine_artifact(final, PUBLISH_SEAM)
                os.replace(staging, final)

            io_call(PUBLISH_SEAM, _rename, detail=final)
            self._commit(final, gen, signature)
            return self._read_generation(_gen_name(gen))
        finally:
            lease.release()

    def _commit(self, path: str, generation: int, signature: str) -> None:
        """The visibility flip: COMMIT lands atomically, after which —
        and only after which — loaders list the generation."""
        io_call(
            PUBLISH_SEAM,
            atomic_write_json,
            os.path.join(path, COMMIT),
            {"generation": generation, "signature": signature},
            detail=os.path.join(path, COMMIT),
        )
        _flight(
            "registry.publish", generation=generation, signature=signature
        )

    def _refuse(
        self, signature, parent, data_ranges, gate_report, extra
    ) -> GenerationInfo:
        verdict = str(gate_report.get("verdict"))
        refused = os.path.join(self.refused_dir, signature)
        manifest = {
            "signature": signature,
            "parent": parent,
            "data_ranges": data_ranges or {},
            "gates": gate_report,
            **(extra or {}),
        }

        def _record():
            os.makedirs(refused, exist_ok=True)
            atomic_write_json(os.path.join(refused, MANIFEST), manifest)

        io_call(PUBLISH_SEAM, _record, detail=refused)
        _flight("registry.refuse", verdict=verdict, signature=signature)
        raise RefusedCandidate(verdict, refused)

    def refused_candidates(self) -> List[Dict[str, object]]:
        """Refusal manifests (debugging/audit; never loadable models)."""
        if not os.path.isdir(self.refused_dir):
            return []
        out = []
        for name in sorted(os.listdir(self.refused_dir)):
            try:
                with open(
                    os.path.join(self.refused_dir, name, MANIFEST)
                ) as f:
                    out.append(json.load(f))
            except (OSError, ValueError) as e:
                logger.warning("unreadable refusal manifest %s: %s", name, e)
        return out

    def quarantine_generation(
        self, generation: int, *, reason: str = ""
    ) -> Optional[str]:
        """Auto-rollback's registry half: move a committed generation to
        ``quarantine/`` so loaders (and the watcher) stop seeing it, and
        record why. Returns the quarantine path (None if the generation
        was not committed)."""
        info = self.generation(generation)
        if info is None:
            return None
        self._ensure_layout()
        dst = os.path.join(self.quarantine_dir, _gen_name(generation))
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(
                self.quarantine_dir, f"{_gen_name(generation)}.{n}"
            )

        def _move():
            os.replace(info.path, dst)
            atomic_write_json(
                os.path.join(dst, "quarantine.json"),
                {"generation": generation, "reason": reason},
            )

        io_call(PUBLISH_SEAM, _move, detail=dst)
        _flight(
            "registry.quarantine", generation=generation, reason=reason
        )
        return dst

    def gc(self, *, keep: int = 5) -> List[int]:
        """Retention: drop committed generations beyond the newest
        ``keep``, EXCEPT any generation still referenced as a parent by
        a retained one (warm-start lineage must stay loadable). Orphaned
        staging dirs are swept too. Returns the removed generation
        numbers."""
        if keep < 1:
            raise ValueError(f"gc keep must be >= 1, got {keep}")
        gens = self.list_generations()
        retained = gens[-keep:]
        referenced = {
            info.parent for info in retained if info.parent is not None
        }
        removed: List[int] = []
        for info in gens[:-keep] if len(gens) > keep else []:
            if info.generation in referenced:
                continue

            def _rm(path=info.path):
                shutil.rmtree(path)

            io_call(PUBLISH_SEAM, _rm, detail=info.path)
            removed.append(info.generation)
        # orphaned staging dirs (crashed publishers) are invisible but
        # not free: sweep any not owned by a live lease holder
        if os.path.isdir(self.generations_dir):
            lease_token = None
            try:
                with open(os.path.join(self.root, LEASE)) as f:
                    holder = json.load(f)
                if _Lease._owner_alive(holder):
                    lease_token = holder.get("token")
            except (OSError, ValueError) as e:
                logger.debug("no live lease during gc sweep: %s", e)
            for name in os.listdir(self.generations_dir):
                if not name.startswith(".staging-"):
                    continue
                if lease_token and name == f".staging-{lease_token}":
                    continue
                shutil.rmtree(
                    os.path.join(self.generations_dir, name),
                    ignore_errors=True,
                )
        return removed
