"""Drift-safe warm starts: align a parent generation's coefficients to
a retrain's (possibly drifted) feature/entity space.

An hourly retrain's vocabulary is ALMOST the parent's: a few new terms
appear (no coefficient yet), a few die (their coefficients must not
leak into other slots), entities churn. The alignment rules, each
explicit and accounted in a :class:`DriftReport`:

- **kept** terms copy their parent value into the new index — by KEY,
  never by position (indices reshuffle whenever the sorted vocabulary
  changes).
- **new** terms initialize to exactly 0.0 (the optimizer's own prior).
- **dropped** terms are discarded, counted — silently losing half a
  model to a bad index map must be visible in the report.
- **churned entities** (random effects): a new entity with no parent
  rows starts from the PRIOR MEAN — the column-mean of the parent bank
  over entities that carried the term — rather than zero, which is the
  empirical-Bayes shrinkage center the reference's random-effect prior
  encodes (SURVEY §4: per-entity models shrink toward the population).

**No-drift bitwise pin:** when the vocabulary (and entity set) are
unchanged, the aligned vector/bank is BITWISE the parent's stored
coefficients — alignment is a permutation-by-key, float values pass
through untouched. The tests pin this; it is what makes "warm-start
from the parent" a no-op rather than a perturbation when nothing
changed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional

import numpy as np

__all__ = [
    "DriftReport",
    "align_coefficients",
    "align_re_bank",
    "warm_start_game_model",
]


@dataclass
class DriftReport:
    """Accounting of one alignment: what the drift actually was."""

    kept: int = 0
    new_zero_init: int = 0
    dropped: int = 0
    kept_entities: int = 0
    churned_entities_prior_init: int = 0
    dropped_entities: int = 0
    dropped_keys_sample: List[str] = field(default_factory=list)

    _SAMPLE = 16

    def note_dropped(self, key: str) -> None:
        self.dropped += 1
        if len(self.dropped_keys_sample) < self._SAMPLE:
            self.dropped_keys_sample.append(key)

    @property
    def no_drift(self) -> bool:
        return (
            self.new_zero_init == 0
            and self.dropped == 0
            and self.churned_entities_prior_init == 0
            and self.dropped_entities == 0
        )

    def as_dict(self) -> Dict[str, object]:
        return {
            "kept": self.kept,
            "new_zero_init": self.new_zero_init,
            "dropped": self.dropped,
            "kept_entities": self.kept_entities,
            "churned_entities_prior_init": (
                self.churned_entities_prior_init
            ),
            "dropped_entities": self.dropped_entities,
            "no_drift": self.no_drift,
            "dropped_keys_sample": list(self.dropped_keys_sample),
        }

    def merge(self, other: "DriftReport") -> "DriftReport":
        self.kept += other.kept
        self.new_zero_init += other.new_zero_init
        self.dropped += other.dropped
        self.kept_entities += other.kept_entities
        self.churned_entities_prior_init += (
            other.churned_entities_prior_init
        )
        self.dropped_entities += other.dropped_entities
        for k in other.dropped_keys_sample:
            if len(self.dropped_keys_sample) < self._SAMPLE:
                self.dropped_keys_sample.append(k)
        return self


def align_coefficients(
    parent_means: Mapping[str, float],
    index_map,
    *,
    report: Optional[DriftReport] = None,
) -> np.ndarray:
    """Parent {feature key: value} -> float32 vector in the NEW index
    space. Keys absent from the new map drop (counted); new-map indices
    with no parent key zero-init (counted)."""
    report = report if report is not None else DriftReport()
    out = np.zeros((index_map.size,), np.float32)
    hit = np.zeros((index_map.size,), bool)
    for key, value in parent_means.items():
        i = index_map.get_index(key)
        if i < 0:
            report.note_dropped(key)
            continue
        out[i] = np.float32(value)
        hit[i] = True
        report.kept += 1
    report.new_zero_init += int((~hit).sum())
    return out


def align_re_bank(
    parent_per_entity: Mapping[str, Mapping[str, float]],
    entity_ids,
    projection: np.ndarray,
    index_map,
    *,
    report: Optional[DriftReport] = None,
) -> np.ndarray:
    """Parent per-entity coefficient dicts -> a [E, D] bank in the new
    random-effect dataset's LOCAL projection space.

    ``entity_ids``: the new dataset's entity order; ``projection``
    [E, D] maps local slot -> global feature id (-1 pad); ``index_map``
    is the shard's global map (key <-> global id).

    Entities present in the parent copy by key through the projection;
    churned (new) entities get the prior mean: for each feature KEY the
    mean of the parent entities' values for it (missing treated as 0 —
    the shrinkage center), counted per entity in the report. Parent
    entities absent from the new dataset drop, counted.
    """
    report = report if report is not None else DriftReport()
    E, D = projection.shape
    bank = np.zeros((E, D), np.float32)
    new_ids = list(entity_ids)
    new_set = set(new_ids)
    report.dropped_entities += sum(
        1 for e in parent_per_entity if e not in new_set
    )
    # prior mean per feature key over the parent population (float32
    # accumulation matches the bank dtype; missing-as-zero denominator
    # is the FULL parent entity count — the shrinkage-to-population
    # convention)
    prior: Dict[str, np.float32] = {}
    n_parent = len(parent_per_entity)
    if n_parent:
        sums: Dict[str, float] = {}
        for means in parent_per_entity.values():
            for key, v in means.items():
                sums[key] = sums.get(key, 0.0) + float(v)
        prior = {
            key: np.float32(total / n_parent)
            for key, total in sums.items()
        }
    # key per (entity slot): resolve via the index map's reverse lookup
    for e, raw_id in enumerate(new_ids):
        means = parent_per_entity.get(raw_id)
        churned = means is None
        source = prior if churned else means
        if churned:
            if n_parent:
                report.churned_entities_prior_init += 1
        else:
            report.kept_entities += 1
        if not source:
            continue
        for local in range(D):
            g = int(projection[e, local])
            if g < 0:
                continue
            key = index_map.get_feature_name(g)
            if key is None:
                continue
            v = source.get(key)
            if v is not None:
                bank[e, local] = np.float32(v)
                if not churned:
                    report.kept += 1
        if not churned:
            # terms the parent entity carried that the new projection
            # has no slot for are dropped coefficients
            slots = {
                index_map.get_feature_name(int(g))
                for g in projection[e]
                if int(g) >= 0
            }
            for key in means:
                if key not in slots:
                    report.note_dropped(key)
    return bank


def warm_start_game_model(
    loaded,
    dataset,
    re_datasets: Mapping[str, object],
    task,
    *,
    coordinate_names=None,
):
    """Build the initial :class:`GameModel` for a GAME retrain from a
    parent generation's loaded artifact (``game.model_io
    .LoadedGameModel``), aligned to the NEW dataset's feature/entity
    spaces. Coordinates the parent does not carry fall back to the
    coordinate's own ``initialize_model`` (by being absent here —
    CoordinateDescent.run treats missing names exactly so). Returns
    ``(GameModel, {coordinate: DriftReport})``.
    """
    import jax.numpy as jnp

    from photon_ml_tpu.game.model import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.glm import create_model

    reports: Dict[str, DriftReport] = {}
    models = {}
    wanted = set(coordinate_names) if coordinate_names is not None else None
    for name, (shard_id, means) in loaded.fixed_effects.items():
        if wanted is not None and name not in wanted:
            continue
        if shard_id not in dataset.shards:
            continue
        report = DriftReport()
        vec = align_coefficients(
            means, dataset.shards[shard_id].index_map, report=report
        )
        models[name] = FixedEffectModel(
            model=create_model(task, Coefficients(jnp.asarray(vec))),
            feature_shard_id=shard_id,
        )
        reports[name] = report
    for name, (re_type, shard_id, per_entity) in (
        loaded.random_effects.items()
    ):
        if wanted is not None and name not in wanted:
            continue
        red = re_datasets.get(name)
        if red is None or shard_id not in dataset.shards:
            continue
        report = DriftReport()
        bank = align_re_bank(
            per_entity,
            dataset.entity_indexes[re_type].ids,
            np.asarray(red.projection),
            dataset.shards[shard_id].index_map,
            report=report,
        )
        models[name] = RandomEffectModel(
            bank=jnp.asarray(bank),
            re_dataset=red,
            random_effect_type=re_type,
            feature_shard_id=shard_id,
        )
        reports[name] = report
    return GameModel(models, task), reports
