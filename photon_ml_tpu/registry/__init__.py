"""Model registry: validation-gated continuous retraining.

- :mod:`registry.registry` — generation-numbered lineage store with
  crash-atomic publish (stage -> rename -> commit marker), a
  single-writer lease, refusal records for gate-failed candidates,
  quarantine for rolled-back generations, and retention GC.
- :mod:`registry.stats_cache` — append-only per-partition scan/stats
  cache: incremental retrains re-read only NEW partitions (counted).
- :mod:`registry.warm_start` — drift-safe alignment of a parent
  generation's coefficients to a retrain's feature/entity spaces
  (bitwise pass-through when nothing drifted).
- :mod:`registry.gates` — candidate-vs-parent promotion gates over a
  streamed holdout; one named terminal verdict per publish attempt.
- :mod:`registry.watcher` — serving-side promotion + auto-rollback.
"""

from photon_ml_tpu.registry.gates import (
    GateConfig,
    GateReport,
    coef_norm_gate,
    evaluate_gates,
)
from photon_ml_tpu.registry.registry import (
    PUBLISH_SEAM,
    GenerationInfo,
    ModelRegistry,
    RefusedCandidate,
    RegistryLeaseHeld,
    content_signature,
)
from photon_ml_tpu.registry.stats_cache import (
    STATS_CACHE_SEAM,
    PartitionStatsCache,
    ScanCacheStats,
    cached_scan_stream,
    cached_scan_stream_with_summary,
)
from photon_ml_tpu.registry.warm_start import (
    DriftReport,
    align_coefficients,
    align_re_bank,
    warm_start_game_model,
)
from photon_ml_tpu.registry.watcher import (
    HealthWindow,
    RegistryWatcher,
    RollbackPolicy,
)

__all__ = [
    "PUBLISH_SEAM",
    "STATS_CACHE_SEAM",
    "GenerationInfo",
    "ModelRegistry",
    "RefusedCandidate",
    "RegistryLeaseHeld",
    "content_signature",
    "PartitionStatsCache",
    "ScanCacheStats",
    "cached_scan_stream",
    "cached_scan_stream_with_summary",
    "DriftReport",
    "align_coefficients",
    "align_re_bank",
    "warm_start_game_model",
    "GateConfig",
    "GateReport",
    "coef_norm_gate",
    "evaluate_gates",
    "HealthWindow",
    "RegistryWatcher",
    "RollbackPolicy",
]
