"""Registry watcher: promote newly published generations into a live
scorer, and ROLL BACK automatically when the swap makes serving worse.

The serving half of continuous retraining: the trainer publishes into
the registry (validation-gated), and this watcher — a thread inside the
serving driver — polls the loader view, hot-swaps a newly committed
generation through the existing staged/donated swap machinery, then
watches the post-swap health window. Health is judged on what the
service itself already measures: the fraction of recent completions
that came back degraded (FE-only after RE quarantine / row-resolution
failures), shed, or errored. If the post-swap window regresses past the
policy bound, the watcher flips BACK to the parent generation —
reloaded from the registry artifact, so the restored scores are
bitwise the parent's — and quarantines the bad generation in the
registry so no watcher (this one or a peer's) promotes it again.

The watcher never blocks the request path: swaps happen on the watcher
thread through ``ServingModel.stage_and_swap`` (all slow work off the
dispatch lock), and health observations are lock-light counters fed
from the completion callback.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from photon_ml_tpu.obs.flight_recorder import flight_recorder
from photon_ml_tpu.registry.registry import GenerationInfo, ModelRegistry

__all__ = ["RollbackPolicy", "HealthWindow", "RegistryWatcher"]


@dataclass(frozen=True)
class RollbackPolicy:
    """When does a swap count as a regression?

    Judged over a sliding window of the most recent ``window``
    completions, only once ``min_requests`` post-swap completions
    exist (a 1-request window would roll back on any single shed).
    ``max_unhealthy_rate`` is an absolute bound on
    (degraded + shed + errors) / window — the degraded path is the
    signature of a generation whose RE bank cannot resolve live
    traffic (the exact failure entity churn + a bad publish produces).
    """

    window: int = 64
    min_requests: int = 16
    max_unhealthy_rate: float = 0.5

    def as_dict(self) -> Dict[str, object]:
        return {
            "window": self.window,
            "min_requests": self.min_requests,
            "max_unhealthy_rate": self.max_unhealthy_rate,
        }


class HealthWindow:
    """Ring buffer of request outcomes: 0 healthy, 1 unhealthy."""

    def __init__(self, size: int):
        self._size = max(int(size), 1)
        self._buf: List[int] = []
        self._pos = 0
        self._lock = threading.Lock()

    def observe(self, unhealthy: bool) -> None:
        with self._lock:
            v = 1 if unhealthy else 0
            if len(self._buf) < self._size:
                self._buf.append(v)
            else:
                self._buf[self._pos] = v
                self._pos = (self._pos + 1) % self._size

    def snapshot(self):
        with self._lock:
            n = len(self._buf)
            return n, (sum(self._buf) / n if n else 0.0)

    def reset(self) -> None:
        with self._lock:
            self._buf = []
            self._pos = 0


@dataclass
class _SwapRecord:
    registry_generation: int
    parent: Optional[int]
    action: str  # "swap" | "rollback"
    ok: bool
    error: str = ""


class RegistryWatcher:
    """Polls ``registry`` and drives ``serving_model`` swaps.

    ``serving_model`` needs ``stage_and_swap(model_dir, **kw)`` (the
    ServingModel protocol); swap kwargs (entity padding, model id) ride
    through ``swap_kwargs``. Health observations arrive via
    :meth:`observe_outcome` from the driver's completion hook.
    """

    def __init__(
        self,
        registry: ModelRegistry,
        serving_model,
        *,
        poll_s: float = 2.0,
        policy: Optional[RollbackPolicy] = None,
        auto_rollback: bool = True,
        swap_kwargs: Optional[Dict[str, object]] = None,
        logger=None,
        initial_generation: Optional[GenerationInfo] = None,
        burn_gate: Optional[Callable[[], bool]] = None,
    ):
        self.registry = registry
        self.serving_model = serving_model
        self.poll_s = max(float(poll_s), 0.05)
        self.policy = policy or RollbackPolicy()
        self.auto_rollback = auto_rollback
        # SLO integration (obs/slo.py): when set, the post-swap health
        # judgment consumes BURN-RATE state (typically
        # SLOEngine.any_alert_active — both windows past threshold)
        # instead of the window's raw error fraction. The window still
        # gates on min_requests so a swap is never judged on no data.
        self.burn_gate = burn_gate
        self.swap_kwargs = dict(swap_kwargs or {})
        self.logger = logger
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # guards the lineage/health FLAGS shared between the watcher
        # thread, the response-path health feed (observe_outcome runs
        # on frontend connection threads) and the operator rollback op
        self._lock = threading.Lock()
        # serializes whole promote/rollback protocols (read lineage ->
        # stage -> flip -> write lineage): the operator rollback op
        # arrives on a connection thread while the watcher thread may
        # be mid-promote — without this, both read the same parent and
        # the loser publishes stale lineage (and two staged swaps race
        # at the serving model)
        self._swap_serial = threading.Lock()
        self._window = HealthWindow(self.policy.window)
        # lineage state: which registry generation is live, its parent
        self._live: Optional[GenerationInfo] = initial_generation
        self._last_swap: Optional[_SwapRecord] = None
        self._watching_swap = False
        self._rollback_wanted = False
        self.history: List[_SwapRecord] = []

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "RegistryWatcher":
        self._thread = threading.Thread(
            target=self._loop, name="photon-registry-watcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def poke(self) -> None:
        """Force an immediate poll (tests / operator ops)."""
        self._wake.set()

    # -- health feed ---------------------------------------------------------

    def observe_outcome(
        self, *, degraded: bool = False, failed: bool = False
    ) -> None:
        """One completed request's health, fed from the driver's
        completion path. Only consulted while a post-swap watch is
        active — steady-state traffic costs one locked flag read."""
        with self._lock:
            if not self._watching_swap:
                return
        self._window.observe(degraded or failed)
        n, rate = self._window.snapshot()
        if self.burn_gate is not None:
            # burn-rate mode: the SLO engine's multi-window verdict
            # replaces the raw window fraction — min_requests still
            # applies, so the first post-swap completion cannot roll
            # back on a stale pre-swap burn
            try:
                unhealthy = bool(self.burn_gate())
            except Exception:
                unhealthy = False  # a wedged gate must not roll back
        else:
            unhealthy = rate > self.policy.max_unhealthy_rate
        if n >= self.policy.min_requests and unhealthy:
            # flag for the watcher thread; the completion callback must
            # never run a swap itself (it holds response-path time).
            # Re-check the watch under the lock: a rollback that just
            # completed cleared it, and re-arming the flag here would
            # roll back AGAIN off the bad generation's stale window.
            with self._lock:
                if self._watching_swap:
                    self._rollback_wanted = True
            self._wake.set()

    # -- status --------------------------------------------------------------

    def lineage(self) -> Dict[str, object]:
        """The frontend-status payload: live registry generation, its
        parent chain, and the last swap/rollback outcome."""
        with self._lock:
            live = self._live
            last = self._last_swap
        out: Dict[str, object] = {
            "registry_path": self.registry.root,
            "registry_generation": (
                live.generation if live is not None else None
            ),
            "parent": live.parent if live is not None else None,
            "lineage": (
                self.registry.lineage(live.generation)
                if live is not None else []
            ),
        }
        if last is not None:
            out["last_swap"] = {
                "action": last.action,
                "registry_generation": last.registry_generation,
                "ok": last.ok,
                "error": last.error,
            }
        n, rate = self._window.snapshot()
        with self._lock:
            watching = self._watching_swap
        out["post_swap_window"] = {
            "observed": n,
            "unhealthy_rate": round(rate, 4),
            "watching": watching,
        }
        return out

    # -- the loop ------------------------------------------------------------

    def _log(self, msg: str, *args) -> None:
        if self.logger is not None:
            self.logger.info(msg, *args)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.poll_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                with self._lock:
                    wanted = self._rollback_wanted
                    self._rollback_wanted = False
                if wanted:
                    self.rollback(reason="post-swap health regression")
                    continue
                self._check_registry()
            except Exception as e:  # the watcher must outlive one bad poll
                self._log("registry watcher poll failed: %s", e)

    def _check_registry(self) -> None:
        latest = self.registry.latest()
        if latest is None:
            return
        with self._lock:
            live_gen = (
                self._live.generation if self._live is not None else None
            )
        if live_gen is not None and latest.generation <= live_gen:
            return
        self._promote(latest)

    def _promote(self, info: GenerationInfo) -> None:
        self._log(
            "registry: promoting generation %d (parent %s)",
            info.generation, info.parent,
        )
        # _swap_serial held across the WHOLE protocol (stage -> flip ->
        # lineage write): the operator rollback op runs on a connection
        # thread and must not interleave with a promote — and holding
        # one outer lock across both _lock sections is what makes the
        # read-then-write below atomic (PL010)
        with self._swap_serial:
            res = self.serving_model.stage_and_swap(
                info.model_dir, **self.swap_kwargs
            )
            rec = _SwapRecord(
                registry_generation=info.generation,
                parent=info.parent,
                action="swap",
                ok=res.ok,
                error=res.error,
            )
            if res.ok and self.auto_rollback:
                self._window.reset()
            with self._lock:
                self.history.append(rec)
                self._last_swap = rec
                if res.ok:
                    self._live = info
                    if self.auto_rollback:
                        # arm AFTER the reset: a straggler completion
                        # between reset and arming is ignored, never
                        # counted against the new generation
                        self._watching_swap = True
                        self._rollback_wanted = False
        self._log(
            "registry swap -> generation %d: ok=%s%s",
            info.generation, res.ok,
            f" error={res.error}" if res.error else "",
        )
        flight_recorder().record(
            "watcher.promote", registry_generation=info.generation,
            parent=info.parent, ok=res.ok, error=res.error,
        )

    def rollback(self, *, reason: str = "operator request") -> bool:
        """Flip back to the live generation's parent (reloaded from the
        registry artifact — bitwise the parent's scores) and quarantine
        the bad generation in the registry. Operator op and the
        auto-rollback trigger both land here — serialized against
        promotes AND against each other, with the health watch disarmed
        (and any pending trigger cleared) BEFORE the flip so a stale
        window from the bad generation can never roll back twice."""
        with self._swap_serial:
            with self._lock:
                live = self._live
                self._watching_swap = False
                self._rollback_wanted = False
            if live is None or live.parent is None:
                self._log("rollback requested but no parent generation")
                return False
            parent = self.registry.generation(live.parent)
            if parent is None:
                self._log(
                    "rollback target generation %d is not loadable",
                    live.parent,
                )
                return False
            self._log(
                "ROLLING BACK generation %d -> parent %d (%s)",
                live.generation, parent.generation, reason,
            )
            res = self.serving_model.stage_and_swap(
                parent.model_dir, **self.swap_kwargs
            )
            rec = _SwapRecord(
                registry_generation=parent.generation,
                parent=parent.parent,
                action="rollback",
                ok=res.ok,
                error=res.error,
            )
            with self._lock:
                self.history.append(rec)
                self._last_swap = rec
                if res.ok:
                    self._live = parent
            if res.ok:
                q = self.registry.quarantine_generation(
                    live.generation, reason=reason
                )
                self._log(
                    "generation %d quarantined in the registry (%s)",
                    live.generation, q,
                )
            # the rollback is the flight recorder's marquee event: the
            # record (kind "watcher.*") also triggers the armed
            # auto-dump, so the ring is on disk the moment the service
            # rolled back — not only at clean exit
            flight_recorder().record(
                "watcher.rollback",
                from_generation=live.generation,
                to_generation=parent.generation,
                reason=reason,
                ok=res.ok,
            )
            return res.ok
