"""Append-only per-partition scan/stats cache: incremental retraining's
answer to "don't re-read 30 days of data to learn about one new hour".

The driver's preprocess stage scans EVERY partition file on EVERY run —
vocabulary, row count, max per-row nnz, and (fused) the colStats
moments. For an hourly retrain over appended data that cost is O(total
history) when the new information is O(one partition). This module
applies the content-addressed schedule-cache pattern
(`ops/schedule_cache.py`) to the DATA artifacts instead: one cache
entry per partition file, keyed by a spot digest of the file's bytes,
holding exactly the per-partition reductions the scan needs. A cached
scan then touches only partitions without a valid entry — which for an
append-only directory is precisely the new ones. The ``scanned`` /
``cached`` counters (and the ``registry.stats_cache`` fault seam) make
"touches only new partitions" a COUNTED claim the bench gates and the
tier-1 tests assert, not a hope.

Exactness: the per-partition reductions are integers (rows, max live
nnz), a key SET, and float64 moment partials.

- ``index_map``/``StreamStats`` from a cached scan are EXACTLY the
  uncached ones: key sets union losslessly and ``IndexMap.build`` sorts
  (order-independent by construction); rows add; max-nnz maxes.
- The summary path merges per-partition float64 moment partials in
  sorted-file order. Against the fused single-pass scan this regroups
  the additions (per-file subtotals first), so moments can differ by
  f64 rounding — the same class of noise the multi-host all-reduce
  already accepts. The bitwise-pinned retrain invariants (no-drift
  alignment, publish parity) never flow through the summary.

Corruption protocol: an entry that fails to decode (or an injected
CORRUPT at the seam) is quarantined to ``*.corrupt`` via the
reliability layer — accounted, never silently trusted — and the
partition is rescanned.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from hashlib import blake2b
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.reliability.faults import InjectedCorruption
from photon_ml_tpu.reliability.retry import (
    SeamFailure,
    io_call,
    quarantine_artifact,
)

__all__ = [
    "STATS_CACHE_SEAM",
    "STATS_CACHE_VERSION",
    "ScanCacheStats",
    "PartitionStatsCache",
    "cached_scan_stream",
    "cached_scan_stream_with_summary",
]

STATS_CACHE_SEAM = "registry.stats_cache"

# Bump when the entry layout or the per-partition reduction semantics
# change: versioned keys simply miss and rescan.
STATS_CACHE_VERSION = 1

_SPOT_BYTES = 64 * 1024


def _partition_key(path: str) -> str:
    """Content key of one partition file: size + first/last 64 KiB.
    Append-only directories never rewrite a partition in place, so a
    same-key file is the same partition; a rewritten file (size or edge
    bytes changed) misses and rescans."""
    st = os.stat(path)
    h = blake2b(digest_size=16)
    h.update(str(STATS_CACHE_VERSION).encode())
    h.update(b"\0")
    h.update(str(st.st_size).encode())
    h.update(b"\0")
    with open(path, "rb") as f:
        h.update(f.read(_SPOT_BYTES))
        if st.st_size > _SPOT_BYTES:
            f.seek(max(st.st_size - _SPOT_BYTES, 0))
            h.update(f.read(_SPOT_BYTES))
    return h.hexdigest()


@dataclass
class ScanCacheStats:
    """Per-call accounting: the "only new partitions" counters."""

    partitions: int = 0
    scanned: int = 0       # partitions actually re-read
    cached: int = 0        # partitions served from the cache
    stored: int = 0
    quarantined: int = 0

    def as_dict(self) -> Dict[str, int]:
        return dict(self.__dict__)


@dataclass
class _PartitionEntry:
    rows: int
    max_live: int
    keys: List[str]
    # per-key float64 moment partials (s1, s2, l1, nnz, mx, mn) + the
    # partition's positive-weight row count, for the fused-summary path.
    # has_moments distinguishes "partials not collected" (a scan-only
    # entry — the summary path must rescan) from "collected, all zero".
    has_moments: bool = False
    real_rows: float = 0.0
    moments: Dict[str, Tuple[float, float, float, float, float, float]] = (
        field(default_factory=dict)
    )


class PartitionStatsCache:
    """One directory of ``<key>.json`` entries (append-only)."""

    def __init__(self, cache_dir: str):
        self.cache_dir = os.path.abspath(cache_dir)
        self.stats = ScanCacheStats()

    def _entry_path(self, key: str) -> str:
        return os.path.join(
            self.cache_dir, f"v{STATS_CACHE_VERSION}", f"{key}.json"
        )

    def load(self, path: str, key: str) -> Optional[_PartitionEntry]:
        entry_path = self._entry_path(key)
        if not os.path.isfile(entry_path):
            return None

        def _load():
            with open(entry_path) as f:
                data = json.load(f)
            if data.get("version") != STATS_CACHE_VERSION or (
                data.get("key") != key
            ):
                raise ValueError(
                    f"stats-cache entry {entry_path} version/key mismatch"
                )
            return _PartitionEntry(
                rows=int(data["rows"]),
                max_live=int(data["max_live"]),
                keys=list(data["keys"]),
                has_moments=bool(data.get("has_moments", False)),
                real_rows=float(data.get("real_rows", 0.0)),
                moments={
                    k: tuple(v) for k, v in data.get("moments", {}).items()
                },
            )

        try:
            return io_call(STATS_CACHE_SEAM, _load, detail=entry_path)
        except (InjectedCorruption, ValueError, KeyError, TypeError):
            # poisoned entry: quarantine (accounted) and rescan the
            # partition — corrupt cache state must cost one re-read,
            # never a wrong model
            quarantine_artifact(entry_path, STATS_CACHE_SEAM)
            self.stats.quarantined += 1
            return None
        except SeamFailure:
            # the cache is an accelerator, not a dependency: an
            # exhausted read budget falls back to the rescan
            return None

    def store(self, path: str, key: str, entry: _PartitionEntry) -> None:
        from photon_ml_tpu.reliability.artifacts import atomic_write_json

        entry_path = self._entry_path(key)
        payload = {
            "version": STATS_CACHE_VERSION,
            "key": key,
            "source": os.path.basename(path),
            "rows": entry.rows,
            "max_live": entry.max_live,
            "keys": entry.keys,
            "has_moments": entry.has_moments,
            "real_rows": entry.real_rows,
            "moments": {k: list(v) for k, v in entry.moments.items()},
        }

        def _store():
            os.makedirs(os.path.dirname(entry_path), exist_ok=True)
            atomic_write_json(entry_path, payload)

        try:
            io_call(STATS_CACHE_SEAM, _store, detail=entry_path)
            self.stats.stored += 1
        except SeamFailure:
            return  # store failures cost the next run a rescan, nothing else


def _scan_partition(
    fmt, path: str, *, with_moments: bool
) -> _PartitionEntry:
    """One partition's reductions via the format's own scan hooks —
    exactly ``stream_scan``'s per-file semantics (selected keys, zero
    values kept in widths, intercept excluded here and re-added by the
    caller), plus the fused-summary moment accumulation when asked."""
    index_map, stats = fmt.stream_scan([path])
    from photon_ml_tpu.utils.index_map import intercept_key

    keys = sorted(k for k, _ in index_map.items() if k != intercept_key())
    max_live = stats.max_nnz - (1 if fmt.add_intercept else 0)
    entry = _PartitionEntry(
        rows=stats.num_rows, max_live=max_live, keys=keys
    )
    if with_moments:
        entry.real_rows, entry.moments = _moment_partials(fmt, path)
        entry.has_moments = True
    return entry


def _moment_partials(fmt, path: str):
    """Raw float64 per-key partials of one partition: the fused scan's
    in-loop accumulation, stopped before finalize."""
    real_rows = 0.0
    s: Dict[str, List[float]] = {}

    def slot(key):
        m = s.get(key)
        if m is None:
            m = [0.0, 0.0, 0.0, 0.0, -np.inf, np.inf]
            s[key] = m
        return m

    from photon_ml_tpu.io.avro_codec import read_avro_records

    decoded = getattr(fmt, "decode_file", lambda p: None)(path)
    if decoded is not None:
        m_rec = decoded.num_records
        sel = np.asarray([
            fmt.selected is None or x in fmt.selected
            for x in decoded.strings
        ]) if len(decoded.strings) else np.zeros(0, bool)
        wgt = (
            decoded.f64("weight")
            if "weight" in decoded.plan.num_slots
            else np.ones(m_rec)
        )
        wgt = np.where(np.isnan(wgt), 1.0, wgt)
        real = wgt > 0
        real_rows = float(real.sum())
        row_ptr, key_ids, values = decoded.bag("features")
        if len(key_ids):
            widths = np.diff(row_ptr)
            row_of = np.repeat(np.arange(m_rec, dtype=np.int64), widths)
            keep = sel[key_ids] & real[row_of] & (values != 0)
            for kid, v in zip(key_ids[keep], values[keep]):
                m = slot(decoded.strings[int(kid)])
                v = float(v)
                m[0] += v
                m[1] += v * v
                m[2] += abs(v)
                m[3] += 1.0
                m[4] = max(m[4], v)
                m[5] = min(m[5], v)
    else:
        for record in read_avro_records([path]):
            wgt_v = record.get("weight")
            w = 1.0 if wgt_v is None else float(wgt_v)
            real = w > 0
            real_rows += 1.0 if real else 0.0
            for key, value in fmt._record_pairs(record):
                if real and value != 0:
                    m = slot(key)
                    m[0] += value
                    m[1] += value * value
                    m[2] += abs(value)
                    m[3] += 1.0
                    m[4] = max(m[4], value)
                    m[5] = min(m[5], value)
    return real_rows, {k: tuple(v) for k, v in s.items()}


def _gather_entries(
    paths, fmt, cache: PartitionStatsCache, *, with_moments: bool
) -> List[Tuple[str, _PartitionEntry]]:
    files = fmt.stream_files(paths)
    out = []
    cache.stats = ScanCacheStats()
    for path in files:
        cache.stats.partitions += 1
        key = _partition_key(path)
        entry = cache.load(path, key)
        if entry is not None and (not with_moments or entry.has_moments):
            cache.stats.cached += 1
        else:
            cache.stats.scanned += 1
            entry = _scan_partition(fmt, path, with_moments=with_moments)
            cache.store(path, key, entry)
        out.append((path, entry))
    return out


def cached_scan_stream(paths, fmt, cache_dir: str, *, index_map=None):
    """Drop-in for ``io.streaming.scan_stream`` over an append-only
    directory: returns the IDENTICAL ``(index_map, StreamStats)`` while
    re-reading only partitions without a valid cache entry. Accounting
    in ``cache.stats`` (also returned for the caller's metrics)."""
    from photon_ml_tpu.io.streaming import StreamStats
    from photon_ml_tpu.utils.index_map import IndexMap

    cache = PartitionStatsCache(cache_dir)
    entries = _gather_entries(paths, fmt, cache, with_moments=False)
    keys = set()
    num_rows = 0
    max_live = 0
    for _path, e in entries:
        num_rows += e.rows
        max_live = max(max_live, e.max_live)
        if index_map is None:
            keys.update(e.keys)
    if index_map is None:
        index_map = IndexMap.build(
            iter(keys), add_intercept=fmt.add_intercept
        )
    max_nnz = max(max_live + (1 if fmt.add_intercept else 0), 1)
    return (
        index_map,
        StreamStats(num_rows=num_rows, max_nnz=max_nnz),
        cache.stats,
    )


def cached_scan_stream_with_summary(
    paths, fmt, cache_dir: str, *, index_map=None
):
    """Cached twin of ``stream_scan_with_summary``: vocabulary + shape
    stats + colStats summary from per-partition partials, re-reading
    only uncached partitions. Returns
    ``(index_map, StreamStats, summary, ScanCacheStats)``."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.stats import finalize_summary
    from photon_ml_tpu.io.streaming import StreamStats
    from photon_ml_tpu.utils.index_map import IndexMap, intercept_key

    cache = PartitionStatsCache(cache_dir)
    entries = _gather_entries(paths, fmt, cache, with_moments=True)
    keys = set()
    num_rows = 0
    max_live = 0
    real_rows = 0.0
    for _path, e in entries:
        num_rows += e.rows
        max_live = max(max_live, e.max_live)
        real_rows += e.real_rows
        if index_map is None:
            keys.update(e.keys)
    if index_map is None:
        index_map = IndexMap.build(
            iter(keys), add_intercept=fmt.add_intercept
        )
    dim = index_map.size
    s1 = np.zeros(dim)
    s2 = np.zeros(dim)
    l1 = np.zeros(dim)
    nnz = np.zeros(dim)
    mx = np.full(dim, -np.inf)
    mn = np.full(dim, np.inf)
    # merge partials in sorted-file order (the _gather order), so the
    # result is deterministic run to run
    for _path, e in entries:
        for key, (p1, p2, pl1, pn, pmx, pmn) in e.moments.items():
            j = index_map.get_index(key)
            if j < 0:
                continue
            s1[j] += p1
            s2[j] += p2
            l1[j] += pl1
            nnz[j] += pn
            mx[j] = max(mx[j], pmx)
            mn[j] = min(mn[j], pmn)
    icept = (
        index_map.get_index(intercept_key()) if fmt.add_intercept else -1
    )
    if icept >= 0 and real_rows > 0:
        s1[icept] = s2[icept] = l1[icept] = real_rows
        nnz[icept] = real_rows
        mx[icept] = mn[icept] = 1.0
    summary = finalize_summary(
        jnp.float32(real_rows),
        jnp.asarray(s1, jnp.float32),
        jnp.asarray(s2, jnp.float32),
        jnp.asarray(l1, jnp.float32),
        jnp.asarray(nnz, jnp.float32),
        jnp.asarray(mx, jnp.float32),
        jnp.asarray(mn, jnp.float32),
    )
    max_nnz = max(max_live + (1 if fmt.add_intercept else 0), 1)
    return (
        index_map,
        StreamStats(num_rows=num_rows, max_nnz=max_nnz),
        summary,
        cache.stats,
    )
