"""Validation gates: the promotion decision between "trained" and
"serving".

A retrain loop that auto-publishes MUST be unable to ship a model that
is worse than what is already serving — bad labels, a broken join, a
drifted feature pipeline all produce models that converge fine and
score garbage. The gates compare the CANDIDATE against its PARENT on a
held-out stream and produce one named terminal verdict:

- ``PASS`` — every gate held; the candidate may commit.
- ``AUC_REGRESSION`` / ``RMSE_REGRESSION`` — holdout quality moved
  against the parent past the configured margin (streamed accumulators
  from ``evaluation/streaming.py``; the holdout is never materialized).
- ``COEF_NORM_BLOWUP`` — the coefficient norm grew past
  ``max_coef_norm_ratio``x the parent's: the classic exploding-fit
  signature of label leakage or a collapsed regularizer.
- ``PREDICTION_DRIFT`` — mean |candidate - parent| margin on the
  holdout beyond ``max_prediction_drift``: the candidate scores a
  DIFFERENT function even where quality metrics look fine (fast
  detector for feature-pipeline skew).

The verdict (and every per-gate measurement) is recorded verbatim in
the registry manifest; a non-PASS verdict makes
``ModelRegistry.publish`` refuse the candidate — a failed gate is a
terminal, named, auditable outcome, not a warning.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Iterable, Optional, Tuple

import numpy as np

__all__ = ["GateConfig", "GateReport", "evaluate_gates", "coef_norm_gate"]

# chunk protocol: (candidate_margins, parent_margins, labels, weights)
ChunkStream = Iterable[Tuple[object, object, object, object]]


@dataclass(frozen=True)
class GateConfig:
    """Thresholds. Margins are ABSOLUTE deltas against the parent's
    measured value (relative thresholds turn degenerate when the parent
    metric sits near 0)."""

    max_auc_drop: float = 0.005
    max_rmse_increase: float = 0.01
    max_coef_norm_ratio: float = 10.0
    max_prediction_drift: Optional[float] = None  # None = gate off
    min_holdout_rows: int = 1

    def as_dict(self) -> Dict[str, object]:
        return {
            "max_auc_drop": self.max_auc_drop,
            "max_rmse_increase": self.max_rmse_increase,
            "max_coef_norm_ratio": self.max_coef_norm_ratio,
            "max_prediction_drift": self.max_prediction_drift,
            "min_holdout_rows": self.min_holdout_rows,
        }


@dataclass
class GateReport:
    """The manifest-recorded outcome: one named verdict + the per-gate
    measurements that produced it."""

    verdict: str
    checks: Dict[str, Dict[str, object]]
    config: Dict[str, object]

    @property
    def passed(self) -> bool:
        return self.verdict == "PASS"

    def as_dict(self) -> Dict[str, object]:
        return {
            "verdict": self.verdict,
            "checks": self.checks,
            "config": self.config,
        }

    @staticmethod
    def from_dict(d: Dict[str, object]) -> "GateReport":
        return GateReport(
            verdict=str(d.get("verdict", "")),
            checks=dict(d.get("checks", {})),
            config=dict(d.get("config", {})),
        )


def coef_norm_gate(
    candidate_norm: float, parent_norm: float, config: GateConfig
) -> Dict[str, object]:
    """The coefficient-sanity check, separable from the holdout pass so
    drivers can run it on whatever norm their model family defines
    (GLM: ||means||2; GAME: FE norm + mean RE row norm)."""
    # an exactly-zero parent norm (fresh intercept-only parent) gates on
    # an absolute bound instead of a ratio of zero
    if parent_norm <= 0.0:
        passed = bool(np.isfinite(candidate_norm))
        ratio = float("inf") if candidate_norm > 0 else 1.0
    else:
        ratio = float(candidate_norm / parent_norm)
        passed = bool(
            np.isfinite(candidate_norm)
            and ratio <= config.max_coef_norm_ratio
        )
    return {
        "passed": passed,
        "candidate_norm": float(candidate_norm),
        "parent_norm": float(parent_norm),
        "ratio": ratio,
        "threshold": config.max_coef_norm_ratio,
    }


def evaluate_gates(
    chunks: ChunkStream,
    task,
    *,
    config: Optional[GateConfig] = None,
    candidate_norm: Optional[float] = None,
    parent_norm: Optional[float] = None,
) -> GateReport:
    """Run the full gate set over one streamed pass of the holdout.

    ``chunks`` yields ``(candidate_margins, parent_margins, labels,
    weights)`` per chunk — the caller owns scoring (GLM margins, GAME
    total scores), this owns the accumulators and the verdict. The
    first failing gate in severity order names the verdict; every
    check's measurement is recorded either way.
    """
    from photon_ml_tpu.evaluation.streaming import (
        StreamingAUC,
        StreamingRMSE,
    )
    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.task import TaskType

    config = config or GateConfig()
    loss = loss_for_task(task)
    use_auc = task in (
        TaskType.LOGISTIC_REGRESSION,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
    )
    use_rmse = task in (
        TaskType.LINEAR_REGRESSION, TaskType.POISSON_REGRESSION,
    )
    cand_auc, par_auc = StreamingAUC(), StreamingAUC()
    cand_rmse, par_rmse = StreamingRMSE(), StreamingRMSE()
    drift_sum = 0.0
    w_sum = 0.0
    rows = 0
    for cand_m, par_m, labels, weights in chunks:
        cm = np.asarray(cand_m, np.float64)
        pm = np.asarray(par_m, np.float64)
        y = np.asarray(labels, np.float64)
        w = np.asarray(weights, np.float64)
        rows += int(cm.shape[0])
        if use_auc:
            cand_auc.update(cm, y, w)
            par_auc.update(pm, y, w)
        if use_rmse:
            import jax.numpy as jnp

            from photon_ml_tpu.parallel import overlap

            # mean-space transform runs on device; ONE counted fetch
            # brings both models' predictions back per chunk
            mean_c, mean_p = overlap.device_get(
                (loss.mean(jnp.asarray(cm)), loss.mean(jnp.asarray(pm)))
            )
            cand_rmse.update(mean_c, y, w)
            par_rmse.update(mean_p, y, w)
        drift_sum += float(np.sum(w * np.abs(cm - pm)))
        w_sum += float(np.sum(w))

    checks: Dict[str, Dict[str, object]] = {}
    verdict = "PASS"

    def fail(name: str) -> None:
        nonlocal verdict
        if verdict == "PASS":
            verdict = name

    if rows < config.min_holdout_rows:
        checks["holdout"] = {
            "passed": False,
            "rows": rows,
            "threshold": config.min_holdout_rows,
        }
        fail("EMPTY_HOLDOUT")
    if candidate_norm is not None and parent_norm is not None:
        checks["coef_norm"] = coef_norm_gate(
            candidate_norm, parent_norm, config
        )
        if not checks["coef_norm"]["passed"]:
            fail("COEF_NORM_BLOWUP")
    if use_auc and rows:
        c, p = cand_auc.result(), par_auc.result()
        ok = bool(
            np.isnan(p) or (
                not np.isnan(c) and c >= p - config.max_auc_drop
            )
        )
        checks["auc"] = {
            "passed": ok,
            "candidate": float(c),
            "parent": float(p),
            "max_drop": config.max_auc_drop,
        }
        if not ok:
            fail("AUC_REGRESSION")
    if use_rmse and rows:
        c, p = cand_rmse.result(), par_rmse.result()
        ok = bool(c <= p + config.max_rmse_increase)
        checks["rmse"] = {
            "passed": ok,
            "candidate": float(c),
            "parent": float(p),
            "max_increase": config.max_rmse_increase,
        }
        if not ok:
            fail("RMSE_REGRESSION")
    if config.max_prediction_drift is not None and w_sum > 0:
        drift = drift_sum / w_sum
        ok = bool(drift <= config.max_prediction_drift)
        checks["prediction_drift"] = {
            "passed": ok,
            "mean_abs_margin_delta": float(drift),
            "threshold": config.max_prediction_drift,
        }
        if not ok:
            fail("PREDICTION_DRIFT")
    return GateReport(
        verdict=verdict, checks=checks, config=config.as_dict()
    )


def glm_gate_chunks(
    candidate_means,
    parent_means,
    paths,
    fmt,
    index_map,
    nnz_width: int,
) -> ChunkStream:
    """GLM chunk adapter: stream the holdout once, scoring BOTH models
    per chunk (the chunk is staged once; two margin computations share
    it)."""
    import jax

    from photon_ml_tpu.io.streaming import iter_chunks
    from photon_ml_tpu.models.glm import compute_margins
    from photon_ml_tpu.parallel import overlap

    margins_fn = jax.jit(compute_margins)
    for chunk in iter_chunks(
        paths, fmt, index_map, rows_per_chunk=65536, nnz_width=nnz_width
    ):
        cand, par = overlap.device_get(
            (
                margins_fn(candidate_means, chunk),
                margins_fn(parent_means, chunk),
            )
        )
        yield cand, par, chunk.labels, chunk.weights
