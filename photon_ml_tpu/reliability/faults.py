"""Deterministic fault injection for the IO and transfer seams.

A production retraining loop has to assume the Podracer operating
conditions (PAPERS.md): components fail and restart while the rest keep
making progress. You cannot test that with `rm -rf` and hope — chaos has
to be REPRODUCIBLE, or a flaky green run proves nothing. This module
gives every IO seam in the package a *named injection point* driven by a
parsed fault plan, so "fail the 3rd chunk read with EIO, once" is a
string you can put in CI (`dev-scripts/chaos.sh`) and replay bit-for-bit.

Plan syntax (``--fault-plan`` / ``PHOTON_FAULT_PLAN``): comma-separated
entries, each ::

    <seam>:<nth>:<error>[:<times>]

- ``seam``: one of :data:`SEAMS` (``chunk_read``, ``spill_write``, ...).
- ``nth``: 1-based call index at which the fault starts firing.
- ``error``: ``EIO`` / ``ENOSPC`` / ``EACCES`` / ``ETIMEDOUT`` (raised
  as :class:`InjectedFault`, an OSError the retry layer treats like any
  transient IO error), ``CORRUPT`` (raised as
  :class:`InjectedCorruption`, a ValueError — the artifact-damage
  class the quarantine paths handle), or ``KILL`` (SIGKILL to the own
  process at that exact crossing: deterministic ``kill -9`` — no
  handlers, no atexit, no flushes — the crash-resume tests' hammer).
- ``times``: how many consecutive calls fail (default 1; ``once`` is an
  accepted alias; ``*`` means every call from ``nth`` on — the
  poisoned-artifact case that must end in quarantine/giveup, never a
  silent skip).

Example: ``chunk_read:3:EIO,ckpt_save:1:ENOSPC:2``.

Injection is counted per seam whether or not a fault fires, so the
accounting in ``metrics.json`` shows exactly which seams a run crossed
and how many faults were injected — the chaos matrix's completion
invariant is checked against these counters.
"""

from __future__ import annotations

import errno
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = [
    "SEAMS",
    "InjectedFault",
    "InjectedCorruption",
    "FaultEntry",
    "FaultPlan",
    "install_plan",
    "active_plan",
    "inject",
    "fault_stats",
    "reset_fault_stats",
]

ENV_FAULT_PLAN = "PHOTON_FAULT_PLAN"

# The seam classes threaded through the package. Every io_call /
# inject() site names one of these; an unknown seam is a programming
# error (raised at plan parse AND at injection time).
SEAMS = (
    "chunk_read",     # Avro/LibSVM file decode feeding iter_chunks
    "spill_write",    # chunk/score/bucket-segment store writes
    "spill_read",     # chunk/score/bucket-segment store reads
    "cache_load",     # tile-schedule cache artifact load
    "cache_store",    # tile-schedule cache artifact store
    "ckpt_save",      # checkpoint step / meta / lambda-snapshot save
    "ckpt_restore",   # checkpoint restore / meta load
    "io_worker",      # overlap.submit_io async artifact writes
    "decode_ahead",   # decode-ahead worker thread handoff
    "serving.model_load",  # serving bank load / hot-swap staging reads
    "serving.frontend.read",   # network front-end per-line reads
    "serving.dispatch",        # micro-batch device dispatch (idempotent)
    "registry.publish",        # model-registry publish protocol steps
    "registry.stats_cache",    # per-partition scan/stats cache load/store
)

_ERRNO = {
    "EIO": errno.EIO,
    "ENOSPC": errno.ENOSPC,
    "EACCES": errno.EACCES,
    "ETIMEDOUT": errno.ETIMEDOUT,
}


class InjectedFault(OSError):
    """A planned transient IO failure (retryable, carries a real errno)."""

    def __init__(self, seam: str, err: str, occurrence: int, detail: str):
        super().__init__(
            _ERRNO[err],
            f"injected {err} at {seam} call #{occurrence}"
            + (f" ({detail})" if detail else ""),
        )
        self.seam = seam
        self.occurrence = occurrence


class InjectedCorruption(ValueError):
    """Planned artifact damage (NOT retryable: re-reading a corrupt file
    yields the same bytes — the quarantine/rebuild paths own this)."""

    def __init__(self, seam: str, occurrence: int, detail: str):
        super().__init__(
            f"injected corruption at {seam} call #{occurrence}"
            + (f" ({detail})" if detail else "")
        )
        self.seam = seam
        self.occurrence = occurrence


@dataclass(frozen=True)
class FaultEntry:
    seam: str
    nth: int          # 1-based first failing call
    error: str        # key of _ERRNO, or "CORRUPT"
    times: int        # consecutive failures; -1 = every call from nth on

    def fires_at(self, occurrence: int) -> bool:
        if occurrence < self.nth:
            return False
        return self.times < 0 or occurrence < self.nth + self.times


@dataclass
class FaultPlan:
    """Parsed plan + per-seam call counters. Deterministic by
    construction: the nth crossing of a seam fires the nth-indexed
    entries, independent of threads or timing (the counter increment is
    atomic under the plan lock)."""

    entries: List[FaultEntry] = field(default_factory=list)
    text: str = ""
    _calls: Dict[str, int] = field(default_factory=dict)
    _injected: Dict[str, int] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        entries = []
        for raw in (text or "").split(","):
            raw = raw.strip()
            if not raw:
                continue
            parts = raw.split(":")
            if len(parts) not in (3, 4):
                raise ValueError(
                    f"bad fault-plan entry {raw!r}: expected "
                    "seam:nth:error[:times]"
                )
            seam, nth_s, err = parts[0].strip(), parts[1].strip(), (
                parts[2].strip().upper()
            )
            if seam not in SEAMS:
                raise ValueError(
                    f"unknown fault seam {seam!r}; known: {', '.join(SEAMS)}"
                )
            if err not in ("CORRUPT", "KILL") and err not in _ERRNO:
                raise ValueError(
                    f"unknown fault error {err!r}; known: "
                    f"{', '.join(_ERRNO)}, CORRUPT, KILL"
                )
            nth = int(nth_s)
            if nth < 1:
                raise ValueError(f"fault nth must be >= 1, got {nth}")
            times_s = parts[3].strip().lower() if len(parts) == 4 else "1"
            if times_s in ("once", "1"):
                times = 1
            elif times_s == "*":
                times = -1
            else:
                times = int(times_s)
                if times < 1:
                    raise ValueError(
                        f"fault times must be >= 1 or '*', got {times_s}"
                    )
            entries.append(FaultEntry(seam, nth, err, times))
        return cls(entries=entries, text=text or "")

    def check(self, seam: str, detail: str = "") -> None:
        """Count one crossing of ``seam``; raise the planned error when an
        entry covers this occurrence."""
        with self._lock:
            n = self._calls.get(seam, 0) + 1
            self._calls[seam] = n
            fire = next(
                (e for e in self.entries
                 if e.seam == seam and e.fires_at(n)),
                None,
            )
            if fire is not None:
                self._injected[seam] = self._injected.get(seam, 0) + 1
        if fire is None:
            return
        # flight-recorder event for every TRIGGERED injection (never
        # for plain crossings — those stay counters), recorded OUTSIDE
        # the plan lock. A KILL entry records BEFORE the SIGKILL: the
        # armed auto-dump persists the ring, so the post-mortem shows
        # the exact crossing that killed the process.
        from photon_ml_tpu.obs.flight_recorder import flight_recorder

        flight_recorder().record(
            "fault.crossing", seam=seam, occurrence=n,
            error=fire.error, detail=detail,
        )
        if fire.error == "KILL":
            # deterministic kill -9 at this exact crossing: SIGKILL is
            # uncatchable, so nothing below this line runs — exactly the
            # no-cleanup crash the resume machinery must survive
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
        if fire.error == "CORRUPT":
            raise InjectedCorruption(seam, n, detail)
        raise InjectedFault(seam, fire.error, n, detail)

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                "calls": dict(self._calls),
                "injected": dict(self._injected),
            }


# -- process-wide plan --------------------------------------------------------

_LOCK = threading.Lock()
_PLAN: Optional[FaultPlan] = None
_PLAN_RESOLVED = False
# Seam-crossing counters kept even with NO plan installed, so the
# accounting in metrics.json always shows which seams a run exercised.
_BASE_CALLS: Dict[str, int] = {}


def install_plan(plan) -> Optional[FaultPlan]:
    """Install a FaultPlan (or plan text, or None to clear). Drivers call
    this from ``--fault-plan``; tests from fixtures."""
    global _PLAN, _PLAN_RESOLVED
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    with _LOCK:
        _PLAN = plan
        _PLAN_RESOLVED = True
    return plan


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, resolving ``PHOTON_FAULT_PLAN`` on first use."""
    global _PLAN, _PLAN_RESOLVED
    with _LOCK:
        if not _PLAN_RESOLVED:
            text = os.environ.get(ENV_FAULT_PLAN, "").strip()
            _PLAN = FaultPlan.parse(text) if text else None
            _PLAN_RESOLVED = True
        return _PLAN


def inject(seam: str, detail: str = "") -> None:
    """The injection point: every reliability seam calls this once per
    attempt. No plan installed -> a counter bump and nothing else (the
    disabled-path cost the bench overhead gate prices)."""
    if seam not in SEAMS:
        raise ValueError(f"unknown fault seam {seam!r}")
    plan = active_plan()
    if plan is not None:
        plan.check(seam, detail)
        return
    with _LOCK:
        _BASE_CALLS[seam] = _BASE_CALLS.get(seam, 0) + 1


def fault_stats() -> Dict[str, Dict[str, int]]:
    """{"calls": {seam: n}, "injected": {seam: k}, "plan": text} for the
    metrics.json accounting block."""
    plan = active_plan()
    if plan is not None:
        out = plan.stats()
        out["plan"] = plan.text
        return out
    with _LOCK:
        return {"calls": dict(_BASE_CALLS), "injected": {}, "plan": ""}


def reset_fault_stats() -> None:
    global _PLAN, _PLAN_RESOLVED
    with _LOCK:
        _BASE_CALLS.clear()
        _PLAN = None
        _PLAN_RESOLVED = False
