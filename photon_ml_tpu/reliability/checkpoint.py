"""Crash-safe resume state beyond GAME in-memory CD: per-λ grid
snapshots (GLM drivers) and per-iteration streaming-CD snapshots (GAME
streaming driver).

The orbax TrainingCheckpointer (utils/checkpoint.py) covers the
in-memory GAME CD loop; these two cover the paths that had NOTHING: a
``kill -9`` during a λ-grid sweep used to lose every solved λ, and a
streamed GAME run lost the whole staged store plus every CD iteration.
Both checkpointers follow the same commit protocol: arrays land in an
``.npz`` written tmp+rename, then a small JSON *commit marker* lands
atomically — a snapshot without its marker (killed between the two
writes) is invisible to resume. All IO runs behind the ckpt_save /
ckpt_restore seams, so chaos plans cover it and transient errors retry.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.reliability.artifacts import atomic_write_json
from photon_ml_tpu.reliability.manifest import ensure_run_manifest
from photon_ml_tpu.reliability.retry import io_call

__all__ = ["GridCheckpointer", "StreamingCDCheckpointer"]


def _save_npz(path: str, arrays: Dict[str, np.ndarray]) -> None:
    """np.savez to a same-directory temp + rename (np.savez itself can
    be killed mid-write; the published file is always complete)."""
    tmp = f"{path}.{os.getpid()}.tmp.npz"

    def _write():
        np.savez(tmp, **arrays)
        os.replace(tmp, path)

    try:
        io_call("ckpt_save", _write, detail=path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _load_npz(path: str) -> Dict[str, np.ndarray]:
    def _read():
        with np.load(path, allow_pickle=False) as z:
            return {k: np.array(z[k]) for k in z.files}

    return io_call("ckpt_restore", _read, detail=path)


def _read_marker(path: str) -> Optional[dict]:
    import json

    if not os.path.isfile(path):
        return None

    def _load():
        with open(path) as f:
            return json.load(f)

    return io_call("ckpt_restore", _load, detail=path)


class GridCheckpointer:
    """Per-λ snapshots for the GLM regularization-path sweeps.

    One snapshot per COMPLETED λ: the warm-start means (optimization
    space — the currency the next λ's solve starts from, so a resumed
    sweep walks bitwise the same iterate chain), the exported model
    (original space), and the OptResult arrays. The run manifest guards
    against resuming a different grid/data/config.
    """

    def __init__(self, directory: str, run_config: Dict[str, object]):
        self.directory = os.path.abspath(directory)
        ensure_run_manifest(self.directory, run_config, kind="glm-grid")

    def _base(self, lam: float) -> str:
        tag = float(lam).hex().replace("0x", "").replace(".", "_")
        return os.path.join(self.directory, f"lambda-{tag}")

    def has(self, lam: float) -> bool:
        return _read_marker(self._base(lam) + ".json") is not None

    def save(
        self,
        lam: float,
        *,
        warm_means: np.ndarray,
        model_means: np.ndarray,
        model_variances: Optional[np.ndarray],
        result_arrays: Dict[str, np.ndarray],
    ) -> None:
        base = self._base(lam)
        arrays = {
            "warm_means": np.asarray(warm_means),
            "model_means": np.asarray(model_means),
        }
        if model_variances is not None:
            arrays["model_variances"] = np.asarray(model_variances)
        for k, v in result_arrays.items():
            if v is not None:
                arrays[f"result__{k}"] = np.asarray(v)
        _save_npz(base + ".npz", arrays)
        # marker last: its atomic publish commits the snapshot
        io_call(
            "ckpt_save", atomic_write_json, base + ".json",
            {"lambda": float(lam)}, detail=base + ".json",
        )

    def load(self, lam: float) -> Optional[Dict[str, object]]:
        base = self._base(lam)
        if _read_marker(base + ".json") is None:
            return None
        arrays = _load_npz(base + ".npz")
        out: Dict[str, object] = {
            "warm_means": arrays["warm_means"],
            "model_means": arrays["model_means"],
            "model_variances": arrays.get("model_variances"),
            "result": {
                k[len("result__"):]: v
                for k, v in arrays.items()
                if k.startswith("result__")
            },
        }
        return out

    # -- unified-mesh grid banks (game/unified.py) ----------------------------
    #
    # The sharded λ-grid bank snapshots in its RAW [G_pad, rows, d]
    # hash-placement layout (GridShardedREBank.snapshot, a declared
    # export scope); restore hands the loaded array to
    # GridShardedREBank.restore, whose jit out_shardings re-shard it
    # device-side — neither direction builds a host [E, d] view. The
    # marker records the layout so a snapshot cannot silently restore
    # onto a different entity-shard count.

    def _grid_base(self, name: str) -> str:
        safe = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in str(name)
        )
        return os.path.join(self.directory, f"grid-bank-{safe}")

    def has_grid_bank(self, name: str) -> bool:
        return _read_marker(self._grid_base(name) + ".json") is not None

    def save_grid_bank(
        self, name: str, bank_snapshot: np.ndarray,
        layout: Dict[str, int],
    ) -> None:
        """Commit a GridShardedREBank.snapshot() array (same tmp+rename
        npz then atomic-marker protocol as the per-λ snapshots)."""
        base = self._grid_base(name)
        _save_npz(base + ".npz", {"bank": np.asarray(bank_snapshot)})
        io_call(
            "ckpt_save", atomic_write_json, base + ".json",
            {"name": str(name), "layout": {
                k: int(v) for k, v in layout.items()
            }},
            detail=base + ".json",
        )

    def load_grid_bank(
        self, name: str, expect_layout: Optional[Dict[str, int]] = None,
    ) -> Optional[Tuple[np.ndarray, Dict[str, int]]]:
        """(snapshot, layout) for a committed grid bank, or None. With
        ``expect_layout``, a committed snapshot whose recorded layout
        disagrees raises — restoring hash-placed rows onto a different
        shard count would scramble entity ownership silently."""
        base = self._grid_base(name)
        marker = _read_marker(base + ".json")
        if marker is None:
            return None
        layout = {
            k: int(v) for k, v in dict(marker.get("layout") or {}).items()
        }
        if expect_layout is not None:
            mismatched = {
                k: (layout.get(k), int(v))
                for k, v in expect_layout.items()
                if layout.get(k) != int(v)
            }
            if mismatched:
                raise ValueError(
                    f"grid-bank snapshot {name!r} was written under a "
                    f"different layout: {mismatched} (recorded vs "
                    "expected); re-run with the original mesh shape or "
                    "start fresh"
                )
        arrays = _load_npz(base + ".npz")
        return arrays["bank"], layout


class StreamingCDCheckpointer:
    """Per-iteration snapshots of the streamed GAME coordinate-descent
    state: every coordinate's means/bank (+ variances when tracked) and
    the host-side histories. Iteration k+1 depends ONLY on the states
    after iteration k (scores/residuals recompute deterministically from
    states against the staged chunks), so the iteration boundary is a
    complete resume point."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max(1, int(max_to_keep))

    def _npz(self, it: int) -> str:
        return os.path.join(self.directory, f"iter-{it:06d}.npz")

    def _marker(self, it: int) -> str:
        return os.path.join(self.directory, f"iter-{it:06d}.json")

    def steps(self) -> List[int]:
        out = []
        for fn in os.listdir(self.directory):
            if fn.startswith("iter-") and fn.endswith(".json"):
                try:
                    out.append(int(fn[len("iter-"):-len(".json")]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def save(
        self,
        it: int,
        states: Dict[str, np.ndarray],
        variances: Dict[str, Optional[np.ndarray]],
        histories: Dict[str, object],
    ) -> None:
        arrays: Dict[str, np.ndarray] = {}
        for name, s in states.items():
            arrays[f"state__{name}"] = np.asarray(s)
        for name, v in variances.items():
            if v is not None:
                arrays[f"var__{name}"] = np.asarray(v)
        _save_npz(self._npz(it), arrays)
        io_call(
            "ckpt_save", atomic_write_json, self._marker(it),
            {"iteration": int(it), "histories": histories},
            detail=self._marker(it),
        )
        for old in self.steps()[: -self.max_to_keep]:
            for path in (self._npz(old), self._marker(old)):
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def load(
        self, it: int
    ) -> Tuple[
        Dict[str, np.ndarray],
        Dict[str, Optional[np.ndarray]],
        Dict[str, object],
    ]:
        marker = _read_marker(self._marker(it))
        if marker is None:
            raise FileNotFoundError(f"no streaming-CD snapshot at {it}")
        arrays = _load_npz(self._npz(it))
        states = {
            k[len("state__"):]: v
            for k, v in arrays.items()
            if k.startswith("state__")
        }
        variances: Dict[str, Optional[np.ndarray]] = {
            name: arrays.get(f"var__{name}") for name in states
        }
        return states, variances, dict(marker.get("histories") or {})
