"""Reliability layer: deterministic fault injection, retrying IO, and
crash-safe resume primitives shared by every driver.

- :mod:`photon_ml_tpu.reliability.faults` — named injection points +
  the seeded fault plan (``--fault-plan`` / ``PHOTON_FAULT_PLAN``).
- :mod:`photon_ml_tpu.reliability.retry` — :func:`io_call` (bounded
  backoff per seam), :class:`SeamFailure`, poisoned-artifact quarantine,
  and the metrics.json accounting block.
- :mod:`photon_ml_tpu.reliability.artifacts` — atomic write-rename for
  every artifact (lint rule PL006 enforces usage).
- :mod:`photon_ml_tpu.reliability.manifest` — run/store manifests for
  resume compatibility + progress.
- :mod:`photon_ml_tpu.reliability.checkpoint` — per-λ grid snapshots
  (GLM) and per-iteration streaming-CD snapshots (GAME).
"""

from photon_ml_tpu.reliability.artifacts import (  # noqa: F401
    atomic_write_bytes,
    atomic_write_json,
    atomic_write_text,
    atomic_writer,
)
from photon_ml_tpu.reliability.checkpoint import (  # noqa: F401
    GridCheckpointer,
    StreamingCDCheckpointer,
)
from photon_ml_tpu.reliability.faults import (  # noqa: F401
    SEAMS,
    FaultPlan,
    InjectedCorruption,
    InjectedFault,
    fault_stats,
    inject,
    install_plan,
    reset_fault_stats,
)
from photon_ml_tpu.reliability.manifest import (  # noqa: F401
    ensure_run_manifest,
    read_manifest,
    write_manifest,
)
from photon_ml_tpu.reliability.retry import (  # noqa: F401
    RetryPolicy,
    SeamFailure,
    io_call,
    policy_for,
    quarantine_artifact,
    reliability_metrics,
    reset_retry_stats,
    retry_stats,
)
