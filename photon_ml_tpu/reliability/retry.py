"""Retry / backoff / quarantine policy for the IO seams.

The reference delegates ALL of this to Spark: a failed partition read is
retried by the task scheduler, a lost executor's lineage recomputes
(SURVEY §5.3). The jax_graft stack has no scheduler underneath it, so the
IO seams built over rounds 6-10 (spill stores, schedule cache, async
artifact writes, decode-ahead readers) each handled failure ad hoc or
not at all. This module is the one policy layer they all route through:

- :func:`io_call` — the reliable-call wrapper: one :func:`faults.inject`
  crossing per attempt (chaos runs exercise the retry path
  deterministically), bounded exponential backoff with deterministic
  jitter, per-seam attempt budgets.
- :class:`SeamFailure` — what a seam raises after its budget is spent:
  names the seam AND the artifact, so a failed write can never
  masquerade as success or as some generic stack trace.
- :func:`quarantine_artifact` — the poisoned-artifact protocol: an
  artifact that keeps failing is renamed to ``*.corrupt`` (it stops
  poisoning every future run) and counted; the caller rebuilds from
  source or fails loudly — never a silent drop.

Backoff jitter is deterministic (seeded from seam + attempt), so a chaos
run's retry schedule replays exactly. Delays are intentionally small
(10 ms base) — these seams are local disk, not RPC.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from photon_ml_tpu.reliability.faults import InjectedCorruption, inject

__all__ = [
    "RetryPolicy",
    "SeamFailure",
    "io_call",
    "policy_for",
    "quarantine_artifact",
    "retry_stats",
    "reset_retry_stats",
    "reliability_metrics",
]

ENV_MAX_ATTEMPTS = "PHOTON_RETRY_ATTEMPTS"
ENV_BASE_DELAY = "PHOTON_RETRY_BASE_S"
ENV_BYPASS = "PHOTON_RELIABILITY_BYPASS"


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff: attempt k sleeps
    ``min(base * 2^(k-1), max_delay) * (1 + jitter * u)`` with u a
    deterministic per-(seam, attempt) uniform draw."""

    max_attempts: int = 3
    base_delay_s: float = 0.01
    max_delay_s: float = 0.25
    jitter: float = 0.5
    # Exception classes worth a retry: transient OS/IO errors. ValueError
    # (artifact corruption) is NOT here — re-reading corrupt bytes yields
    # corrupt bytes; that class routes to quarantine instead.
    retryable: Tuple[type, ...] = (OSError, EOFError)


# Per-seam budgets: data-path reads get the deepest budget (losing one
# loses the run), cache seams the shallowest (their fallback is a cheap
# rebuild, not a failure).
_POLICIES: Dict[str, RetryPolicy] = {
    "chunk_read": RetryPolicy(max_attempts=4),
    "spill_write": RetryPolicy(max_attempts=3),
    "spill_read": RetryPolicy(max_attempts=3),
    "cache_load": RetryPolicy(max_attempts=2),
    "cache_store": RetryPolicy(max_attempts=2),
    "ckpt_save": RetryPolicy(max_attempts=3),
    "ckpt_restore": RetryPolicy(max_attempts=3),
    "io_worker": RetryPolicy(max_attempts=3),
    "decode_ahead": RetryPolicy(max_attempts=1),
    # a failed swap load rolls back to the serving generation, so the
    # budget is shallow-ish: three attempts, then keep serving N
    "serving.model_load": RetryPolicy(max_attempts=3),
    # a failed connection read is the CLIENT's problem: one named error
    # response, no retry — the service must not burn dispatcher time on
    # a broken socket
    "serving.frontend.read": RetryPolicy(max_attempts=1),
    # dispatch is pure compute + one readback (idempotent); a transient
    # fault retries bitwise, an exhausted budget fails the batch's
    # futures with the seam-named error
    "serving.dispatch": RetryPolicy(max_attempts=3, base_delay_s=0.002),
    # every publish step is idempotent (stage into a token-unique
    # directory, rename, marker write), so transient faults retry; an
    # exhausted budget aborts the publish with NOTHING visible — the
    # crash-resume path (adopt-or-quarantine of uncommitted dirs)
    # handles the rest
    "registry.publish": RetryPolicy(max_attempts=3),
    # cache seams fall back to a rescan of the partition, so the budget
    # is shallow like the schedule cache's
    "registry.stats_cache": RetryPolicy(max_attempts=2),
}


def policy_for(seam: str) -> RetryPolicy:
    policy = _POLICIES.get(seam, RetryPolicy())
    forced = os.environ.get(ENV_MAX_ATTEMPTS)
    base = os.environ.get(ENV_BASE_DELAY)
    if forced or base:
        from dataclasses import replace

        if forced:
            policy = replace(policy, max_attempts=max(1, int(forced)))
        if base:
            policy = replace(policy, base_delay_s=float(base))
    return policy


class SeamFailure(RuntimeError):
    """A seam exhausted its retry budget. Carries the seam and artifact
    name so the failure is attributable from the driver log alone."""

    def __init__(self, seam: str, detail: str, attempts: int):
        super().__init__(
            f"{seam} failed after {attempts} attempt(s)"
            + (f" on {detail}" if detail else "")
        )
        self.seam = seam
        self.detail = detail
        self.attempts = attempts


# -- stats --------------------------------------------------------------------

_LOCK = threading.Lock()
_ATTEMPTS: Dict[str, int] = {}
_RETRIES: Dict[str, int] = {}
_GIVEUPS: Dict[str, int] = {}
_QUARANTINED: Dict[str, int] = {}
_QUARANTINED_PATHS: List[str] = []


def _note(table: Dict[str, int], seam: str) -> None:
    with _LOCK:
        table[seam] = table.get(seam, 0) + 1


def retry_stats() -> Dict[str, Dict[str, int]]:
    with _LOCK:
        return {
            "attempts": dict(_ATTEMPTS),
            "retries": dict(_RETRIES),
            "giveups": dict(_GIVEUPS),
            "quarantined": dict(_QUARANTINED),
            "quarantined_artifacts": list(_QUARANTINED_PATHS),
        }


def reset_retry_stats() -> None:
    with _LOCK:
        _ATTEMPTS.clear()
        _RETRIES.clear()
        _GIVEUPS.clear()
        _QUARANTINED.clear()
        _QUARANTINED_PATHS.clear()


def reliability_metrics() -> Dict[str, object]:
    """The metrics.json accounting block: fault-injection counters +
    retry/quarantine counters. Every retry and every quarantine a run
    performed is visible here — the chaos matrix asserts against it."""
    from photon_ml_tpu.reliability.faults import fault_stats

    return {"faults": fault_stats(), "retries": retry_stats()}


# -- the reliable-call wrapper ------------------------------------------------


def _bypassed() -> bool:
    return os.environ.get(ENV_BYPASS, "").strip().lower() in (
        "1", "true", "yes",
    )


def _backoff_s(policy: RetryPolicy, seam: str, attempt: int) -> float:
    import random
    import zlib

    delay = min(
        policy.base_delay_s * (2.0 ** (attempt - 1)), policy.max_delay_s
    )
    # crc32, not hash(): the builtin is PYTHONHASHSEED-randomized, so
    # the per-(seam, attempt) jitter schedule — which tests and reruns
    # rely on being reproducible — would differ per process
    seed = zlib.crc32(f"{seam}:{attempt}".encode("utf-8"))
    u = random.Random(seed).random()
    return delay * (1.0 + policy.jitter * u)


def io_call(
    seam: str,
    fn: Callable,
    *args,
    detail: str = "",
    policy: Optional[RetryPolicy] = None,
    **kwargs,
):
    """Run one IO operation behind its seam: fault injection fires per
    ATTEMPT (a planned once-fault exercises the retry; an every-call
    fault exhausts the budget), transient errors back off and retry,
    the budget's end raises :class:`SeamFailure` naming the artifact.

    The wrapped ``fn`` must be idempotent per attempt (seek-then-write,
    whole-file decode, tmp+rename) — every seam in the package is.
    """
    if _bypassed():  # the bench A/B's "layer off" arm — never set in prod
        return fn(*args, **kwargs)
    policy = policy or policy_for(seam)
    attempt = 0
    while True:
        attempt += 1
        _note(_ATTEMPTS, seam)
        try:
            inject(seam, detail=detail)
            return fn(*args, **kwargs)
        except InjectedCorruption:
            raise  # corruption is the caller's quarantine path, not ours
        except policy.retryable as e:
            if attempt >= policy.max_attempts:
                _note(_GIVEUPS, seam)
                raise SeamFailure(seam, detail, attempt) from e
            _note(_RETRIES, seam)
            time.sleep(_backoff_s(policy, seam, attempt))


def quarantine_artifact(path: str, seam: str) -> Optional[str]:
    """Rename a poisoned artifact (file OR directory) to ``*.corrupt``
    so it cannot fail every future run; returns the quarantine path
    (None when the artifact vanished underneath us). Counted per seam
    and listed by name in :func:`reliability_metrics` — quarantines are
    accounted, never silent."""
    if not os.path.exists(path):
        return None
    dst = path + ".corrupt"
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{path}.corrupt-{n}"
    try:
        os.replace(path, dst)
    except OSError:
        # cross-device or permission trouble: fall back to removal — the
        # point is that the next run must not reload the poison
        import shutil

        shutil.rmtree(path, ignore_errors=True) if os.path.isdir(
            path
        ) else os.remove(path)
        dst = path + " (removed)"
    _note(_QUARANTINED, seam)
    with _LOCK:
        _QUARANTINED_PATHS.append(dst)
    return dst
