"""Run/store manifests: the compatibility guard + progress record that
makes crash-safe resume trustworthy.

Two jobs:

- :func:`ensure_run_manifest` — refuse to resume into a directory
  produced by a DIFFERENT run configuration. Resuming foreign weights or
  foreign staged chunks would silently corrupt the result; a changed
  config must get a fresh directory (the GAME driver grew this guard in
  round 6 — this is the shared, atomic version every resume path uses).
- :func:`write_manifest` / :func:`read_manifest` — the per-store
  progress record (staged chunk count, rows consumed, fill-pass flags)
  updated atomically after each completed unit of work, so a ``kill -9``
  leaves either the old manifest or the new one — never a torn record.
  A store without a readable manifest is treated as absent and rebuilt.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

from photon_ml_tpu.reliability.artifacts import atomic_write_json
from photon_ml_tpu.reliability.retry import io_call

__all__ = [
    "MANIFEST_NAME",
    "write_manifest",
    "read_manifest",
    "ensure_run_manifest",
]

MANIFEST_NAME = "manifest.json"


def write_manifest(
    directory: str, payload: Dict[str, object], *, seam: str = "ckpt_save"
) -> None:
    path = os.path.join(directory, MANIFEST_NAME)
    io_call(seam, atomic_write_json, path, payload, detail=path)


def read_manifest(
    directory: str, *, seam: str = "ckpt_restore"
) -> Optional[Dict[str, object]]:
    path = os.path.join(directory, MANIFEST_NAME)
    if not os.path.isfile(path):
        return None

    def _load():
        with open(path) as f:
            return json.load(f)

    try:
        return io_call(seam, _load, detail=path)
    except (ValueError, json.JSONDecodeError):
        # a torn/garbage manifest means the store cannot be trusted:
        # quarantine it (accounted) and rebuild from source
        from photon_ml_tpu.reliability.retry import quarantine_artifact

        quarantine_artifact(path, seam)
        return None


def ensure_run_manifest(
    directory: str, config: Dict[str, object], *, kind: str
) -> Dict[str, object]:
    """Create-or-verify the run manifest: a fresh directory records
    ``config``; an existing one must match it exactly (the resume
    compatibility contract). Returns the manifest on disk. Progress keys
    (anything outside "config"/"kind") are preserved on verify."""
    os.makedirs(directory, exist_ok=True)
    existing = read_manifest(directory)
    if existing is not None:
        if existing.get("kind") != kind or existing.get("config") != config:
            raise ValueError(
                f"{kind} directory {directory} was created by a different "
                "run configuration (inputs, shards, grid, or sequence "
                "changed); point it somewhere fresh or delete it. Recorded "
                f"config: {os.path.join(directory, MANIFEST_NAME)}"
            )
        return existing
    manifest: Dict[str, object] = {"kind": kind, "config": config}
    write_manifest(directory, manifest)
    return manifest
