"""Atomic write-rename for every artifact the drivers emit.

A torn ``metrics.json`` (killed mid-``json.dump``) or a half-written
Avro part file is worse than a missing one: the next stage reads garbage
instead of failing cleanly, and a resumed run trusts it. Every artifact
write in the package goes through these helpers (lint rule PL006
enforces it): the bytes land in a same-directory temp file and
``os.replace`` publishes them — readers see the old file or the whole
new one, never a prefix. Same-directory matters: ``os.replace`` is only
atomic within a filesystem.
"""

from __future__ import annotations

import json
import os
import tempfile
from contextlib import contextmanager
from typing import IO, Iterator

__all__ = [
    "atomic_writer",
    "atomic_write_json",
    "atomic_write_bytes",
    "atomic_write_text",
]


@contextmanager
def atomic_writer(
    path: str, mode: str = "w", **open_kwargs
) -> Iterator[IO]:
    """Open a temp file next to ``path``; rename over it on clean exit,
    unlink the temp on error. ``mode`` is "w" or "wb"."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp", dir=parent
    )
    try:
        with os.fdopen(fd, mode, **open_kwargs) as f:
            yield f
            f.flush()
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:  # photon: allow(PL006) — best-effort tmp cleanup on the error path; the original exception re-raises below
            pass
        raise


def atomic_write_json(path: str, payload, *, indent: int = 2) -> None:
    with atomic_writer(path, "w") as f:
        json.dump(payload, f, indent=indent)


def atomic_write_bytes(path: str, data: bytes) -> None:
    with atomic_writer(path, "wb") as f:
        f.write(data)


def atomic_write_text(path: str, text: str, encoding: str = "utf-8") -> None:
    with atomic_writer(path, "w", encoding=encoding) as f:
        f.write(text)
