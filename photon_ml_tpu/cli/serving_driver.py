"""Online scoring driver: load a GAME model into a device-resident bank
and serve score requests through the micro-batched request path.

Three request sources:

- a replayed Avro trace (the batch scoring driver's own input format,
  which is what makes serving-vs-batch bitwise parity a one-line diff);
- JSON lines on stdin (``--request-paths -``);
- a real TCP network front-end (``--frontend-port``): the JSON-lines
  accept loop from :mod:`photon_ml_tpu.serving.frontend`, with
  admission control, deadlines, readiness/liveness status requests and
  a SIGTERM drain protocol. The bound port (0 = ephemeral) is published
  to ``<output-dir>/frontend.json``.

Two replay load modes:

- ``closed`` (default): one request in flight at a time — the
  single-request latency floor (every dispatch is shape 1).
- ``open``: ``--concurrency N`` submitter threads each run their own
  closed loop over a shared trace iterator — the saturating-load mode
  where the batcher's coalescing fills the ladder.

``--swap-model-dir`` stages a second model generation and flips it
after ``--swap-after-requests`` completions, under live traffic — the
hot-swap demonstration the chaos matrix drives with fault plans.

Lifecycle: SIGTERM (or Ctrl-C) anywhere stops admitting, drains the
batcher within ``--drain-timeout`` (leftover futures fail with the
named ``DRAIN_TIMEOUT`` outcome — never a hang), drains async IO, and
writes metrics.json with an ``interrupted`` marker so a partial run
still accounts for everything it did.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.evaluation import EvaluatorType
from photon_ml_tpu.game.config import FeatureShardConfiguration
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.utils.logging_util import PhotonLogger, Timer

DEFAULT_LADDER_TEXT = "1,8,64,256"


@dataclass
class ServingParams:
    game_model_input_dir: str = ""
    output_dir: str = ""
    # Replay source: an Avro file/dir trace (request_paths) or "-" for
    # JSON lines on stdin.
    request_paths: List[str] = field(default_factory=list)
    feature_shards: List[FeatureShardConfiguration] = field(
        default_factory=list
    )
    task_type: TaskType = TaskType.LOGISTIC_REGRESSION
    model_id: str = ""
    has_response: bool = True
    evaluator_types: List[EvaluatorType] = field(default_factory=list)
    # Prebuilt feature maps (required for stdin; the Avro replay path
    # can fall back to building maps from the trace itself, which is
    # exactly what the batch scorer's in-memory mode does).
    offheap_indexmap_dir: Optional[str] = None
    offheap_indexmap_num_partitions: Optional[int] = None
    feature_name_and_term_set_path: Optional[str] = None
    # Padded micro-batch shape ladder + batching policy.
    ladder: List[int] = field(default_factory=lambda: [1, 8, 64, 256])
    max_wait_ms: float = 0.0
    max_queue: int = 4096
    # Per-shard request nnz width for stdin mode ("shard:k|shard:k" or
    # one integer for all shards); Avro replay derives widths from the
    # trace's padded layout.
    request_nnz_width: Optional[str] = None
    # Load mode.
    mode: str = "closed"
    concurrency: int = 8
    # Hot swap demonstration: stage + flip this model generation after
    # N completed requests.
    swap_model_dir: Optional[str] = None
    swap_after_requests: int = 0
    entity_pad_to: int = 256
    write_scores: bool = True
    delete_output_dir_if_exists: bool = False
    application_name: str = "photon-ml-tpu-serving"
    no_overlap: bool = False
    fault_plan: Optional[str] = None
    # Network front-end (ISSUE 8): serve over a TCP JSON-lines socket
    # instead of replaying a trace. 0 = ephemeral port, published to
    # <output-dir>/frontend.json.
    frontend_host: str = "127.0.0.1"
    frontend_port: Optional[int] = None
    # SIGTERM drain budget: pending requests past it fail with the
    # named DRAIN_TIMEOUT outcome — zero hung futures.
    drain_timeout_s: float = 10.0
    # Admission default: requests that carry no deadline_ms of their
    # own get this one (None = no deadline).
    default_deadline_ms: Optional[float] = None
    # Continuous retraining (registry/): serve the latest committed
    # generation of a model registry and hot-swap newly published ones
    # under live traffic. --auto-rollback flips BACK to the parent
    # generation (bitwise, reloaded from the registry artifact) and
    # quarantines the bad one when the post-swap health window
    # regresses (degraded/shed/error rate over the sliding window).
    registry_dir: Optional[str] = None
    registry_poll_s: float = 2.0
    auto_rollback: bool = True
    rollback_window: int = 64
    rollback_min_requests: int = 16
    rollback_max_unhealthy: float = 0.5
    # Planet-scale serving (ISSUE 12). Shard-server mode: this replica
    # serves ONE entity shard (--shard-index of --shard-count) in
    # partial-score mode with the router control ops attached; topology
    # is published in frontend.json and every status response. Router
    # mode: --shard-servers host:port,... replays the trace through the
    # scatter/gather tier instead of a local bank.
    shard_index: Optional[int] = None
    shard_count: Optional[int] = None
    shard_servers: Optional[str] = None
    hot_cache_entries: int = 4096
    router_subrequest_timeout_ms: float = 2000.0
    router_hedge: bool = True
    # Unified telemetry plane (ISSUE 13): --obs-dir enables request
    # tracing (Chrome trace-event JSON), the live metrics registry
    # ({"op": "metrics"} + periodic atomic snapshots), and the flight
    # recorder (auto-dumped on swap/rollback transitions + at drain).
    obs_dir: Optional[str] = None
    obs_snapshot_s: float = 5.0
    # Device-timeline co-capture: jax.profiler trace over the serve
    # phase (replay AND frontend modes), next to the host spans.
    profile_dir: Optional[str] = None
    # Fleet-scale observability (ISSUE 15). Router mode only:
    # --fleet-obs-dir runs a live FleetCollector over the shard fleet
    # (incremental {"op":"trace"} drains on fresh connections, NTP-style
    # clock-skew normalization) and writes ONE merged fleet_trace.json
    # + fleet_conservation.json at exit.
    fleet_obs_dir: Optional[str] = None
    fleet_poll_s: float = 1.0
    # Declarative SLOs with multi-window burn-rate alerting: inline
    # JSON, @file, or "default". Alerts land on the flight-recorder
    # ring and as registry gauges; with a registry watcher attached the
    # post-swap health judgment consumes the burn-rate state.
    slo: Optional[str] = None
    slo_tick_s: float = 1.0
    # photon-wire (ISSUE 17). Router mode: the data-plane protocol —
    # "binary" requires every shard to advertise photon-wire framing
    # (mismatched fleets are refused at connect), "auto" negotiates
    # binary when the whole fleet speaks it, "json" pins the legacy
    # plane. Frontends always speak BOTH (first-byte sniffing), so the
    # flag only routes the router's own connections. --max-frame-bytes
    # is the shared framing cap (JSON line length == binary frame
    # length; None resolves PHOTON_MAX_FRAME_BYTES, then 1 MiB) —
    # published in frontend.json and every status response.
    wire: str = "auto"
    max_frame_bytes: Optional[int] = None

    @property
    def stdin_mode(self) -> bool:
        return self.request_paths == ["-"]

    @property
    def frontend_mode(self) -> bool:
        return self.frontend_port is not None

    @property
    def shard_mode(self) -> bool:
        return self.shard_index is not None or self.shard_count is not None

    @property
    def router_mode(self) -> bool:
        return bool(self.shard_servers)

    @property
    def entity_shard(self):
        return (
            (self.shard_index, self.shard_count)
            if self.shard_mode
            else None
        )

    @property
    def shard_addresses(self):
        out = []
        for part in (self.shard_servers or "").split(","):
            part = part.strip()
            if not part:
                continue
            host, _, port = part.rpartition(":")
            out.append((host or "127.0.0.1", int(port)))
        return out

    def validate(self) -> None:
        if self.fleet_obs_dir and not self.router_mode:
            raise ValueError(
                "--fleet-obs-dir is the router-side fleet collector; "
                "it requires --shard-servers (router mode)"
            )
        if self.fleet_poll_s <= 0:
            raise ValueError("fleet-poll-s must be > 0")
        if self.wire not in ("json", "binary", "auto"):
            raise ValueError(
                f"--wire must be json|binary|auto, got {self.wire!r}"
            )
        if self.max_frame_bytes is not None and self.max_frame_bytes <= 0:
            raise ValueError("--max-frame-bytes must be positive")
        if self.slo_tick_s <= 0:
            raise ValueError("slo-tick-s must be > 0")
        if self.slo:
            from photon_ml_tpu.obs.slo import parse_slo_specs

            # parse-time rejection: a typo'd spec must fail the launch,
            # not silently alert on nothing
            parse_slo_specs(self.slo)
        if self.shard_mode:
            if self.shard_index is None or self.shard_count is None:
                raise ValueError(
                    "--shard-index and --shard-count go together"
                )
            if not (
                self.shard_count >= 1
                and 0 <= self.shard_index < self.shard_count
            ):
                raise ValueError(
                    f"need 0 <= shard-index < shard-count, got "
                    f"{self.shard_index}/{self.shard_count}"
                )
            if not self.frontend_mode:
                raise ValueError(
                    "a shard-server serves the routing tier over TCP; "
                    "--shard-index requires --frontend-port"
                )
            if self.registry_dir:
                raise ValueError(
                    "--shard-index is incompatible with --registry-dir: "
                    "a watcher-owned swap on one shard would desync the "
                    "fleet's generations — the router coordinates swaps "
                    "through the stage/commit ops"
                )
            if self.swap_model_dir:
                raise ValueError(
                    "--swap-model-dir is incompatible with "
                    "--shard-index: shard generations flip through the "
                    "router's two-step stage/commit protocol"
                )
        if self.router_mode:
            if self.shard_mode:
                raise ValueError(
                    "a process is a shard-server or a router, not both"
                )
            if self.frontend_mode:
                raise ValueError(
                    "router mode replays --request-paths through the "
                    "fleet; it does not serve a frontend itself"
                )
            if self.registry_dir:
                raise ValueError(
                    "router mode coordinates fleet swaps itself; "
                    "--registry-dir is the single-server watcher path"
                )
            if not self.request_paths:
                raise ValueError(
                    "router mode needs --request-paths ('-' for stdin)"
                )
            if not self.shard_addresses:
                raise ValueError(
                    f"unparseable --shard-servers {self.shard_servers!r}"
                )
            if self.swap_model_dir and self.swap_after_requests < 1:
                raise ValueError(
                    "swap-model-dir requires --swap-after-requests >= 1"
                )
            if not self.game_model_input_dir:
                raise ValueError(
                    "router mode needs --game-model-input-dir (the "
                    "router builds its entity->shard index from the "
                    "model's entity universe)"
                )
            if not self.output_dir:
                raise ValueError("output-dir is required")
            if self.mode not in ("closed", "open"):
                raise ValueError(
                    f"mode must be closed|open, got {self.mode!r}"
                )
            if not self.feature_shards:
                raise ValueError(
                    "feature shard configuration is required"
                )
            return  # the bank/ladder rules below are shard-side
        if not self.game_model_input_dir and not self.registry_dir:
            raise ValueError(
                "game-model-input-dir is required (or --registry-dir to "
                "serve the latest committed registry generation)"
            )
        if self.game_model_input_dir and self.registry_dir:
            raise ValueError(
                "choose ONE model source: --game-model-input-dir or "
                "--registry-dir"
            )
        if self.registry_dir and not self.frontend_mode:
            raise ValueError(
                "--registry-dir serves live traffic (the watcher swaps "
                "generations under load); it requires --frontend-port"
            )
        if self.registry_dir and self.swap_model_dir:
            raise ValueError(
                "--swap-model-dir is the manual swap demonstration; "
                "with --registry-dir the watcher owns swaps"
            )
        if self.registry_poll_s <= 0:
            raise ValueError("registry-poll-s must be > 0")
        if not 0 < self.rollback_max_unhealthy <= 1:
            raise ValueError(
                "rollback-max-unhealthy must be in (0, 1]"
            )
        if self.rollback_window < 1 or self.rollback_min_requests < 1:
            raise ValueError(
                "rollback window/min-requests must be >= 1"
            )
        if not self.output_dir:
            raise ValueError("output-dir is required")
        if not self.request_paths and not self.frontend_mode:
            raise ValueError(
                "request-paths is required ('-' for stdin) unless "
                "--frontend-port starts the network front-end"
            )
        if self.frontend_mode and self.request_paths:
            raise ValueError(
                "choose ONE request source: --request-paths (replay) or "
                "--frontend-port (network front-end)"
            )
        if not self.feature_shards:
            raise ValueError("feature shard configuration is required")
        if self.mode not in ("closed", "open"):
            raise ValueError(f"mode must be closed|open, got {self.mode!r}")
        if self.mode == "open" and self.concurrency < 1:
            raise ValueError("concurrency must be >= 1")
        if sorted(set(self.ladder)) != list(self.ladder) or not self.ladder:
            raise ValueError(f"ladder must be increasing: {self.ladder}")
        if self.swap_model_dir and self.swap_after_requests < 1:
            raise ValueError(
                "swap-model-dir requires --swap-after-requests >= 1"
            )
        if self.drain_timeout_s <= 0:
            raise ValueError(
                f"drain-timeout must be > 0, got {self.drain_timeout_s}"
            )
        if (
            self.default_deadline_ms is not None
            and self.default_deadline_ms <= 0
        ):
            raise ValueError(
                "default-deadline-ms must be > 0 when set, got "
                f"{self.default_deadline_ms}"
            )
        if self.stdin_mode or self.frontend_mode:
            source = "stdin" if self.stdin_mode else "front-end"
            if not (
                self.offheap_indexmap_dir
                or self.feature_name_and_term_set_path
            ):
                raise ValueError(
                    f"{source} serving requires prebuilt feature maps "
                    "(--offheap-indexmap-dir or "
                    "--feature-name-and-term-set-path): a request stream "
                    "has no vocabulary to build from"
                )
            if not self.request_nnz_width:
                raise ValueError(
                    f"{source} serving requires --request-nnz-width (the "
                    "fixed per-shard feature width baked into the AOT "
                    "program shapes)"
                )


@dataclass
class _RoutedRequest:
    """Just enough of a ScoreRequest for the score-artifact writer and
    the trace evaluators (router mode routes raw records; nothing else
    needs assembling)."""

    uid: str
    label: Optional[float]
    weight: float
    metadata: Optional[Dict[str, str]]


def _parse_widths(text: str, shard_ids: List[str]) -> Dict[str, int]:
    text = text.strip()
    if "|" not in text and ":" not in text:
        return {sid: int(text) for sid in shard_ids}
    out: Dict[str, int] = {}
    for part in text.split("|"):
        sid, _, k = part.partition(":")
        out[sid.strip()] = int(k)
    missing = [sid for sid in shard_ids if sid not in out]
    if missing:
        raise ValueError(f"request-nnz-width missing shards {missing}")
    return out


class ServingDriver:
    def __init__(self, params: ServingParams, logger=None):
        params.validate()
        self.params = params
        if params.no_overlap:
            from photon_ml_tpu.parallel import overlap

            overlap.set_overlap(False)
        if params.fault_plan:
            from photon_ml_tpu.reliability import install_plan

            install_plan(params.fault_plan)
        from photon_ml_tpu.parallel.multihost import prepare_output_dir

        prepare_output_dir(
            params.output_dir,
            delete_if_exists=params.delete_output_dir_if_exists,
        )
        self.logger = logger or PhotonLogger(params.output_dir)
        self.timer = Timer()
        # --obs-dir: one session owns tracing + registry + flight
        # recorder; the driver's own drain paths call finish() (signal
        # dumps ride the drain protocol, not a second handler)
        from photon_ml_tpu.obs import ObsSession

        self.obs = ObsSession(
            params.obs_dir,
            snapshot_period_s=params.obs_snapshot_s,
            signal_dump=False,
        )
        self.serving_model = None
        self.metrics = None
        self.results: List[float] = []
        # replay interrupt machinery (satellite: SIGTERM/Ctrl-C writes
        # partial accounting instead of losing it)
        self._stop_replay = threading.Event()
        self._closed_scored: List[tuple] = []
        self._open_results: Dict[int, tuple] = {}
        self.drain_report = None
        self.interrupted = False
        # continuous-retraining state (--registry-dir)
        self.registry = None            # registry.ModelRegistry
        self.registry_watcher = None    # registry.RegistryWatcher
        self._registry_generation = None
        # fleet observability (--fleet-obs-dir / --slo)
        self.slo_engine = None          # obs.slo.SLOEngine
        self.fleet_collector = None     # obs.fleet.FleetCollector

    # -- SLO engine (--slo) --------------------------------------------------

    def _start_slo(self, *, router=None):
        """Start the burn-rate engine over the process registry: bind
        the live instruments (serving or router plane), register the
        status view, run the tick thread. Alerts file onto the flight
        ring and surface as slo_* gauges."""
        p = self.params
        if not p.slo:
            return None
        from photon_ml_tpu.obs.flight_recorder import flight_recorder
        from photon_ml_tpu.obs.registry import default_registry
        from photon_ml_tpu.obs.slo import (
            SLOEngine,
            default_router_slos,
            parse_slo_specs,
        )

        registry = self.obs.registry or default_registry()
        if p.slo.strip() == "default" and router is not None:
            specs = default_router_slos()
        else:
            specs = parse_slo_specs(p.slo)
        if router is not None:
            router.metrics.bind_registry(registry)
        elif self.metrics is not None:
            self.metrics.bind_registry(registry)
        engine = SLOEngine(registry, specs, recorder=flight_recorder())
        registry.register_view("slo", engine.status)
        engine.start(period_s=p.slo_tick_s)
        self.slo_engine = engine
        self.logger.info(
            "SLO engine: %d spec(s), tick %.2fs — %s",
            len(specs), p.slo_tick_s,
            ", ".join(s.name for s in specs),
        )
        return engine

    def _finish_slo(self) -> Optional[Dict]:
        if self.slo_engine is None:
            return None
        self.slo_engine.stop()
        return self.slo_engine.status()

    # -- setup ---------------------------------------------------------------

    def _prebuilt_index_maps(self):
        p = self.params
        if p.offheap_indexmap_dir:
            from photon_ml_tpu.utils.native_index import (
                load_offheap_index_maps,
            )

            return load_offheap_index_maps(
                p.offheap_indexmap_dir,
                [cfg.shard_id for cfg in p.feature_shards],
                num_partitions=p.offheap_indexmap_num_partitions,
            )
        if p.feature_name_and_term_set_path:
            from photon_ml_tpu.io.name_term_list import (
                index_maps_from_name_term_lists,
            )

            return index_maps_from_name_term_lists(
                p.feature_name_and_term_set_path, p.feature_shards
            )
        return None

    def _build(self):
        """Load the model artifact (behind the serving.model_load seam),
        resolve feature maps + widths, stage the device bank, AOT-warm
        the whole ladder. Returns the replayable request list."""
        from photon_ml_tpu.serving import (
            ServingModel,
            ServingPrograms,
            build_model_bank,
            load_model_artifact,
            requests_from_dataset,
        )
        from photon_ml_tpu.serving.batcher import request_from_record

        p = self.params
        model_dir = p.game_model_input_dir
        if p.registry_dir:
            from photon_ml_tpu.registry import ModelRegistry

            self.registry = ModelRegistry(p.registry_dir)
            info = self.registry.latest()
            if info is None:
                raise ValueError(
                    f"registry {p.registry_dir} has no committed "
                    "generation to serve"
                )
            self._registry_generation = info
            model_dir = info.model_dir
            self.logger.info(
                "serving registry generation %d (parent %s, gates %s)",
                info.generation, info.parent, info.gate_verdict,
            )
        with self.timer.time("load-model"):
            loaded = load_model_artifact(model_dir)
        id_types = sorted(
            {re_t for re_t, _, _ in loaded.random_effects.values()}
            | {
                t
                for rt, ct, _, _ in loaded.matrix_factorizations.values()
                for t in (rt, ct)
            }
        )
        index_maps = self._prebuilt_index_maps()
        requests = None
        dataset = None
        if p.stdin_mode or p.frontend_mode:
            widths = _parse_widths(
                p.request_nnz_width,
                [cfg.shard_id for cfg in p.feature_shards],
            )
        else:
            with self.timer.time("load-trace"):
                from photon_ml_tpu.game.data import (
                    build_game_dataset_from_files,
                )

                dataset = build_game_dataset_from_files(
                    p.request_paths,
                    p.feature_shards,
                    id_types,
                    index_maps=index_maps,
                    is_response_required=p.has_response,
                )
            if index_maps is None:
                # batch-scorer in-memory parity mode: the trace itself
                # defines the vocabulary
                index_maps = {
                    sid: sd.index_map for sid, sd in dataset.shards.items()
                }
            widths = (
                _parse_widths(
                    p.request_nnz_width,
                    [cfg.shard_id for cfg in p.feature_shards],
                )
                if p.request_nnz_width
                else {
                    sid: sd.indices.shape[1]
                    for sid, sd in dataset.shards.items()
                }
            )
        with self.timer.time("stage-bank"):
            bank = build_model_bank(
                loaded,
                index_maps,
                widths,
                entity_pad_to=p.entity_pad_to,
                model_id=p.model_id,
                entity_shard=p.entity_shard,
            )
        with self.timer.time("warmup-programs"):
            self.serving_model = ServingModel(
                bank,
                ServingPrograms(tuple(p.ladder)),
                partial=p.shard_mode,
                entity_shard=p.entity_shard,
            )
        self.logger.info(
            "bank generation %d staged: %d coordinate(s), %.1f MiB on "
            "device, ladder %s AOT-compiled (%d program(s))%s",
            bank.generation,
            len(bank.spec),
            bank.device_bytes() / (1 << 20),
            tuple(p.ladder),
            self.serving_model.programs.stats()["compiled_programs"],
            (
                f", entity shard {p.shard_index}/{p.shard_count} "
                "(partial-score mode)"
                if p.shard_mode else ""
            ),
        )
        if dataset is not None:
            with self.timer.time("assemble-requests"):
                requests = requests_from_dataset(dataset, bank)
        elif p.stdin_mode:
            def stdin_requests():
                for line in sys.stdin:
                    line = line.strip()
                    if not line:
                        continue
                    yield request_from_record(
                        json.loads(line),
                        bank,
                        p.feature_shards,
                        has_response=p.has_response,
                    )

            requests = stdin_requests()
        # frontend mode: requests arrive over the socket, not here
        return requests

    # -- replay --------------------------------------------------------------

    def _maybe_swap(self, completed: int, swap_once: threading.Lock):
        p = self.params
        if (
            p.swap_model_dir
            and completed >= p.swap_after_requests
            # non-blocking acquire = atomic test-and-set: exactly one
            # thread stages the flip, racers skip past
            and swap_once.acquire(blocking=False)
        ):
            with self.timer.time("hot-swap"):
                res = self.serving_model.stage_and_swap(
                    p.swap_model_dir,
                    entity_pad_to=p.entity_pad_to,
                    model_id=p.model_id,
                )
            self.logger.info(
                "hot swap after %d request(s): ok=%s generation=%d "
                "donated=%s recompiled=%d rolled_back=%s%s",
                completed, res.ok, res.generation, res.donated,
                res.recompiled_programs, res.rolled_back,
                f" quarantined={res.quarantined}" if res.quarantined else "",
            )

    def _score_one(self, batcher, req) -> tuple:
        """One request -> one named terminal outcome: ("ok", score) or
        (outcome_name, None). Sheds, deadline drops, drain failures and
        seam-named dispatch failures are RESULTS of an overloaded or
        draining service, not driver crashes — they are accounted, and
        the replay keeps going."""
        import concurrent.futures

        from photon_ml_tpu.reliability import SeamFailure
        from photon_ml_tpu.serving import (
            DeadlineExceeded,
            RequestShed,
            ServingError,
        )

        try:
            return ("ok", batcher.score(req))
        except RequestShed:
            return ("shed", None)
        except DeadlineExceeded:
            return ("deadline_exceeded", None)
        except ServingError as e:
            return (f"error:{e.code}", None)
        except SeamFailure:
            return ("error:DISPATCH_FAILED", None)
        except concurrent.futures.TimeoutError:
            return ("error:TIMEOUT", None)

    def _replay_closed(self, batcher, requests) -> List[tuple]:
        swap_once = threading.Lock()
        out = self._closed_scored
        for req in requests:
            if self._stop_replay.is_set():
                break
            outcome, score = self._score_one(batcher, req)
            out.append((req, outcome, score))
            self._maybe_swap(len(out), swap_once)
        return out

    def _replay_open(self, batcher, requests) -> List[tuple]:
        """``concurrency`` closed-loop submitters over one shared
        iterator: results keep trace order via their request index."""
        p = self.params
        it = iter(enumerate(requests))
        it_lock = threading.Lock()
        out_lock = threading.Lock()
        swap_once = threading.Lock()
        results = self._open_results
        errors: List[BaseException] = []

        def worker():
            while not self._stop_replay.is_set():
                with it_lock:
                    try:
                        i, req = next(it)
                    except StopIteration:
                        return
                try:
                    outcome, score = self._score_one(batcher, req)
                except BaseException as e:
                    with out_lock:
                        errors.append(e)
                    return
                with out_lock:
                    results[i] = (req, outcome, score)
                    n = len(results)
                self._maybe_swap(n, swap_once)

        threads = [
            threading.Thread(
                target=worker, name=f"photon-serving-load-{t}", daemon=True
            )
            for t in range(p.concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return [results[i] for i in sorted(results)]

    def _partial_results(self) -> List[tuple]:
        """Whatever the replay completed before an interrupt."""
        if self.params.mode == "closed":
            return list(self._closed_scored)
        return [self._open_results[i] for i in sorted(self._open_results)]

    # -- output --------------------------------------------------------------

    def _write_scores(self, scored: List[tuple]) -> None:
        from photon_ml_tpu.io import schemas
        from photon_ml_tpu.io.avro_codec import write_container

        p = self.params

        def records():
            for req, outcome, score in scored:
                if outcome != "ok":
                    continue  # shed/expired/failed: accounted, not scored
                yield {
                    "uid": req.uid,
                    "label": req.label if p.has_response else None,
                    "modelId": p.model_id or "game-model",
                    "predictionScore": float(score),
                    "weight": req.weight,
                    "metadataMap": req.metadata or None,
                }

        write_container(
            os.path.join(p.output_dir, "scores", "part-00000.avro"),
            schemas.SCORING_RESULT_AVRO,
            records(),
        )

    def _evaluate(self, scored: List[tuple]) -> Dict[str, float]:
        """Pointwise trace metrics (AUC/RMSE/losses) over the replayed
        scores — the same evaluator path as the batch driver, on host
        arrays the request loop already paid for."""
        import jax.numpy as jnp

        from photon_ml_tpu.evaluation import Evaluator
        from photon_ml_tpu.ops.losses import loss_for_task

        p = self.params
        out: Dict[str, float] = {}
        ok = [(r, s) for r, outcome, s in scored if outcome == "ok"]
        if not (p.evaluator_types and p.has_response and ok):
            return out
        scores = jnp.asarray(
            np.asarray([s for _, s in ok], np.float32)
        )
        labels = jnp.asarray(
            np.asarray([r.label for r, _ in ok], np.float32)
        )
        weights = jnp.asarray(
            np.asarray([r.weight for r, _ in ok], np.float32)
        )
        loss = loss_for_task(p.task_type)
        for et in p.evaluator_types:
            if et.is_sharded:
                raise ValueError(
                    f"sharded evaluator {et.render()!r} needs global "
                    "per-group data; evaluate with the batch driver"
                )
            metric_in = loss.mean(scores) if et.name == "RMSE" else scores
            value = float(Evaluator(et).evaluate(metric_in, labels, weights))
            out[et.render()] = value
            self.logger.info("%s = %g", et.render(), value)
        return out

    # -- lifecycle -----------------------------------------------------------

    def _install_signal_handlers(self, handler) -> List[tuple]:
        """Install SIGTERM/SIGINT handlers (main thread only — a driver
        constructed inside a test worker skips them); returns what to
        restore."""
        prev = []
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev.append((sig, signal.signal(sig, handler)))
            except ValueError:
                pass  # not the main thread
        return prev

    @staticmethod
    def _restore_signal_handlers(prev: List[tuple]) -> None:
        for sig, old in prev:
            try:
                signal.signal(sig, old)
            except (ValueError, TypeError):
                pass

    def _metrics_extra(self, scored, eval_metrics) -> Dict:
        from photon_ml_tpu.parallel import overlap

        outcomes: Dict[str, int] = {}
        for _req, outcome, _s in scored:
            outcomes[outcome] = outcomes.get(outcome, 0) + 1
        extra = {
            **eval_metrics,
            "mode": (
                "frontend" if self.params.frontend_mode else self.params.mode
            ),
            "interrupted": self.interrupted,
            "generation": self.serving_model.generation,
            "programs": self.serving_model.programs.stats(),
            "readbacks": overlap.readback_stats(),
            "swap_history": [
                {
                    "ok": s.ok,
                    "generation": s.generation,
                    "donated": s.donated,
                    "recompiled_programs": s.recompiled_programs,
                    "rolled_back": s.rolled_back,
                    "quarantined": s.quarantined,
                    "error": s.error,
                }
                for s in self.serving_model.swap_history
            ],
        }
        if outcomes:
            extra["outcomes"] = dict(sorted(outcomes.items()))
        if self.drain_report is not None:
            extra["drain"] = self.drain_report.to_dict()
        slo_status = self._finish_slo()
        if slo_status is not None:
            extra["slo"] = slo_status
        if self.registry_watcher is not None:
            extra["registry"] = {
                **self.registry_watcher.lineage(),
                "watcher_history": [
                    {
                        "action": r.action,
                        "registry_generation": r.registry_generation,
                        "parent": r.parent,
                        "ok": r.ok,
                        "error": r.error,
                    }
                    for r in self.registry_watcher.history
                ],
            }
        elif self.registry is not None:
            extra["registry"] = {
                "registry_path": self.registry.root,
                "registry_generation": (
                    self._registry_generation.generation
                    if self._registry_generation is not None else None
                ),
            }
        return extra

    def run(self) -> None:
        from photon_ml_tpu.parallel import overlap
        from photon_ml_tpu.serving import MicroBatcher, ServingMetrics

        p = self.params
        self.logger.info("application: %s", p.application_name)
        if p.router_mode:
            self._run_router()
            return
        requests = self._build()
        self.metrics = ServingMetrics()
        self.obs.register_view("serving", self.metrics.snapshot)
        self._start_slo()
        overlap.reset_readback_stats()
        batcher = MicroBatcher(
            self.serving_model.current,
            self.serving_model.programs,
            self.metrics,
            max_wait_s=p.max_wait_ms / 1e3,
            max_queue=p.max_queue,
            default_deadline_ms=p.default_deadline_ms,
        )
        if p.frontend_mode:
            self._run_frontend(batcher)
            return

        def _interrupt(signum, frame):
            # raised in the main thread: aborts the replay loop / joins;
            # workers observe _stop_replay and stop submitting
            self._stop_replay.set()
            raise KeyboardInterrupt(f"signal {signum}")

        from photon_ml_tpu.utils.profiling import profile_trace

        prev = self._install_signal_handlers(_interrupt)
        scored = []
        try:
            try:
                # --profile-dir: device timeline over the serve phase,
                # co-captured with the host spans (--obs-dir trace.json)
                with self.timer.time("serve"), profile_trace(p.profile_dir):
                    scored = (
                        self._replay_closed(batcher, requests)
                        if p.mode == "closed"
                        else self._replay_open(batcher, requests)
                    )
            except KeyboardInterrupt:
                # satellite: Ctrl-C / SIGTERM must not lose the
                # accounting — drain within budget, mark the artifact
                self.interrupted = True
                self._stop_replay.set()
                self.logger.info(
                    "interrupted: draining batcher (budget %.1fs)",
                    p.drain_timeout_s,
                )
                self.drain_report = batcher.drain(p.drain_timeout_s)
                scored = self._partial_results()
        finally:
            self._restore_signal_handlers(prev)
            batcher.close()
            overlap.drain_io()
        if not scored and not self.interrupted:
            raise ValueError("empty request trace")
        self.logger.info(
            "served %d request(s) in %s mode%s",
            len(scored), p.mode,
            " (interrupted)" if self.interrupted else "",
        )
        if p.write_scores and scored:
            with self.timer.time("write-scores"):
                self._write_scores(scored)
        eval_metrics = self._evaluate(scored)
        extra = self._metrics_extra(scored, eval_metrics)
        obs_summary = self.obs.finish()
        if obs_summary is not None:
            extra["obs"] = obs_summary
        self.metrics.write(
            os.path.join(p.output_dir, "metrics.json"),
            extra=extra,
        )
        self.results = [s for _, outcome, s in scored if outcome == "ok"]
        self.logger.info("timers:\n%s", self.timer.summary())

    # -- router mode (--shard-servers) ---------------------------------------

    def _router_entity_ids(self, loaded) -> Dict[str, List[str]]:
        """The router's only model state: each id type's FULL sorted
        entity-id universe (position == code == the ownership rule's
        input). No coefficients are ever loaded router-side."""
        entity_ids: Dict[str, List[str]] = {}
        for re_type, _sid, per_entity in loaded.random_effects.values():
            ids = sorted(per_entity)
            prev = entity_ids.get(re_type)
            if prev is not None and prev != ids:
                raise ValueError(
                    f"random-effect coordinates disagree on the "
                    f"{re_type!r} entity set"
                )
            entity_ids[re_type] = ids
        for row_t, col_t, rows, cols in (
            loaded.matrix_factorizations.values()
        ):
            for t, latent in ((row_t, rows), (col_t, cols)):
                entity_ids.setdefault(t, sorted(latent))
        return entity_ids

    def _router_records(self):
        p = self.params
        if p.stdin_mode:
            def stdin_records():
                for line in sys.stdin:
                    line = line.strip()
                    if line:
                        yield json.loads(line)

            return stdin_records()
        from photon_ml_tpu.io.avro_codec import read_avro_records

        out = []
        for path in p.request_paths:
            out.extend(read_avro_records(path))
        return out

    def _route_one(self, router, record) -> tuple:
        from photon_ml_tpu.serving import ServingError

        p = self.params
        try:
            outcome = router.score_record(
                record,
                deadline_ms=record.get("deadline_ms",
                                       p.default_deadline_ms),
            )
            return ("ok", outcome)
        except ServingError as e:
            return (f"error:{e.code}", None)

    def _maybe_router_swap(
        self, router, completed: int, swap_once: threading.Lock
    ) -> None:
        p = self.params
        if (
            p.swap_model_dir
            and completed >= p.swap_after_requests
            and swap_once.acquire(blocking=False)
        ):
            with self.timer.time("router-swap"):
                res = router.coordinate_swap(p.swap_model_dir)
            self._router_swap_result = res
            self.logger.info(
                "router-coordinated two-step swap after %d request(s): "
                "%s", completed, res,
            )

    def _run_router(self) -> None:
        """Replay the trace through the scatter/gather tier: the driver
        is the THIN router — no device bank, no programs, just the
        entity->shard index and the fleet connections. Bitwise vs the
        single-server replay is the acceptance bar; a mid-replay
        --swap-model-dir runs the two-step fleet flip."""
        from photon_ml_tpu.game.data import record_response
        from photon_ml_tpu.parallel import overlap
        from photon_ml_tpu.reliability import (
            atomic_write_json,
            reliability_metrics,
        )
        from photon_ml_tpu.serving import (
            RoutingPolicy,
            ShardRouter,
        )
        from photon_ml_tpu.serving.swap import load_model_artifact

        p = self.params
        with self.timer.time("load-model"):
            loaded = load_model_artifact(p.game_model_input_dir)
        router = ShardRouter(
            p.shard_addresses,
            entity_ids=self._router_entity_ids(loaded),
            shard_configs=p.feature_shards,
            policy=RoutingPolicy(
                hedge=p.router_hedge,
                subrequest_timeout_s=(
                    p.router_subrequest_timeout_ms / 1e3
                ),
            ),
            cache_entries=p.hot_cache_entries,
            wire=p.wire,
        )
        with self.timer.time("connect-fleet"):
            info = router.connect()
        self.obs.register_view("routing", router.status)
        self.logger.info(
            "routing over %d shard-server(s), fleet generation %d, "
            "%s wire", info["shards"], info["generation"], info["wire"],
        )
        self._start_slo(router=router)
        if p.fleet_obs_dir:
            os.makedirs(p.fleet_obs_dir, exist_ok=True)
            from photon_ml_tpu.obs.fleet import FleetCollector

            # the live fleet collector: incremental {"op":"trace"}
            # drains over fresh connections against every shard, plus
            # the router's own local spans — one merged timeline
            self.fleet_collector = FleetCollector(
                [
                    (f"shard{i}", h, pt)
                    for i, (h, pt) in enumerate(p.shard_addresses)
                ],
                local_name="router",
                poll_s=p.fleet_poll_s,
            ).start()
            self.logger.info(
                "fleet collector polling %d shard(s) every %.2fs -> %s",
                len(p.shard_addresses), p.fleet_poll_s, p.fleet_obs_dir,
            )
        self._router_swap_result = None
        records = self._router_records()
        swap_once = threading.Lock()
        scored: List[tuple] = []
        out_lock = threading.Lock()

        def _interrupt(signum, frame):
            self._stop_replay.set()
            raise KeyboardInterrupt(f"signal {signum}")

        from photon_ml_tpu.utils.profiling import profile_trace

        prev = self._install_signal_handlers(_interrupt)
        try:
            try:
                with self.timer.time("serve"), profile_trace(p.profile_dir):
                    if p.mode == "closed":
                        for rec in records:
                            if self._stop_replay.is_set():
                                break
                            outcome, score = self._route_one(router, rec)
                            scored.append((rec, outcome, score))
                            self._maybe_router_swap(
                                router, len(scored), swap_once
                            )
                    else:
                        it = iter(enumerate(records))
                        it_lock = threading.Lock()
                        results: Dict[int, tuple] = {}
                        errors: List[BaseException] = []

                        def worker():
                            while not self._stop_replay.is_set():
                                with it_lock:
                                    try:
                                        i, rec = next(it)
                                    except StopIteration:
                                        return
                                try:
                                    outcome, score = self._route_one(
                                        router, rec
                                    )
                                except BaseException as e:
                                    with out_lock:
                                        errors.append(e)
                                    return
                                with out_lock:
                                    results[i] = (rec, outcome, score)
                                    n = len(results)
                                self._maybe_router_swap(
                                    router, n, swap_once
                                )

                        threads = [
                            threading.Thread(
                                target=worker,
                                name=f"photon-router-load-{t}",
                                daemon=True,
                            )
                            for t in range(p.concurrency)
                        ]
                        for t in threads:
                            t.start()
                        for t in threads:
                            t.join()
                        if errors:
                            raise errors[0]
                        scored = [results[i] for i in sorted(results)]
            except KeyboardInterrupt:
                self.interrupted = True
                self._stop_replay.set()
        finally:
            self._restore_signal_handlers(prev)
            router.close()
            overlap.drain_io()
        if not scored and not self.interrupted:
            raise ValueError("empty request trace")
        self.logger.info(
            "routed %d request(s) in %s mode%s",
            len(scored), p.mode,
            " (interrupted)" if self.interrupted else "",
        )
        outcomes: Dict[str, int] = {}
        for _rec, outcome, _s in scored:
            outcomes[outcome] = outcomes.get(outcome, 0) + 1
        if p.write_scores and scored:
            id_types = sorted(router._indexes)

            def shim(rec):
                meta = {
                    t: str(v) for t, v in (
                        (t, rec.get(t) or (rec.get("metadataMap") or {})
                         .get(t))
                        for t in id_types
                    ) if v is not None
                }
                return _RoutedRequest(
                    uid=str(rec.get("uid") or ""),
                    label=(
                        record_response(rec, True)
                        if p.has_response else None
                    ),
                    weight=(
                        1.0 if rec.get("weight") is None
                        else float(rec["weight"])
                    ),
                    metadata=meta or None,
                )

            with self.timer.time("write-scores"):
                self._write_scores([
                    (shim(rec), outcome, score)
                    for rec, outcome, score in scored
                ])
        status = router.status()
        degraded = sum(
            1 for _r, o, s in scored
            if o == "ok" and getattr(s, "degraded", False)
        )
        fleet_block = self._finish_fleet_obs()
        slo_status = self._finish_slo()
        obs_summary = self.obs.finish()
        atomic_write_json(
            os.path.join(p.output_dir, "metrics.json"),
            {
                "mode": "router",
                **({"obs": obs_summary} if obs_summary else {}),
                **({"fleet_obs": fleet_block} if fleet_block else {}),
                **({"slo": slo_status} if slo_status else {}),
                "interrupted": self.interrupted,
                "outcomes": dict(sorted(outcomes.items())),
                "degraded_responses": degraded,
                "generation": router.generation,
                "routing": status,
                "swap": self._router_swap_result,
                "shard_servers": [
                    f"{h}:{pt}" for h, pt in p.shard_addresses
                ],
                "reliability": reliability_metrics(),
            },
        )
        self.results = [
            s for _r, outcome, s in scored if outcome == "ok"
        ]
        self.logger.info("timers:\n%s", self.timer.summary())

    def _finish_fleet_obs(self) -> Optional[Dict]:
        """Stop the collector (one final drain poll), fetch every
        member's flight book, write fleet_trace.json +
        fleet_conservation.json, and return the metrics.json block."""
        if self.fleet_collector is None:
            return None
        from photon_ml_tpu.obs.fleet import fleet_check_conservation
        from photon_ml_tpu.reliability import atomic_write_json

        p = self.params
        collector = self.fleet_collector
        collector.stop()
        flight = collector.collect_flight()
        books = {
            f"shard{i}": {
                "conservation": (
                    flight.get(f"shard{i}", {}).get("conservation") or {}
                ),
                "complete": bool(
                    flight.get(f"shard{i}", {}).get("complete")
                ),
                "shard_indices": [i],
            }
            for i in range(len(p.shard_addresses))
        }
        router_book = (
            flight.get("router", {}).get("conservation") or {}
        )
        conservation = fleet_check_conservation(router_book, books)
        trace_path = os.path.join(p.fleet_obs_dir, "fleet_trace.json")
        n_events = collector.export(
            trace_path, extra={"conservation_ok": conservation["ok"]}
        )
        atomic_write_json(
            os.path.join(p.fleet_obs_dir, "fleet_conservation.json"),
            conservation,
        )
        self.logger.info(
            "fleet obs: %d merged trace event(s) -> %s; conservation "
            "%s", n_events, trace_path,
            "OK" if conservation["ok"] else "VIOLATED",
        )
        return {
            "fleet_obs_dir": p.fleet_obs_dir,
            "fleet_trace_path": trace_path,
            "trace_events": n_events,
            "members": collector.member_status(),
            "conservation": conservation,
        }

    def _run_frontend(self, batcher) -> None:
        """Network-serving main loop: publish the bound port, serve
        until SIGTERM/SIGINT, then the drain protocol — stop accepting,
        drain the batcher within ``--drain-timeout`` (leftovers fail
        with DRAIN_TIMEOUT), flush + close every connection, write
        metrics.json with the interrupted marker."""
        from photon_ml_tpu.parallel import overlap
        from photon_ml_tpu.reliability import atomic_write_json
        from photon_ml_tpu.serving import ServingFrontend
        from photon_ml_tpu.serving.wire import (
            WIRE_PROTOCOLS as wire_protocols,
            WIRE_VERSION as wire_version,
        )

        p = self.params
        swap_once = threading.Lock()
        on_completion = (
            (lambda n: self._maybe_swap(n, swap_once))
            if p.swap_model_dir
            else None
        )
        on_outcome = None
        lineage_provider = None
        rollback_handler = None
        if self.registry is not None:
            from photon_ml_tpu.registry import (
                RegistryWatcher,
                RollbackPolicy,
            )

            self.registry_watcher = RegistryWatcher(
                self.registry,
                self.serving_model,
                poll_s=p.registry_poll_s,
                policy=RollbackPolicy(
                    window=p.rollback_window,
                    min_requests=p.rollback_min_requests,
                    max_unhealthy_rate=p.rollback_max_unhealthy,
                ),
                auto_rollback=p.auto_rollback,
                swap_kwargs={
                    "entity_pad_to": p.entity_pad_to,
                    "model_id": p.model_id,
                },
                logger=self.logger,
                initial_generation=self._registry_generation,
                # --slo: the post-swap health judgment consumes the
                # burn-rate alert state instead of raw error fractions
                burn_gate=(
                    self.slo_engine.any_alert_active
                    if self.slo_engine is not None
                    else None
                ),
            ).start()
            on_outcome = (
                lambda ok, degraded, failed:
                self.registry_watcher.observe_outcome(
                    degraded=degraded, failed=failed
                )
            )
            lineage_provider = self.registry_watcher.lineage
            rollback_handler = self.registry_watcher.rollback
        extra_ops = None
        status_extra = None
        shard_block = None
        if p.shard_mode:
            from photon_ml_tpu.serving import make_shard_ops, shard_topology

            extra_ops = make_shard_ops(
                self.serving_model,
                p.entity_shard,
                swap_kwargs={
                    "entity_pad_to": p.entity_pad_to,
                    "model_id": p.model_id,
                },
            )
            status_extra = lambda: {  # noqa: E731
                "shard": shard_topology(self.serving_model, p.entity_shard)
            }
            shard_block = shard_topology(self.serving_model, p.entity_shard)
        frontend = ServingFrontend(
            batcher,
            self.serving_model,
            p.feature_shards,
            metrics=self.metrics,
            host=p.frontend_host,
            port=p.frontend_port,
            has_response=p.has_response,
            max_frame_bytes=p.max_frame_bytes,
            on_completion=on_completion,
            on_outcome=on_outcome,
            lineage_provider=lineage_provider,
            rollback_handler=rollback_handler,
            extra_ops=extra_ops,
            status_extra=status_extra,
            metrics_registry=self.obs.registry,
            flight_dump_path=(
                self.obs.flight_path if self.obs.enabled else None
            ),
        )
        frontend.start()
        atomic_write_json(
            os.path.join(p.output_dir, "frontend.json"),
            {  # photon: entropy(discovery artifact; pid names the live process for operators and chaos arms)
                "host": p.frontend_host,
                "port": frontend.port,
                "pid": os.getpid(),
                # the registry this replica follows (null when serving
                # a fixed artifact): operators and the chaos arms read
                # it to publish/poke the SAME lineage the service sees
                "registry": (
                    self.registry.root if self.registry is not None
                    else None
                ),
                # shard topology (null off the routing tier): how the
                # router — and any operator — discovers the fleet
                # layout without out-of-band config
                "shard": shard_block,
                # the wire contract this frontend enforces: protocols
                # spoken on the port (both, via first-byte sniffing)
                # and the shared JSON-line/binary-frame cap
                "wire": {
                    "protocols": list(wire_protocols),
                    "version": wire_version,
                    "max_frame_bytes": frontend.max_frame_bytes,
                },
            },
        )
        self.logger.info(
            "front-end listening on %s:%d (drain budget %.1fs)",
            p.frontend_host, frontend.port, p.drain_timeout_s,
        )
        from photon_ml_tpu.utils.profiling import profile_trace

        shutdown = threading.Event()
        prev = self._install_signal_handlers(
            lambda signum, frame: shutdown.set()
        )
        try:
            try:
                # --profile-dir: the device timeline of everything the
                # dispatcher runs while the frontend serves (the trace
                # closes at SIGTERM, before the drain)
                with profile_trace(p.profile_dir):
                    while not shutdown.wait(timeout=0.2):
                        pass
            except KeyboardInterrupt:
                pass
            self.interrupted = True
            with self.timer.time("drain"):
                if self.registry_watcher is not None:
                    # stop promoting before the drain: a swap staged
                    # into a draining batcher would never serve
                    self.registry_watcher.stop()
                frontend.stop_accepting()
                self.drain_report = batcher.drain(p.drain_timeout_s)
                frontend.close()
        finally:
            self._restore_signal_handlers(prev)
            if self.registry_watcher is not None:
                self.registry_watcher.stop()
            batcher.close()
            overlap.drain_io()
        leaked = frontend.open_connections()
        self.logger.info(
            "drained: %s; open connections after close: %d",
            self.drain_report.to_dict(), leaked,
        )
        extra = {
            **self._metrics_extra([], {}),
            "frontend_completed": frontend.completed(),
            "leaked_connections": leaked,
        }
        obs_summary = self.obs.finish(reason="drain")
        if obs_summary is not None:
            extra["obs"] = obs_summary
        self.metrics.write(
            os.path.join(p.output_dir, "metrics.json"),
            extra=extra,
        )
        self.logger.info("timers:\n%s", self.timer.summary())


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="photon-ml-tpu serving")
    ap.add_argument(
        "--game-model-input-dir", default=None,
        help="GAME model artifact to serve (or --registry-dir to "
        "follow a model registry's committed generations)",
    )
    ap.add_argument("--output-dir", required=True)
    ap.add_argument(
        "--request-paths", default=None,
        help="Avro trace file(s)/dir(s), comma-separated, or '-' for "
        "JSON-lines requests on stdin (omit when --frontend-port serves "
        "over the network)",
    )
    ap.add_argument(
        "--feature-shard-id-to-feature-section-keys-map", required=True
    )
    ap.add_argument("--feature-shard-id-to-intercept-map", default=None)
    ap.add_argument("--task-type", default="LOGISTIC_REGRESSION")
    ap.add_argument("--evaluator-types", default=None)
    ap.add_argument("--game-model-id", default=None)
    ap.add_argument("--has-response", default="true")
    ap.add_argument("--offheap-indexmap-dir", default=None)
    ap.add_argument(
        "--offheap-indexmap-num-partitions", type=int, default=None
    )
    ap.add_argument("--feature-name-and-term-set-path", default=None)
    ap.add_argument(
        "--ladder", default=DEFAULT_LADDER_TEXT,
        help="padded micro-batch shapes, comma-separated increasing "
        f"(default {DEFAULT_LADDER_TEXT}); every shape AOT-compiles at "
        "startup",
    )
    ap.add_argument(
        "--max-wait-ms", type=float, default=0.0,
        help="linger for coalescing before dispatching a partial batch "
        "(0 = continuous batching: dispatch whatever accumulated)",
    )
    ap.add_argument("--max-queue", type=int, default=4096)
    ap.add_argument(
        "--request-nnz-width", default=None,
        help="per-shard request feature width ('shard:k|shard:k' or one "
        "int for all); required for stdin, defaults to the trace's "
        "padded width for Avro replay",
    )
    ap.add_argument(
        "--mode", default="closed",
        help="closed = one request in flight (latency floor); open = "
        "--concurrency submitter threads (saturating load)",
    )
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument(
        "--swap-model-dir", default=None,
        help="stage + hot-swap this model generation mid-replay",
    )
    ap.add_argument("--swap-after-requests", type=int, default=0)
    ap.add_argument("--entity-pad-to", type=int, default=256)
    ap.add_argument("--write-scores", default="true")
    ap.add_argument("--delete-output-dir-if-exists", default="false")
    ap.add_argument("--application-name", default=None)
    ap.add_argument(
        "--no-overlap", default="false",
        help="disable the host-device overlap layer (A/B baseline)",
    )
    ap.add_argument(
        "--fault-plan", default=None,
        help="deterministic fault injection "
        "(seam:nth:error[:times], comma-separated); also via "
        "PHOTON_FAULT_PLAN",
    )
    ap.add_argument("--frontend-host", default="127.0.0.1")
    ap.add_argument(
        "--frontend-port", type=int, default=None,
        help="serve over a TCP JSON-lines front-end on this port "
        "(0 = ephemeral; the bound port is published to "
        "<output-dir>/frontend.json); SIGTERM drains and exits",
    )
    ap.add_argument(
        "--drain-timeout", type=float, default=10.0,
        help="seconds to finish pending requests on SIGTERM/Ctrl-C; "
        "leftovers fail with the named DRAIN_TIMEOUT outcome",
    )
    ap.add_argument(
        "--default-deadline-ms", type=float, default=None,
        help="deadline applied to requests that carry none of their "
        "own; enables load shedding under overload",
    )
    ap.add_argument(
        "--registry-dir", default=None,
        help="model-registry directory: serve its latest committed "
        "generation and hot-swap newly published ones under live "
        "traffic (requires --frontend-port; the registry path is "
        "published to frontend.json)",
    )
    ap.add_argument(
        "--registry-poll-s", type=float, default=2.0,
        help="registry poll period for the generation watcher",
    )
    ap.add_argument(
        "--auto-rollback", default="true",
        help="roll back to the parent generation (bitwise) and "
        "quarantine the bad one when the post-swap health window "
        "regresses",
    )
    ap.add_argument(
        "--rollback-window", type=int, default=64,
        help="sliding window of post-swap completions judged for "
        "auto-rollback",
    )
    ap.add_argument(
        "--rollback-min-requests", type=int, default=16,
        help="minimum post-swap completions before auto-rollback can "
        "trigger",
    )
    ap.add_argument(
        "--rollback-max-unhealthy", type=float, default=0.5,
        help="auto-rollback when (degraded+shed+errors)/window exceeds "
        "this rate",
    )
    ap.add_argument(
        "--shard-index", type=int, default=None,
        help="serve ONE entity shard of the model (0-based) in "
        "partial-score mode for the routing tier; requires "
        "--shard-count and --frontend-port",
    )
    ap.add_argument(
        "--shard-count", type=int, default=None,
        help="total shard-servers in the fleet (the N of the "
        "entity_code %% N ownership rule)",
    )
    ap.add_argument(
        "--shard-servers", default=None,
        help="router mode: comma-separated host:port shard-servers; "
        "the trace replays through the scatter/gather tier instead of "
        "a local bank (--swap-model-dir runs the two-step fleet flip)",
    )
    ap.add_argument(
        "--hot-cache-entries", type=int, default=4096,
        help="router hot-entity cache capacity (generation-keyed LRU "
        "of partial scores; 0 disables)",
    )
    ap.add_argument(
        "--router-subrequest-timeout-ms", type=float, default=2000.0,
        help="per-shard sub-request budget for deadline-less requests",
    )
    ap.add_argument(
        "--router-hedge", default="true",
        help="hedge a slow shard once on a fresh connection inside the "
        "remaining budget before shedding it (FE-only for its "
        "entities)",
    )
    ap.add_argument(
        "--obs-dir", default=None,
        help="unified telemetry: enable request tracing + the live "
        "metrics registry + the flight recorder; trace.json / "
        "flight.json / metrics_snapshot.json land here atomically "
        "(also exposed live via the {\"op\": \"metrics\"} and "
        "{\"op\": \"flight\"} control ops)",
    )
    ap.add_argument(
        "--obs-snapshot-s", type=float, default=5.0,
        help="period of the --obs-dir metrics snapshot writer",
    )
    ap.add_argument(
        "--profile-dir", default=None,
        help="jax.profiler device-timeline trace over the serve phase "
        "(replay, frontend and router modes) — co-captured with the "
        "--obs-dir host spans",
    )
    ap.add_argument(
        "--fleet-obs-dir", default=None,
        help="router mode: run the live fleet collector (incremental "
        "{\"op\": \"trace\"} drains over fresh connections, clock-skew "
        "normalized) and write ONE merged fleet_trace.json + "
        "fleet_conservation.json here at exit",
    )
    ap.add_argument(
        "--fleet-poll-s", type=float, default=1.0,
        help="fleet collector poll period",
    )
    ap.add_argument(
        "--slo", default=None,
        help="declarative SLOs with multi-window burn-rate alerting: "
        "inline JSON (object or list of {name, objective, kind, "
        "metric, ...}), @file, or 'default'; alerts land on the "
        "flight-recorder ring and as slo_* registry gauges, and a "
        "registry watcher consumes the burn-rate state for its "
        "post-swap health judgment",
    )
    ap.add_argument(
        "--slo-tick-s", type=float, default=1.0,
        help="SLO engine evaluation period",
    )
    ap.add_argument(
        "--wire", default="auto", choices=("json", "binary", "auto"),
        help="router data-plane protocol: binary requires every shard "
        "to advertise photon-wire framing (mismatches refused at "
        "connect), auto negotiates it fleet-wide, json pins the "
        "legacy JSON-lines plane; frontends always speak both via "
        "first-byte sniffing",
    )
    ap.add_argument(
        "--max-frame-bytes", type=int, default=None,
        help="framing cap enforced identically for JSON line lengths "
        "and binary frame lengths (default: PHOTON_MAX_FRAME_BYTES "
        "env, then 1 MiB); published in frontend.json and every "
        "status response",
    )
    return ap


def params_from_args(argv=None) -> ServingParams:
    from photon_ml_tpu.cli.game_training_driver import (
        apply_intercept_map,
        parse_shard_map,
    )

    ns = build_arg_parser().parse_args(argv)

    def truthy(s) -> bool:
        return str(s).lower() in ("true", "1", "yes")

    return ServingParams(
        game_model_input_dir=ns.game_model_input_dir or "",
        output_dir=ns.output_dir,
        request_paths=(
            []
            if ns.request_paths is None
            else ["-"]
            if ns.request_paths.strip() == "-"
            else ns.request_paths.split(",")
        ),
        feature_shards=apply_intercept_map(
            parse_shard_map(ns.feature_shard_id_to_feature_section_keys_map),
            ns.feature_shard_id_to_intercept_map,
        ),
        task_type=TaskType.parse(ns.task_type),
        evaluator_types=(
            [EvaluatorType.parse(s) for s in ns.evaluator_types.split(",")]
            if ns.evaluator_types
            else []
        ),
        model_id=ns.game_model_id or "",
        has_response=truthy(ns.has_response),
        offheap_indexmap_dir=ns.offheap_indexmap_dir,
        offheap_indexmap_num_partitions=ns.offheap_indexmap_num_partitions,
        feature_name_and_term_set_path=ns.feature_name_and_term_set_path,
        ladder=[int(b) for b in ns.ladder.split(",")],
        max_wait_ms=ns.max_wait_ms,
        max_queue=ns.max_queue,
        request_nnz_width=ns.request_nnz_width,
        mode=ns.mode,
        concurrency=ns.concurrency,
        swap_model_dir=ns.swap_model_dir,
        swap_after_requests=ns.swap_after_requests,
        entity_pad_to=ns.entity_pad_to,
        write_scores=truthy(ns.write_scores),
        delete_output_dir_if_exists=truthy(ns.delete_output_dir_if_exists),
        application_name=ns.application_name or "photon-ml-tpu-serving",
        no_overlap=truthy(ns.no_overlap),
        fault_plan=ns.fault_plan,
        frontend_host=ns.frontend_host,
        frontend_port=ns.frontend_port,
        drain_timeout_s=ns.drain_timeout,
        default_deadline_ms=ns.default_deadline_ms,
        registry_dir=ns.registry_dir,
        registry_poll_s=ns.registry_poll_s,
        auto_rollback=truthy(ns.auto_rollback),
        rollback_window=ns.rollback_window,
        rollback_min_requests=ns.rollback_min_requests,
        rollback_max_unhealthy=ns.rollback_max_unhealthy,
        shard_index=ns.shard_index,
        shard_count=ns.shard_count,
        shard_servers=ns.shard_servers,
        hot_cache_entries=ns.hot_cache_entries,
        router_subrequest_timeout_ms=ns.router_subrequest_timeout_ms,
        router_hedge=truthy(ns.router_hedge),
        obs_dir=ns.obs_dir,
        obs_snapshot_s=ns.obs_snapshot_s,
        profile_dir=ns.profile_dir,
        fleet_obs_dir=ns.fleet_obs_dir,
        wire=ns.wire,
        max_frame_bytes=ns.max_frame_bytes,
        fleet_poll_s=ns.fleet_poll_s,
        slo=ns.slo,
        slo_tick_s=ns.slo_tick_s,
    )


def main(argv=None) -> None:
    ServingDriver(params_from_args(argv)).run()


if __name__ == "__main__":
    main()
