"""Online scoring driver: load a GAME model into a device-resident bank
and serve score requests through the micro-batched request path.

The request source is a replayed trace — an Avro file/dir (the batch
scoring driver's own input format, which is what makes serving-vs-batch
bitwise parity a one-line diff) or JSON lines on stdin — so the driver
exercises the full serving stack (bank, AOT ladder, batcher, hot swap,
metrics) with no network dependency. A production front-end would
replace the trace reader with a socket accept loop; everything behind
``MicroBatcher.submit`` stays the same.

Two load modes:

- ``closed`` (default): one request in flight at a time — the
  single-request latency floor (every dispatch is shape 1).
- ``open``: ``--concurrency N`` submitter threads each run their own
  closed loop over a shared trace iterator — the saturating-load mode
  where the batcher's coalescing fills the ladder.

``--swap-model-dir`` stages a second model generation and flips it
after ``--swap-after-requests`` completions, under live traffic — the
hot-swap demonstration the chaos matrix drives with fault plans.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.evaluation import EvaluatorType
from photon_ml_tpu.game.config import FeatureShardConfiguration
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.utils.logging_util import PhotonLogger, Timer

DEFAULT_LADDER_TEXT = "1,8,64,256"


@dataclass
class ServingParams:
    game_model_input_dir: str = ""
    output_dir: str = ""
    # Replay source: an Avro file/dir trace (request_paths) or "-" for
    # JSON lines on stdin.
    request_paths: List[str] = field(default_factory=list)
    feature_shards: List[FeatureShardConfiguration] = field(
        default_factory=list
    )
    task_type: TaskType = TaskType.LOGISTIC_REGRESSION
    model_id: str = ""
    has_response: bool = True
    evaluator_types: List[EvaluatorType] = field(default_factory=list)
    # Prebuilt feature maps (required for stdin; the Avro replay path
    # can fall back to building maps from the trace itself, which is
    # exactly what the batch scorer's in-memory mode does).
    offheap_indexmap_dir: Optional[str] = None
    offheap_indexmap_num_partitions: Optional[int] = None
    feature_name_and_term_set_path: Optional[str] = None
    # Padded micro-batch shape ladder + batching policy.
    ladder: List[int] = field(default_factory=lambda: [1, 8, 64, 256])
    max_wait_ms: float = 0.0
    max_queue: int = 4096
    # Per-shard request nnz width for stdin mode ("shard:k|shard:k" or
    # one integer for all shards); Avro replay derives widths from the
    # trace's padded layout.
    request_nnz_width: Optional[str] = None
    # Load mode.
    mode: str = "closed"
    concurrency: int = 8
    # Hot swap demonstration: stage + flip this model generation after
    # N completed requests.
    swap_model_dir: Optional[str] = None
    swap_after_requests: int = 0
    entity_pad_to: int = 256
    write_scores: bool = True
    delete_output_dir_if_exists: bool = False
    application_name: str = "photon-ml-tpu-serving"
    no_overlap: bool = False
    fault_plan: Optional[str] = None

    @property
    def stdin_mode(self) -> bool:
        return self.request_paths == ["-"]

    def validate(self) -> None:
        if not self.game_model_input_dir:
            raise ValueError("game-model-input-dir is required")
        if not self.output_dir:
            raise ValueError("output-dir is required")
        if not self.request_paths:
            raise ValueError("request-paths is required ('-' for stdin)")
        if not self.feature_shards:
            raise ValueError("feature shard configuration is required")
        if self.mode not in ("closed", "open"):
            raise ValueError(f"mode must be closed|open, got {self.mode!r}")
        if self.mode == "open" and self.concurrency < 1:
            raise ValueError("concurrency must be >= 1")
        if sorted(set(self.ladder)) != list(self.ladder) or not self.ladder:
            raise ValueError(f"ladder must be increasing: {self.ladder}")
        if self.swap_model_dir and self.swap_after_requests < 1:
            raise ValueError(
                "swap-model-dir requires --swap-after-requests >= 1"
            )
        if self.stdin_mode:
            if not (
                self.offheap_indexmap_dir
                or self.feature_name_and_term_set_path
            ):
                raise ValueError(
                    "stdin serving requires prebuilt feature maps "
                    "(--offheap-indexmap-dir or "
                    "--feature-name-and-term-set-path): a request stream "
                    "has no vocabulary to build from"
                )
            if not self.request_nnz_width:
                raise ValueError(
                    "stdin serving requires --request-nnz-width (the "
                    "fixed per-shard feature width baked into the AOT "
                    "program shapes)"
                )


def _parse_widths(text: str, shard_ids: List[str]) -> Dict[str, int]:
    text = text.strip()
    if "|" not in text and ":" not in text:
        return {sid: int(text) for sid in shard_ids}
    out: Dict[str, int] = {}
    for part in text.split("|"):
        sid, _, k = part.partition(":")
        out[sid.strip()] = int(k)
    missing = [sid for sid in shard_ids if sid not in out]
    if missing:
        raise ValueError(f"request-nnz-width missing shards {missing}")
    return out


class ServingDriver:
    def __init__(self, params: ServingParams, logger=None):
        params.validate()
        self.params = params
        if params.no_overlap:
            from photon_ml_tpu.parallel import overlap

            overlap.set_overlap(False)
        if params.fault_plan:
            from photon_ml_tpu.reliability import install_plan

            install_plan(params.fault_plan)
        from photon_ml_tpu.parallel.multihost import prepare_output_dir

        prepare_output_dir(
            params.output_dir,
            delete_if_exists=params.delete_output_dir_if_exists,
        )
        self.logger = logger or PhotonLogger(params.output_dir)
        self.timer = Timer()
        self.serving_model = None
        self.metrics = None
        self.results: List[float] = []

    # -- setup ---------------------------------------------------------------

    def _prebuilt_index_maps(self):
        p = self.params
        if p.offheap_indexmap_dir:
            from photon_ml_tpu.utils.native_index import (
                load_offheap_index_maps,
            )

            return load_offheap_index_maps(
                p.offheap_indexmap_dir,
                [cfg.shard_id for cfg in p.feature_shards],
                num_partitions=p.offheap_indexmap_num_partitions,
            )
        if p.feature_name_and_term_set_path:
            from photon_ml_tpu.io.name_term_list import (
                index_maps_from_name_term_lists,
            )

            return index_maps_from_name_term_lists(
                p.feature_name_and_term_set_path, p.feature_shards
            )
        return None

    def _build(self):
        """Load the model artifact (behind the serving.model_load seam),
        resolve feature maps + widths, stage the device bank, AOT-warm
        the whole ladder. Returns the replayable request list."""
        from photon_ml_tpu.serving import (
            ServingModel,
            ServingPrograms,
            build_model_bank,
            load_model_artifact,
            requests_from_dataset,
        )
        from photon_ml_tpu.serving.batcher import request_from_record

        p = self.params
        with self.timer.time("load-model"):
            loaded = load_model_artifact(p.game_model_input_dir)
        id_types = sorted(
            {re_t for re_t, _, _ in loaded.random_effects.values()}
            | {
                t
                for rt, ct, _, _ in loaded.matrix_factorizations.values()
                for t in (rt, ct)
            }
        )
        index_maps = self._prebuilt_index_maps()
        requests = None
        dataset = None
        if p.stdin_mode:
            widths = _parse_widths(
                p.request_nnz_width,
                [cfg.shard_id for cfg in p.feature_shards],
            )
        else:
            with self.timer.time("load-trace"):
                from photon_ml_tpu.game.data import (
                    build_game_dataset_from_files,
                )

                dataset = build_game_dataset_from_files(
                    p.request_paths,
                    p.feature_shards,
                    id_types,
                    index_maps=index_maps,
                    is_response_required=p.has_response,
                )
            if index_maps is None:
                # batch-scorer in-memory parity mode: the trace itself
                # defines the vocabulary
                index_maps = {
                    sid: sd.index_map for sid, sd in dataset.shards.items()
                }
            widths = (
                _parse_widths(
                    p.request_nnz_width,
                    [cfg.shard_id for cfg in p.feature_shards],
                )
                if p.request_nnz_width
                else {
                    sid: sd.indices.shape[1]
                    for sid, sd in dataset.shards.items()
                }
            )
        with self.timer.time("stage-bank"):
            bank = build_model_bank(
                loaded,
                index_maps,
                widths,
                entity_pad_to=p.entity_pad_to,
                model_id=p.model_id,
            )
        with self.timer.time("warmup-programs"):
            self.serving_model = ServingModel(
                bank, ServingPrograms(tuple(p.ladder))
            )
        self.logger.info(
            "bank generation %d staged: %d coordinate(s), %.1f MiB on "
            "device, ladder %s AOT-compiled (%d program(s))",
            bank.generation,
            len(bank.spec),
            bank.device_bytes() / (1 << 20),
            tuple(p.ladder),
            self.serving_model.programs.stats()["compiled_programs"],
        )
        if dataset is not None:
            with self.timer.time("assemble-requests"):
                requests = requests_from_dataset(dataset, bank)
        else:
            def stdin_requests():
                for line in sys.stdin:
                    line = line.strip()
                    if not line:
                        continue
                    yield request_from_record(
                        json.loads(line),
                        bank,
                        p.feature_shards,
                        has_response=p.has_response,
                    )

            requests = stdin_requests()
        return requests

    # -- replay --------------------------------------------------------------

    def _maybe_swap(self, completed: int, swap_once: threading.Lock):
        p = self.params
        if (
            p.swap_model_dir
            and completed >= p.swap_after_requests
            # non-blocking acquire = atomic test-and-set: exactly one
            # thread stages the flip, racers skip past
            and swap_once.acquire(blocking=False)
        ):
            with self.timer.time("hot-swap"):
                res = self.serving_model.stage_and_swap(
                    p.swap_model_dir,
                    entity_pad_to=p.entity_pad_to,
                    model_id=p.model_id,
                )
            self.logger.info(
                "hot swap after %d request(s): ok=%s generation=%d "
                "donated=%s recompiled=%d rolled_back=%s%s",
                completed, res.ok, res.generation, res.donated,
                res.recompiled_programs, res.rolled_back,
                f" quarantined={res.quarantined}" if res.quarantined else "",
            )

    def _replay_closed(self, batcher, requests) -> List[tuple]:
        swap_once = threading.Lock()
        out = []
        for req in requests:
            out.append((req, batcher.score(req)))
            self._maybe_swap(len(out), swap_once)
        return out

    def _replay_open(self, batcher, requests) -> List[tuple]:
        """``concurrency`` closed-loop submitters over one shared
        iterator: results keep trace order via their request index."""
        p = self.params
        it = iter(enumerate(requests))
        it_lock = threading.Lock()
        out_lock = threading.Lock()
        swap_once = threading.Lock()
        results: Dict[int, tuple] = {}
        errors: List[BaseException] = []

        def worker():
            while True:
                with it_lock:
                    try:
                        i, req = next(it)
                    except StopIteration:
                        return
                try:
                    score = batcher.score(req)
                except BaseException as e:
                    with out_lock:
                        errors.append(e)
                    return
                with out_lock:
                    results[i] = (req, score)
                    n = len(results)
                self._maybe_swap(n, swap_once)

        threads = [
            threading.Thread(target=worker, name=f"photon-serving-load-{t}")
            for t in range(p.concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return [results[i] for i in sorted(results)]

    # -- output --------------------------------------------------------------

    def _write_scores(self, scored: List[tuple]) -> None:
        from photon_ml_tpu.io import schemas
        from photon_ml_tpu.io.avro_codec import write_container

        p = self.params

        def records():
            for req, score in scored:
                yield {
                    "uid": req.uid,
                    "label": req.label if p.has_response else None,
                    "modelId": p.model_id or "game-model",
                    "predictionScore": float(score),
                    "weight": req.weight,
                    "metadataMap": req.metadata or None,
                }

        write_container(
            os.path.join(p.output_dir, "scores", "part-00000.avro"),
            schemas.SCORING_RESULT_AVRO,
            records(),
        )

    def _evaluate(self, scored: List[tuple]) -> Dict[str, float]:
        """Pointwise trace metrics (AUC/RMSE/losses) over the replayed
        scores — the same evaluator path as the batch driver, on host
        arrays the request loop already paid for."""
        import jax.numpy as jnp

        from photon_ml_tpu.evaluation import Evaluator
        from photon_ml_tpu.ops.losses import loss_for_task

        p = self.params
        out: Dict[str, float] = {}
        if not (p.evaluator_types and p.has_response):
            return out
        scores = jnp.asarray(
            np.asarray([s for _, s in scored], np.float32)
        )
        labels = jnp.asarray(
            np.asarray([r.label for r, _ in scored], np.float32)
        )
        weights = jnp.asarray(
            np.asarray([r.weight for r, _ in scored], np.float32)
        )
        loss = loss_for_task(p.task_type)
        for et in p.evaluator_types:
            if et.is_sharded:
                raise ValueError(
                    f"sharded evaluator {et.render()!r} needs global "
                    "per-group data; evaluate with the batch driver"
                )
            metric_in = loss.mean(scores) if et.name == "RMSE" else scores
            value = float(Evaluator(et).evaluate(metric_in, labels, weights))
            out[et.render()] = value
            self.logger.info("%s = %g", et.render(), value)
        return out

    def run(self) -> None:
        from photon_ml_tpu.parallel import overlap
        from photon_ml_tpu.serving import MicroBatcher, ServingMetrics

        p = self.params
        self.logger.info("application: %s", p.application_name)
        requests = self._build()
        self.metrics = ServingMetrics()
        overlap.reset_readback_stats()
        batcher = MicroBatcher(
            self.serving_model.current,
            self.serving_model.programs,
            self.metrics,
            max_wait_s=p.max_wait_ms / 1e3,
            max_queue=p.max_queue,
        )
        try:
            with self.timer.time("serve"):
                scored = (
                    self._replay_closed(batcher, requests)
                    if p.mode == "closed"
                    else self._replay_open(batcher, requests)
                )
        finally:
            batcher.close()
        if not scored:
            raise ValueError("empty request trace")
        self.logger.info(
            "served %d request(s) in %s mode", len(scored), p.mode
        )
        if p.write_scores:
            with self.timer.time("write-scores"):
                self._write_scores(scored)
        eval_metrics = self._evaluate(scored)
        prog_stats = self.serving_model.programs.stats()
        self.metrics.write(
            os.path.join(p.output_dir, "metrics.json"),
            extra={
                **eval_metrics,
                "mode": p.mode,
                "generation": self.serving_model.generation,
                "programs": prog_stats,
                "readbacks": overlap.readback_stats(),
                "swap_history": [
                    {
                        "ok": s.ok,
                        "generation": s.generation,
                        "donated": s.donated,
                        "recompiled_programs": s.recompiled_programs,
                        "rolled_back": s.rolled_back,
                        "quarantined": s.quarantined,
                        "error": s.error,
                    }
                    for s in self.serving_model.swap_history
                ],
            },
        )
        self.results = [s for _, s in scored]
        self.logger.info("timers:\n%s", self.timer.summary())


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="photon-ml-tpu serving")
    ap.add_argument("--game-model-input-dir", required=True)
    ap.add_argument("--output-dir", required=True)
    ap.add_argument(
        "--request-paths", required=True,
        help="Avro trace file(s)/dir(s), comma-separated, or '-' for "
        "JSON-lines requests on stdin",
    )
    ap.add_argument(
        "--feature-shard-id-to-feature-section-keys-map", required=True
    )
    ap.add_argument("--feature-shard-id-to-intercept-map", default=None)
    ap.add_argument("--task-type", default="LOGISTIC_REGRESSION")
    ap.add_argument("--evaluator-types", default=None)
    ap.add_argument("--game-model-id", default=None)
    ap.add_argument("--has-response", default="true")
    ap.add_argument("--offheap-indexmap-dir", default=None)
    ap.add_argument(
        "--offheap-indexmap-num-partitions", type=int, default=None
    )
    ap.add_argument("--feature-name-and-term-set-path", default=None)
    ap.add_argument(
        "--ladder", default=DEFAULT_LADDER_TEXT,
        help="padded micro-batch shapes, comma-separated increasing "
        f"(default {DEFAULT_LADDER_TEXT}); every shape AOT-compiles at "
        "startup",
    )
    ap.add_argument(
        "--max-wait-ms", type=float, default=0.0,
        help="linger for coalescing before dispatching a partial batch "
        "(0 = continuous batching: dispatch whatever accumulated)",
    )
    ap.add_argument("--max-queue", type=int, default=4096)
    ap.add_argument(
        "--request-nnz-width", default=None,
        help="per-shard request feature width ('shard:k|shard:k' or one "
        "int for all); required for stdin, defaults to the trace's "
        "padded width for Avro replay",
    )
    ap.add_argument(
        "--mode", default="closed",
        help="closed = one request in flight (latency floor); open = "
        "--concurrency submitter threads (saturating load)",
    )
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument(
        "--swap-model-dir", default=None,
        help="stage + hot-swap this model generation mid-replay",
    )
    ap.add_argument("--swap-after-requests", type=int, default=0)
    ap.add_argument("--entity-pad-to", type=int, default=256)
    ap.add_argument("--write-scores", default="true")
    ap.add_argument("--delete-output-dir-if-exists", default="false")
    ap.add_argument("--application-name", default=None)
    ap.add_argument(
        "--no-overlap", default="false",
        help="disable the host-device overlap layer (A/B baseline)",
    )
    ap.add_argument(
        "--fault-plan", default=None,
        help="deterministic fault injection "
        "(seam:nth:error[:times], comma-separated); also via "
        "PHOTON_FAULT_PLAN",
    )
    return ap


def params_from_args(argv=None) -> ServingParams:
    from photon_ml_tpu.cli.game_training_driver import (
        apply_intercept_map,
        parse_shard_map,
    )

    ns = build_arg_parser().parse_args(argv)

    def truthy(s) -> bool:
        return str(s).lower() in ("true", "1", "yes")

    return ServingParams(
        game_model_input_dir=ns.game_model_input_dir,
        output_dir=ns.output_dir,
        request_paths=(
            ["-"] if ns.request_paths.strip() == "-"
            else ns.request_paths.split(",")
        ),
        feature_shards=apply_intercept_map(
            parse_shard_map(ns.feature_shard_id_to_feature_section_keys_map),
            ns.feature_shard_id_to_intercept_map,
        ),
        task_type=TaskType.parse(ns.task_type),
        evaluator_types=(
            [EvaluatorType.parse(s) for s in ns.evaluator_types.split(",")]
            if ns.evaluator_types
            else []
        ),
        model_id=ns.game_model_id or "",
        has_response=truthy(ns.has_response),
        offheap_indexmap_dir=ns.offheap_indexmap_dir,
        offheap_indexmap_num_partitions=ns.offheap_indexmap_num_partitions,
        feature_name_and_term_set_path=ns.feature_name_and_term_set_path,
        ladder=[int(b) for b in ns.ladder.split(",")],
        max_wait_ms=ns.max_wait_ms,
        max_queue=ns.max_queue,
        request_nnz_width=ns.request_nnz_width,
        mode=ns.mode,
        concurrency=ns.concurrency,
        swap_model_dir=ns.swap_model_dir,
        swap_after_requests=ns.swap_after_requests,
        entity_pad_to=ns.entity_pad_to,
        write_scores=truthy(ns.write_scores),
        delete_output_dir_if_exists=truthy(ns.delete_output_dir_if_exists),
        application_name=ns.application_name or "photon-ml-tpu-serving",
        no_overlap=truthy(ns.no_overlap),
        fault_plan=ns.fault_plan,
    )


def main(argv=None) -> None:
    ServingDriver(params_from_args(argv)).run()


if __name__ == "__main__":
    main()
