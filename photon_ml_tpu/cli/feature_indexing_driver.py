"""Feature indexing job: build partitioned native index stores from data.

Reference: photon-ml FeatureIndexingJob.scala:59-136 — a separate Spark job
that hash-partitions distinct feature names and builds per-partition PalDB
name<->index stores (with per-shard maps for GAME). Here the stores are
the native mmap format (native/index_store.cpp) built on host.
"""

from __future__ import annotations

import argparse
import os
from typing import Iterable, Iterator

from photon_ml_tpu.io.avro_codec import read_avro_records
from photon_ml_tpu.io.libsvm import read_libsvm
from photon_ml_tpu.utils.index_map import feature_key, intercept_key
from photon_ml_tpu.utils.native_index import build_partitioned_index


def _avro_keys(paths, feature_bags) -> Iterator[str]:
    for record in read_avro_records(paths):
        for bag in feature_bags:
            for f in record.get(bag) or []:
                yield feature_key(f["name"], f["term"])


def _libsvm_keys(paths) -> Iterator[str]:
    for _, pairs in read_libsvm(paths):
        for idx, _ in pairs:
            yield feature_key(str(idx))


def run_feature_indexing(
    input_paths,
    output_dir: str,
    *,
    data_format: str = "AVRO",
    feature_bags: Iterable[str] = ("features",),
    num_partitions: int = 1,
    add_intercept: bool = True,
    shard_name: str = "global",
) -> str:
    """Build the partitioned store for one feature shard; returns its
    directory (``<output>/<shard_name>``)."""
    if data_format.upper() == "AVRO":
        keys: Iterator[str] = _avro_keys(input_paths, list(feature_bags))
    elif data_format.upper() == "LIBSVM":
        keys = _libsvm_keys(input_paths)
    else:
        raise ValueError(f"unknown format {data_format}")

    def with_intercept(it):
        yield from it
        if add_intercept:
            yield intercept_key()

    shard_dir = os.path.join(output_dir, shard_name)
    pm = build_partitioned_index(
        with_intercept(keys), shard_dir, num_partitions=num_partitions
    )
    pm.close()
    return shard_dir


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="photon-ml-tpu feature-indexing")
    ap.add_argument("--input-paths", required=True)
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--format", default="AVRO")
    ap.add_argument("--feature-bags", default="features")
    ap.add_argument("--num-partitions", type=int, default=1)
    ap.add_argument("--add-intercept", default="true")
    ap.add_argument("--shard-name", default="global")
    ns = ap.parse_args(argv)
    shard_dir = run_feature_indexing(
        ns.input_paths.split(","),
        ns.output_dir,
        data_format=ns.format,
        feature_bags=[b for b in ns.feature_bags.split(",") if b],
        num_partitions=ns.num_partitions,
        add_intercept=str(ns.add_intercept).lower() in ("true", "1"),
        shard_name=ns.shard_name,
    )
    print(shard_dir)


if __name__ == "__main__":
    main()
