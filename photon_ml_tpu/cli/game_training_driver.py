"""GAME training driver + CLI.

Reference: photon-ml .../cli/game/training/Driver.scala:642-757 (run:
prepareFeatureMaps -> prepareGameDataSet -> prepareTrainingDataSet ->
evaluators -> train over the config grid -> save models) and
Params.scala:199-426 (option names kept verbatim: ``train-input-dirs``,
``feature-shard-id-to-feature-section-keys-map``,
``fixed-effect-data-configurations``, per-coordinate config maps in the
``coord1:cfg|coord2:cfg`` string DSL with grid expansion).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
from dataclasses import dataclass, field
from itertools import product
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.evaluation import Evaluator, EvaluatorType
from photon_ml_tpu.game.config import (
    FactoredRandomEffectConfiguration,
    FeatureShardConfiguration,
    FixedEffectDataConfiguration,
    RandomEffectDataConfiguration,
)
from photon_ml_tpu.game.coordinate import (
    FactoredRandomEffectCoordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.coordinate_descent import CoordinateDescent
from photon_ml_tpu.game.data import GameDataset, build_game_dataset_from_files
from photon_ml_tpu.game.model import GameModel
from photon_ml_tpu.game.model_io import save_game_model
from photon_ml_tpu.game.random_effect import RandomEffectOptimizationProblem
from photon_ml_tpu.game.random_effect_data import build_random_effect_dataset
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.optim.config import GLMOptimizationConfiguration
from photon_ml_tpu.optim.problem import create_glm_problem
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.utils.logging_util import PhotonLogger, Timer
from photon_ml_tpu.utils.profiling import profile_trace


def parse_keyed_map(s: str) -> Dict[str, str]:
    """``key1:value1|key2:value2`` -> dict (the per-coordinate DSL)."""
    out: Dict[str, str] = {}
    for part in s.split("|"):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition(":")
        out[key.strip()] = value.strip()
    return out


def parse_shard_map(s: str) -> List[FeatureShardConfiguration]:
    """``shard1:bag1,bag2|shard2:bag3`` -> shard configs."""
    return [
        FeatureShardConfiguration(k, [b.strip() for b in v.split(",") if b.strip()])
        for k, v in parse_keyed_map(s).items()
    ]


def apply_intercept_map(
    shards: List[FeatureShardConfiguration], intercept_map: Optional[str]
) -> List[FeatureShardConfiguration]:
    """``shardId1:true|shardId2:false`` -> per-shard add_intercept
    (featureShardIdToInterceptMap, Params.scala:289-300; default true,
    a bare ``shardId`` also means true)."""
    if not intercept_map:
        return shards
    import dataclasses

    flags = {}
    for k, v in parse_keyed_map(intercept_map).items():
        s = v.strip().lower()
        if s in ("", "true", "1", "yes"):
            flags[k] = True
        elif s in ("false", "0", "no"):
            flags[k] = False
        else:
            # a typo like "ture" must not silently drop the intercept
            # (the reference's .toBoolean throws the same way)
            raise ValueError(
                f"intercept map value for {k!r} must be true/false, got {v!r}"
            )
    unknown = set(flags) - {s.shard_id for s in shards}
    if unknown:
        raise ValueError(
            f"intercept map references unknown feature shards {sorted(unknown)}"
        )
    return [
        dataclasses.replace(
            s, add_intercept=flags.get(s.shard_id, s.add_intercept)
        )
        for s in shards
    ]


def _ensure_manifest(directory: str, manifest: Dict[str, object]) -> None:
    """Refuse to reuse a checkpoint directory produced by a different run
    configuration — resuming foreign weights would silently corrupt the
    result; a changed config must get a fresh --checkpoint-dir."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, "manifest.json")
    if os.path.isfile(path):
        with open(path) as f:
            existing = json.load(f)
        if existing != manifest:
            raise ValueError(
                f"checkpoint directory {directory} was created by a "
                "different run configuration (inputs, shards, or update "
                "sequence changed); point --checkpoint-dir somewhere fresh "
                f"or delete it. Recorded config: {path}"
            )
        return
    # atomic write: concurrent processes sharing the directory either see
    # no file (and write identical content) or a complete one — never a
    # partial JSON
    from photon_ml_tpu.reliability import atomic_write_json

    atomic_write_json(path, manifest)


def expand_config_grid(
    opt_configs: Dict[str, str]
) -> List[Dict[str, GLMOptimizationConfiguration]]:
    """Per-coordinate strings may carry comma-grids in regWeight via ';'
    separated alternatives; the reference expands the cross-product of
    per-coordinate config lists into one training run each
    (cli/game/training/Driver.scala:329-347)."""
    names = list(opt_configs)
    alternatives: List[List[GLMOptimizationConfiguration]] = []
    for name in names:
        opts = [
            GLMOptimizationConfiguration.parse(alt)
            for alt in opt_configs[name].split(";")
            if alt.strip()
        ]
        alternatives.append(opts)
    return [dict(zip(names, combo)) for combo in product(*alternatives)]


@dataclass
class GameTrainingParams:
    train_input_dirs: List[str] = field(default_factory=list)
    validate_input_dirs: Optional[List[str]] = None
    output_dir: str = ""
    # Dated-input coordinates (Params.scala:44-82): with a range set, each
    # input dir is expected in daily format <dir>/daily/yyyy/MM/dd.
    train_date_range: Optional[str] = None
    train_date_range_days_ago: Optional[str] = None
    validate_date_range: Optional[str] = None
    validate_date_range_days_ago: Optional[str] = None
    task_type: TaskType = TaskType.LOGISTIC_REGRESSION
    feature_shards: List[FeatureShardConfiguration] = field(default_factory=list)
    fixed_effect_data_configs: Dict[str, FixedEffectDataConfiguration] = field(
        default_factory=dict
    )
    fixed_effect_opt_configs: Dict[str, str] = field(default_factory=dict)
    random_effect_data_configs: Dict[str, RandomEffectDataConfiguration] = field(
        default_factory=dict
    )
    random_effect_opt_configs: Dict[str, str] = field(default_factory=dict)
    factored_re_configs: Dict[str, FactoredRandomEffectConfiguration] = field(
        default_factory=dict
    )
    updating_sequence: Optional[List[str]] = None
    num_iterations: int = 1
    evaluator_types: List[EvaluatorType] = field(default_factory=list)
    compute_variance: bool = False
    # ALL: best-model plus every combo's final model under all/<index>
    # (ModelOutputMode.scala, cli/game/training/Driver.scala:620-635);
    # BEST: best-model only; NONE: no model output.
    model_output_mode: str = "ALL"
    # Split each random-effect coordinate's per-entity model records
    # across N Avro part files (numberOfOutputFilesForRandomEffectModel,
    # Params.scala:387-391); <=0 writes one file.
    num_output_files_for_random_effect_model: int = 1
    application_name: str = "photon-ml-tpu-game-training"
    # Prebuilt per-shard partitioned feature-index stores (the reference's
    # offheap-indexmap-dir, prepareFeatureMaps at
    # cli/game/GAMEDriver.scala:89-97): a directory with one store
    # subdirectory per feature shard id, as written by the
    # feature-indexing job with --shard-name.
    offheap_indexmap_dir: Optional[str] = None
    offheap_indexmap_num_partitions: Optional[int] = None
    # Feature name-and-term list files (the reference's default feature-map
    # source, GAMEDriver.prepareFeatureMapsDefault +
    # NameAndTermFeatureSetContainer.scala): <path>/<sectionKey>/ text
    # files of name TAB term lines; a shard's vocabulary is the union of
    # its section keys' lists. Ignored when offheap_indexmap_dir is set
    # (same precedence as the reference's prepareFeatureMaps dispatch).
    feature_name_and_term_set_path: Optional[str] = None
    delete_output_dir_if_exists: bool = False
    # "auto": fixed-effect solves run data-parallel under shard_map and
    # random-effect banks shard their entity axis whenever >1 device is
    # visible (cli/game/training/Driver.scala is cluster-by-construction);
    # "off": single-device; "feature": the fixed effect runs
    # FEATURE-SHARDED over a 2-D (data, model) mesh — the reference's
    # huge-dimension GAME fixed effect (treeAggregate depth valve at
    # >=200k features, Driver.scala:357-363,717-719; "hundreds of
    # billions of coefficients", README.md:73) — while random-effect
    # banks keep sharding entities over a 1-D mesh
    distributed: str = "auto"
    model_shards: Optional[int] = None  # model-axis size for "feature"
    # Pod-scale GAME (game/pod.py): shard every random-effect bank —
    # plus its optimizer/tracker state and per-entity data — over an
    # N-device "entity" mesh by entity hash, with two-hop all_to_all
    # residual routing. 0/None keeps the replicated banks; -1 uses
    # every visible device; N uses the first N. Composes with
    # --streaming (each device stages only its shard of a segment).
    entity_shards: Optional[int] = None
    # Multi-host orchestration (SparkContextConfiguration analog).
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    # Step checkpoints + preemption-safe resume (upgrade over the
    # reference, which only recovers via saved models / Spark lineage):
    # when set, every coordinate-descent iteration checkpoints here, a
    # SIGTERM (spot/preemptible TPU eviction warning) stops training at
    # the next iteration boundary, and a rerun resumes from the latest
    # step.
    checkpoint_dir: Optional[str] = None
    # jax.profiler trace of the training combos into this directory
    # (SURVEY §7.11): one trace spanning the coordinate-descent fits.
    profile_dir: Optional[str] = None
    # Unified telemetry (ISSUE 13): training-span tracing + flight
    # recorder under --obs-dir (trace.json / flight.json at exit).
    obs_dir: Optional[str] = None
    # Persistent content-addressed tile-schedule cache directory
    # (ops/schedule_cache.py): GAME sweeps over the same dataset reuse
    # the tiled layout across runs. None falls back to the
    # PHOTON_TILE_CACHE_DIR env var; unset = off.
    tile_cache_dir: Optional[str] = None
    # Escape hatch for the host-device overlap layer (parallel/overlap.py):
    # True runs fully serial — eager readbacks, inline host prep,
    # synchronous checkpoint/metrics writes (the pre-overlap behavior and
    # the dev-scripts/bench_overlap.sh A/B baseline).
    no_overlap: bool = False
    # Out-of-core GAME training (game/streaming.py): the train set streams
    # once per CD pass through spilled fixed-shape chunks, random effects
    # group into disk-backed bucket segments, scores/residuals live on
    # disk per chunk — host peak RSS is bounded by --stream-memory-budget
    # instead of the dataset. IDENTITY-projected plain coordinates only.
    streaming: bool = False
    # Byte budget for the streaming layer (chunk rows + RE segment size);
    # 0 keeps the default chunk sizing (65536 rows / 1 GiB segments).
    stream_memory_budget: int = 0
    # Streaming diagnostics reservoir (the GLM driver's byte-budgeted
    # bounded sample, extended to wide-row GAME streams): rows scale DOWN
    # when the staged row is wide so the sample cannot blow the bounded-
    # memory contract (cli.glm_driver.budgeted_reservoir_rows).
    diagnostic_reservoir_rows: int = 100_000
    diagnostic_reservoir_bytes: int = 256 << 20
    # Fixed-effect λ-tuning policy for the combo grid (the GAME analog of
    # the GLM driver's --grid-mode): when the grid is a PURE FE λ sweep
    # (one fixed-effect coordinate, no random effects, 1 CD iteration,
    # combos differing only in regWeight), "batched" solves every combo's
    # FE GLM in ONE vmapped program; "auto" does so when the G×d state
    # bank fits --grid-memory-budget; "sequential" keeps the warm-started
    # per-combo sweep. Grids that are not pure FE λ sweeps always run
    # sequential.
    grid_mode: str = "auto"
    grid_memory_budget: int = 1 << 30
    # Deterministic fault plan (reliability.faults), e.g.
    # "spill_write:2:EIO,ckpt_save:1:ENOSPC"; also via PHOTON_FAULT_PLAN.
    fault_plan: Optional[str] = None
    # Continuous retraining (registry/): --retrain-from warm-starts the
    # FE coefficient vectors AND the per-entity RE banks from the latest
    # committed generation with drift-safe alignment (new vocab terms
    # zero-init, removed terms dropped with accounting, churned entities
    # prior-mean-initialized; bitwise pass-through when nothing
    # drifted); --publish-registry publishes best-model as the next
    # generation, gated against the parent on the validation data.
    retrain_from: Optional[str] = None
    publish_registry: Optional[str] = None
    gate_max_auc_drop: float = 0.005
    gate_max_rmse_increase: float = 0.01
    gate_max_coef_norm_ratio: float = 10.0
    gate_max_prediction_drift: Optional[float] = None

    def validate(self) -> None:
        if not self.train_input_dirs:
            raise ValueError("train-input-dirs is required")
        if not self.output_dir:
            raise ValueError("output-dir is required")
        if self.distributed not in ("auto", "off", "feature"):
            raise ValueError(f"unknown distributed mode {self.distributed!r}")
        if self.model_output_mode not in ("ALL", "BEST", "NONE"):
            raise ValueError(
                f"unknown model output mode {self.model_output_mode!r}"
            )
        # Exclusivity AND range-string format validated up front.
        from photon_ml_tpu.utils.date_range import resolve_date_range

        resolve_date_range(self.train_date_range, self.train_date_range_days_ago)
        resolve_date_range(
            self.validate_date_range, self.validate_date_range_days_ago
        )
        coords = set(self.fixed_effect_data_configs) | set(
            self.random_effect_data_configs
        )
        if not coords:
            raise ValueError("at least one coordinate configuration required")
        for name in self.fixed_effect_data_configs:
            if name not in self.fixed_effect_opt_configs:
                raise ValueError(f"missing optimization config for {name}")
            # Down-sampling composes with --distributed feature since the
            # sampler became pure row re-weighting on the cached sharded
            # layout (the per-draw weights are traced arguments —
            # FixedEffectCoordinate._update_model_feature_sharded); the
            # round-5 parse-time rejection is gone with the limitation.
        for name in self.random_effect_data_configs:
            if name not in self.random_effect_opt_configs:
                raise ValueError(f"missing optimization config for {name}")
        if self.diagnostic_reservoir_rows < 1:
            raise ValueError("diagnostic-reservoir-rows must be >= 1")
        if self.diagnostic_reservoir_bytes < 1:
            raise ValueError("diagnostic-reservoir-bytes must be >= 1")
        if self.grid_mode not in ("batched", "sequential", "auto"):
            raise ValueError(
                f"unknown grid mode {self.grid_mode!r}; expected "
                "batched | sequential | auto"
            )
        if self.entity_shards is not None and self.entity_shards not in (
            0, -1
        ) and self.entity_shards < 1:
            raise ValueError(
                f"entity-shards must be -1, 0 or >= 1, got "
                f"{self.entity_shards}"
            )
        if self.entity_shards not in (None, 0):
            if self.factored_re_configs:
                raise ValueError(
                    "--entity-shards supports plain random-effect "
                    "coordinates only (factored REs re-project rows "
                    "through a replicated latent view)"
                )
            if self.compute_variance and self.streaming:
                raise ValueError(
                    "--entity-shards with --streaming does not support "
                    "--compute-variance yet"
                )
        if self.grid_memory_budget < 1:
            raise ValueError("grid-memory-budget must be >= 1")
        if self.streaming:
            # the streaming layer's structural gates; everything else the
            # in-memory path supports is a bounded pass over staged chunks
            unsupported = []
            if self.factored_re_configs:
                unsupported.append(
                    "factored random effects (latent re-projection "
                    "re-materializes every row per inner iteration)"
                )
            if self.distributed == "feature":
                unsupported.append(
                    "a feature-sharded fixed effect (use the GLM driver's "
                    "--streaming --distributed feature for that "
                    "composition)"
                )
            if self.coordinator_address is not None:
                unsupported.append("multi-process training")
            for et in self.evaluator_types:
                if et.is_sharded:
                    unsupported.append(
                        f"the sharded evaluator {et.render()}"
                    )
            if unsupported:
                raise ValueError(
                    "streaming GAME training does not support: "
                    + ", ".join(unsupported)
                )
            from photon_ml_tpu.game.streaming import (
                validate_streaming_game_configs,
            )

            validate_streaming_game_configs(self.random_effect_data_configs)
        if self.retrain_from:
            unsupported = []
            if self.streaming:
                unsupported.append(
                    "--streaming (the out-of-core CD builds its banks "
                    "from disk segments; warm-starting them is not "
                    "wired yet)"
                )
            if self.entity_shards not in (None, 0):
                unsupported.append(
                    "--entity-shards (the pod coordinates own their "
                    "sharded bank layout)"
                )
            if unsupported:
                raise ValueError(
                    "--retrain-from does not support: "
                    + ", ".join(unsupported)
                )
        if (
            self.retrain_from
            and self.publish_registry
            and not self.validate_input_dirs
        ):
            raise ValueError(
                "validation-gated promotion (--retrain-from + "
                "--publish-registry) requires validate-input-dirs: the "
                "gates compare candidate vs parent on held-out data"
            )
        if self.publish_registry and self.model_output_mode == "NONE":
            raise ValueError(
                "--publish-registry publishes the saved best-model; "
                "model-output-mode NONE writes none"
            )


class GameTrainingDriver:
    def __init__(self, params: GameTrainingParams, logger=None):
        params.validate()
        self.params = params
        from photon_ml_tpu.parallel.multihost import (
            initialize_multihost,
            is_coordinator,
            prepare_output_dir,
        )

        initialize_multihost(
            params.coordinator_address, params.num_processes, params.process_id
        )
        if params.tile_cache_dir is not None:
            # process-wide: every coordinate's tiled conversion (FE solves
            # across all combos) shares the persistent tier
            from photon_ml_tpu.ops.schedule_cache import configure

            configure(params.tile_cache_dir)
        if params.no_overlap:
            from photon_ml_tpu.parallel import overlap

            overlap.set_overlap(False)
        if params.fault_plan:
            from photon_ml_tpu.reliability import install_plan

            install_plan(params.fault_plan)
        prepare_output_dir(
            params.output_dir,
            delete_if_exists=params.delete_output_dir_if_exists,
        )
        self.logger = logger or PhotonLogger(
            params.output_dir if is_coordinator() else None
        )
        self.timer = Timer()
        from photon_ml_tpu.obs import ObsSession

        self.obs = ObsSession(params.obs_dir, signal_dump=False)
        self.results = []
        self.best_result = None
        self.best_config = None
        # continuous retraining state (--retrain-from / --publish-registry)
        self._parent_generation = None   # registry.GenerationInfo
        self._parent_loaded = None       # game.model_io.LoadedGameModel
        self._drift_reports = {}
        self._published_generation = None
        self._gate_report = None

    # -- data --------------------------------------------------------------

    def _expand_dated(self, dirs, date_range, days_ago):
        from photon_ml_tpu.utils.date_range import expand_dated_paths

        return expand_dated_paths(dirs, date_range, days_ago, self.logger)

    def _load_dataset(self, dirs: Sequence[str], index_maps=None) -> GameDataset:
        re_types = [
            c.random_effect_type
            for c in self.params.random_effect_data_configs.values()
        ]
        # sharded evaluators need their id columns too
        for et in self.params.evaluator_types:
            if et.id_type and et.id_type not in re_types:
                re_types.append(et.id_type)
        # native column decode when available; Python codec fallback inside
        return build_game_dataset_from_files(
            list(dirs),
            self.params.feature_shards,
            re_types,
            index_maps=index_maps,
            is_response_required=True,
        )

    # -- coordinates -------------------------------------------------------

    def _mesh(self):
        """Data-parallel/entity-parallel mesh; None when single-device or
        --distributed off. In "feature" mode this is the 1-D mesh the
        RANDOM-EFFECT banks shard over; the fixed effect gets its own
        2-D mesh from _fe_mesh.

        A PARTIAL pod entity mesh (--entity-shards N < visible devices)
        restricts the data mesh to the same N devices: CD row currency
        (scores, residuals) is committed to the entity device set, and
        jit refuses `residual + new_score` across two device sets."""
        from photon_ml_tpu.parallel.mesh import (
            DATA_AXIS,
            make_mesh,
            maybe_make_mesh,
        )

        mode = self.params.distributed
        mesh = maybe_make_mesh("auto" if mode == "feature" else mode)
        pod = self._entity_mesh()
        if (
            mesh is None
            or pod is None
            or pod.devices.size >= mesh.devices.size
        ):
            return mesh
        devs = list(pod.devices.flat)
        if len(devs) < 2:
            return None
        return make_mesh((len(devs),), (DATA_AXIS,), devs)

    def _entity_mesh(self):
        """Pod-scale entity mesh (--entity-shards), or None for the
        replicated random-effect banks."""
        from photon_ml_tpu.parallel.mesh import entity_mesh
        from photon_ml_tpu.training import resolve_entity_shards

        n = resolve_entity_shards(self.params.entity_shards)
        return entity_mesh(n) if n is not None else None

    def _fe_mesh(self):
        """Mesh for the fixed-effect solves: the 2-D (data, model) mesh in
        "feature" mode (feature-sharded coefficients inside the GAME CD),
        the shared 1-D data mesh otherwise. Like _mesh, a partial pod
        entity mesh restricts the device set (the FE's row scores feed
        the pod residual)."""
        from photon_ml_tpu.parallel.mesh import (
            DATA_AXIS,
            MODEL_AXIS,
            make_mesh,
            maybe_make_mesh,
        )

        p = self.params
        if p.distributed != "feature":
            return self._mesh()
        mesh = maybe_make_mesh("feature", p.model_shards)
        pod = self._entity_mesh()
        if (
            mesh is None
            or pod is None
            or pod.devices.size >= mesh.devices.size
        ):
            return mesh
        devs = list(pod.devices.flat)
        m = p.model_shards if p.model_shards is not None else 2
        if len(devs) % m != 0:
            raise ValueError(
                f"model_shards={m} does not divide the {len(devs)}-device "
                "entity mesh (--entity-shards restricts the fixed "
                "effect's (data, model) mesh to the pod device set)"
            )
        return make_mesh(
            (len(devs) // m, m), (DATA_AXIS, MODEL_AXIS), devs
        )

    def _build_coordinates(
        self,
        dataset: GameDataset,
        re_datasets,
        opt_combo: Dict[str, GLMOptimizationConfiguration],
    ):
        p = self.params
        mesh = self._mesh()
        fe_mesh = self._fe_mesh()
        pod_mesh = self._entity_mesh()
        coords = {}
        for name, dcfg in p.fixed_effect_data_configs.items():
            ocfg = opt_combo[name]
            dim = dataset.shards[dcfg.feature_shard_id].dim
            coords[name] = FixedEffectCoordinate(
                name=name,
                dataset=dataset,
                problem=create_glm_problem(
                    p.task_type,
                    dim,
                    config=ocfg.optimizer_config,
                    regularization=ocfg.regularization,
                    compute_variances=p.compute_variance,
                    intercept_index=dataset.shards[dcfg.feature_shard_id].intercept_index,
                ),
                feature_shard_id=dcfg.feature_shard_id,
                reg_weight=ocfg.reg_weight,
                down_sampling_rate=ocfg.down_sampling_rate,
                mesh=fe_mesh,
            )
        loss = loss_for_task(p.task_type)
        for name, dcfg in p.random_effect_data_configs.items():
            ocfg = opt_combo[name]
            red = re_datasets[name]
            problem = RandomEffectOptimizationProblem(
                loss,
                ocfg.optimizer_config,
                ocfg.regularization,
                reg_weight=ocfg.reg_weight,
                # the pod layer owns placement on the entity-sharded path
                mesh=None if pod_mesh is not None else mesh,
                # plain RE coordinates attach per-entity variances; the
                # factored path persists in the ORIGINAL space where the
                # latent-space Hdiag does not transform diagonally
                compute_variances=(
                    p.compute_variance and name not in p.factored_re_configs
                ),
            )
            if name in p.factored_re_configs:
                fcfg = p.factored_re_configs[name]
                coords[name] = FactoredRandomEffectCoordinate(
                    name=name,
                    dataset=dataset,
                    re_dataset=red,
                    problem=problem,
                    projection_problem=create_glm_problem(
                        p.task_type,
                        red.local_dim * fcfg.latent_space_dimension,
                        config=ocfg.optimizer_config,
                        regularization=ocfg.regularization,
                    ),
                    config=fcfg,
                    reg_weight_projection=ocfg.reg_weight,
                )
            elif pod_mesh is not None:
                from photon_ml_tpu.game.coordinate import (
                    PodRandomEffectCoordinate,
                )

                coords[name] = PodRandomEffectCoordinate(
                    name=name, dataset=dataset, re_dataset=red,
                    problem=problem, mesh=pod_mesh,
                )
            else:
                coords[name] = RandomEffectCoordinate(
                    name=name, dataset=dataset, re_dataset=red, problem=problem
                )
        return coords

    def _fe_grid_lambdas(self, combos) -> Optional[List[float]]:
        """The combo grid as a pure fixed-effect λ sweep, or None.

        Batchable when: one FE coordinate, no random effects, 1 CD
        iteration (a single-coordinate CD iteration IS one GLM solve),
        no checkpointing, and every combo identical except the FE
        regWeight. Then the whole sweep collapses into
        training.train_grid_batched's engine — one vmapped program for
        all G combos (--grid-mode; auto applies the memory-budget
        fallback). The feature-sharded FE batches too
        (feature_sharded_glm_fit(grid=True): a [G, d_pad] bank over the
        (data, model) mesh), and down-sampling composes when every combo
        shares the rate — the draw is λ-independent, so one weight
        rewrite serves the whole grid.
        """
        p = self.params
        if p.grid_mode == "sequential":
            return None
        if (
            len(p.fixed_effect_data_configs) != 1
            or p.random_effect_data_configs
            or p.factored_re_configs
            or p.num_iterations != 1
            or p.checkpoint_dir is not None
            or p.retrain_from is not None  # warm start needs the
            # sequential sweep's initial_model seam
            or len(combos) <= 1
        ):
            return None
        name = next(iter(p.fixed_effect_data_configs))
        base = combos[0][name]
        for combo in combos:
            cfg = combo[name]
            if (
                cfg.optimizer_config != base.optimizer_config
                or cfg.regularization != base.regularization
                or cfg.down_sampling_rate != base.down_sampling_rate
            ):
                return None
        lambdas = [combo[name].reg_weight for combo in combos]
        if len(set(lambdas)) != len(lambdas):
            return None
        if p.grid_mode == "auto":
            from photon_ml_tpu.training import resolve_grid_mode

            dcfg = p.fixed_effect_data_configs[name]
            shard = dcfg.feature_shard_id
            dim = None
            try:
                dim = self._dataset_dim_hint(shard)
            except Exception:
                dim = None
            if dim is not None:
                mode = resolve_grid_mode(
                    "auto",
                    num_weights=len(lambdas),
                    dim=dim,
                    optimizer_type=base.optimizer_config.optimizer_type,
                    history=base.optimizer_config.lbfgs_history,
                    memory_budget_bytes=p.grid_memory_budget,
                )
                if mode != "batched":
                    self.logger.info(
                        "grid-mode auto: FE grid bank over %d features "
                        "exceeds the %d-byte budget; sequential sweep",
                        dim, p.grid_memory_budget,
                    )
                    return None
        return lambdas

    def _dataset_dim_hint(self, shard_id: str) -> Optional[int]:
        """Coefficient dimension of a feature shard if a dataset is
        already loaded (the auto budget check); None before load."""
        ds = getattr(self, "_train_dataset", None)
        if ds is None:
            return None
        return ds.shards[shard_id].dim

    def _train_fe_grid_batched(
        self, combos, dataset, re_datasets, validation_fn, maximize
    ) -> None:
        """Pure-FE λ sweep on the batched grid engine: ONE vmapped
        program solves every combo's fixed effect; per-combo objectives
        (loss + the combo's reg term) stay device-resident and return in
        ONE batched fetch; validation/selection then runs per combo
        exactly like the sequential sweep."""
        import jax.numpy as jnp

        from photon_ml_tpu.game.coordinate_descent import (
            CoordinateDescentResult,
        )
        from photon_ml_tpu.game.model import GameModel
        from photon_ml_tpu.ops.losses import loss_for_task
        from photon_ml_tpu.parallel import overlap

        p = self.params
        name = next(iter(p.fixed_effect_data_configs))
        lambdas = [combo[name].reg_weight for combo in combos]
        self.logger.info(
            "training the %d-combo FE lambda grid BATCHED (one vmapped "
            "program; no cross-combo warm starts)", len(combos),
        )
        with self.timer.time("train-fe-grid-batched"):
            coords = self._build_coordinates(dataset, re_datasets, combos[0])
            coord = coords[name]
            fitted = coord.update_model_grid(lambdas)

            loss = loss_for_task(p.task_type)
            offsets = jnp.asarray(dataset.offsets)
            labels = jnp.asarray(dataset.labels)
            weights = jnp.asarray(dataset.weights)
            regularization = coord.problem.regularization
            objective_ds = []
            for lam, (fe_model, _res) in zip(lambdas, fitted):
                z = coord.score(fe_model) + offsets
                value = jnp.sum(weights * loss.value(z, labels))
                l1, l2 = regularization.split(lam)
                w = fe_model.model.means
                value = value + 0.5 * l2 * jnp.vdot(w, w)
                if l1:
                    value = value + l1 * jnp.sum(jnp.abs(w))
                objective_ds.append(overlap.Deferred(value, float))
            # the grid's CD objectives materialize in ONE batched fetch
            overlap.fetch_all(objective_ds)

        best_orig_idx = None
        for ci, combo in enumerate(combos):
            fe_model, res = fitted[ci]
            game_model = GameModel({name: fe_model}, p.task_type)
            validation_history = []
            best_metric = None
            if validation_fn is not None:
                metrics = validation_fn(game_model)
                validation_history.append(metrics)
                self.logger.info(
                    "combo %d validation: %s", ci, metrics
                )
                if self._evaluators:
                    best_metric = metrics[self._evaluators[0].render()]
            result = CoordinateDescentResult(
                model=game_model,
                objective_history=[objective_ds[ci].result()],
                trackers={name: [res]},
                validation_history=validation_history,
                best_model=game_model,
                best_metric=best_metric,
            )
            self.results.append((combo, result, ci))
            metric = result.best_metric
            if metric is None:
                if self.best_result is None or (
                    self.best_result[1] is None and ci < best_orig_idx
                ):
                    self.best_result = (result, None)
                    self.best_config = combo
                    best_orig_idx = ci
            elif (
                self.best_result is None
                or self.best_result[1] is None
                or (maximize and metric > self.best_result[1])
                or (not maximize and metric < self.best_result[1])
            ):
                self.best_result = (result, metric)
                self.best_config = combo
                best_orig_idx = ci

    # -- validation --------------------------------------------------------

    def _validation_fn(self, vdata: GameDataset):
        p = self.params
        loss = loss_for_task(p.task_type)
        evaluators = p.evaluator_types or [
            EvaluatorType.parse(
                "AUC" if p.task_type == TaskType.LOGISTIC_REGRESSION else "RMSE"
            )
        ]

        def fn(game_model: GameModel) -> Dict[str, float]:
            scores = self._score_on(game_model, vdata)
            z = scores + jnp.asarray(vdata.offsets)
            lab = jnp.asarray(vdata.labels)
            w = jnp.asarray(vdata.weights)
            out = {}
            for et in evaluators:
                if et.is_sharded:
                    gids = vdata.entity_codes[et.id_type]
                    ev = Evaluator(et, num_groups=vdata.entity_indexes[et.id_type].num_entities)
                    out[et.render()] = float(
                        ev.evaluate(z, lab, w, jnp.maximum(jnp.asarray(gids), 0))
                    )
                else:
                    metric_in = loss.mean(z) if et.name == "RMSE" else z
                    out[et.render()] = float(
                        Evaluator(et).evaluate(metric_in, lab, w)
                    )
            return out

        self._evaluators = evaluators
        return fn

    def _score_on(self, game_model: GameModel, vdata: GameDataset):
        """Score a validation dataset: fixed effects score directly; RE
        coordinates need row views over the validation rows."""
        total = jnp.zeros((vdata.num_rows,), jnp.float32)
        from photon_ml_tpu.game.model import (
            FixedEffectModel,
            MatrixFactorizationModel,
            RandomEffectModel,
        )
        from photon_ml_tpu.game.coordinate import FactoredRandomEffectModel

        for name, sub in game_model.models.items():
            if isinstance(sub, (FixedEffectModel, MatrixFactorizationModel)):
                total = total + sub.score(vdata)
            elif isinstance(sub, (RandomEffectModel, FactoredRandomEffectModel)):
                view = self._re_view(sub, vdata)
                if isinstance(sub, RandomEffectModel):
                    from photon_ml_tpu.game.random_effect import score_random_effect

                    total = total + score_random_effect(sub.bank, view)
                else:
                    ix = jnp.asarray(view.row_local_indices)
                    v = jnp.asarray(view.row_local_values)
                    x_lat = jnp.einsum(
                        "nk,nkl->nl", v, jnp.take(sub.projection, ix, axis=0)
                    )
                    codes = jnp.maximum(jnp.asarray(view.row_entity_codes), 0)
                    valid = jnp.asarray(view.row_entity_codes >= 0)
                    w_rows = jnp.take(sub.bank, codes, axis=0)
                    total = total + jnp.where(
                        valid, jnp.sum(x_lat * w_rows, axis=-1), 0.0
                    )
        return total

    def _re_view(self, sub, vdata: GameDataset):
        """Project validation rows into the model's entity-local spaces.

        Entities are matched by RAW id between train and validation
        (the reference's join on idTypeToValueMap); unseen entities score 0.
        """
        from dataclasses import replace as dc_replace

        base = sub.re_dataset
        train_eindex = self._train_dataset.entity_indexes[sub.random_effect_type]
        v_eindex = vdata.entity_indexes[sub.random_effect_type]
        sd = vdata.shards[sub.feature_shard_id]
        n, k = sd.indices.shape
        codes = np.full((n,), -1, np.int32)
        v_codes = vdata.entity_codes[sub.random_effect_type]
        for i in range(n):
            c = v_codes[i]
            if c >= 0 and vdata.weights[i] > 0:
                raw = v_eindex.ids[c]
                tc = train_eindex.code_of.get(raw)
                if tc is not None:
                    codes[i] = tc
        row_ix = np.zeros((n, k), np.int32)
        row_v = np.zeros((n, k), np.float32)
        from photon_ml_tpu.game.config import ProjectorType

        ptype = base.config.projector_type
        if ptype == ProjectorType.IDENTITY:
            row_ix, row_v = sd.indices.copy(), sd.values.copy()
        elif ptype == ProjectorType.RANDOM:
            D = base.local_dim
            row_ix = np.tile(np.arange(D, dtype=np.int32)[None, :], (n, 1))
            row_v = np.zeros((n, D), np.float32)
            for i in range(n):
                if codes[i] < 0:
                    continue
                nz = sd.values[i] != 0
                row_v[i] = (
                    base.random_projection[sd.indices[i][nz]].T @ sd.values[i][nz]
                )
        else:
            lmaps = {}
            for i in range(n):
                c = int(codes[i])
                if c < 0:
                    continue
                if c not in lmaps:
                    proj = base.projection[c]
                    lmaps[c] = {int(g): l for l, g in enumerate(proj) if g >= 0}
                lm = lmaps[c]
                for s in range(k):
                    if sd.values[i, s] != 0:
                        l = lm.get(int(sd.indices[i, s]))
                        if l is not None:
                            row_ix[i, s] = l
                            row_v[i, s] = sd.values[i, s]
        return dc_replace(
            base,
            row_local_indices=row_ix,
            row_local_values=row_v,
            row_entity_codes=codes,
            buckets=[],
        )

    # -- continuous retraining (registry/) ----------------------------------

    def _load_parent(self) -> None:
        """Resolve --retrain-from to the latest committed generation's
        loaded GAME artifact (cold start when the registry is empty)."""
        p = self.params
        if not p.retrain_from:
            return
        from photon_ml_tpu.game.model_io import load_game_model
        from photon_ml_tpu.registry import ModelRegistry

        registry = ModelRegistry(p.retrain_from)
        info = registry.latest()
        if info is None:
            self.logger.info(
                "retrain-from registry %s has no committed generation; "
                "cold start", p.retrain_from,
            )
            return
        self._parent_generation = info
        with self.timer.time("load-parent"):
            self._parent_loaded = load_game_model(info.model_dir)
        self.logger.info(
            "retraining from generation %d (lineage %s, coordinates %s)",
            info.generation,
            registry.lineage(info.generation),
            self._parent_loaded.coordinate_names(),
        )

    def _warm_start_model(self, dataset, re_datasets):
        """The initial GameModel for the first combo: parent FE vectors
        and RE banks aligned to the NEW dataset (coordinates the parent
        lacks fall back to zero-init inside CoordinateDescent.run)."""
        if self._parent_loaded is None:
            return None
        from photon_ml_tpu.registry import warm_start_game_model

        model, reports = warm_start_game_model(
            self._parent_loaded, dataset, re_datasets,
            self.params.task_type,
        )
        self._drift_reports = reports
        for name, rep in reports.items():
            self.logger.info(
                "warm-start %s: %d kept, %d new, %d dropped, "
                "%d entities kept, %d churned (prior-mean), "
                "%d entities dropped%s",
                name, rep.kept, rep.new_zero_init, rep.dropped,
                rep.kept_entities, rep.churned_entities_prior_init,
                rep.dropped_entities,
                "" if rep.no_drift else " [DRIFT]",
            )
        return model

    def _model_norms(self, best_model):
        """(candidate_norm, parent_norm): FE + RE coefficient L2 norms
        for the coefficient-sanity gate, both sides over their own
        stored coefficients."""
        from photon_ml_tpu.game.model import (
            FixedEffectModel,
            RandomEffectModel,
        )
        from photon_ml_tpu.parallel import overlap

        sq_terms = []
        for sub in best_model.models.values():
            if isinstance(sub, FixedEffectModel):
                w = sub.model.means
                sq_terms.append(jnp.vdot(w, w))
            elif isinstance(sub, RandomEffectModel):
                sq_terms.append(jnp.vdot(sub.bank, sub.bank))
        cand_sq = (
            sum(float(x) for x in overlap.device_get(sq_terms))
            if sq_terms else 0.0
        )
        par_sq = 0.0
        for _name, (_sid, means) in self._parent_loaded.fixed_effects.items():
            par_sq += sum(float(v) ** 2 for v in means.values())
        for _name, (_rt, _sid, per_entity) in (
            self._parent_loaded.random_effects.items()
        ):
            for means in per_entity.values():
                par_sq += sum(float(v) ** 2 for v in means.values())
        return float(np.sqrt(cand_sq)), float(np.sqrt(par_sq))

    def _run_gates(self, best_model, vdata):
        """Candidate-vs-parent gates on the loaded validation dataset
        (both models score the SAME rows; the parent resolves features/
        entities by key, so drift costs it exactly its vanished terms)."""
        from photon_ml_tpu.parallel import overlap
        from photon_ml_tpu.registry import GateConfig, evaluate_gates

        p = self.params
        config = GateConfig(
            max_auc_drop=p.gate_max_auc_drop,
            max_rmse_increase=p.gate_max_rmse_increase,
            max_coef_norm_ratio=p.gate_max_coef_norm_ratio,
            max_prediction_drift=p.gate_max_prediction_drift,
        )
        offsets = jnp.asarray(vdata.offsets)
        cand, par, labels, weights = overlap.device_get(
            (
                self._score_on(best_model, vdata) + offsets,
                self._parent_loaded.score(vdata, p.task_type) + offsets,
                vdata.labels,
                vdata.weights,
            )
        )
        cand_norm, par_norm = self._model_norms(best_model)
        report = evaluate_gates(
            [(cand, par, labels, weights)],
            p.task_type,
            config=config,
            candidate_norm=cand_norm,
            parent_norm=par_norm,
        )
        self._gate_report = report
        self.logger.info(
            "validation gates: %s %s", report.verdict,
            {k: v.get("passed") for k, v in report.checks.items()},
        )
        return report

    def _publish_to_registry(self, vdata) -> None:
        """Publish the saved best-model directory as the next
        generation; a failed gate records its named verdict (registry
        refusal + metrics.json) and leaves the lineage unchanged."""
        p = self.params
        best = self.best_result[0] if self.best_result is not None else None
        if best is None:
            return
        gate_report = None
        if self._parent_loaded is not None and vdata is not None:
            gate_report = self._run_gates(best.best_model, vdata)
        from photon_ml_tpu.registry import ModelRegistry, RefusedCandidate

        registry = ModelRegistry(p.publish_registry)
        extra = {"task": p.task_type.name}
        if self._drift_reports:
            extra["drift"] = {
                name: rep.as_dict()
                for name, rep in self._drift_reports.items()
            }
        try:
            info = registry.publish(
                os.path.join(p.output_dir, "best-model"),
                parent=(
                    self._parent_generation.generation
                    if self._parent_generation is not None
                    else None
                ),
                data_ranges={
                    "train_input_dirs": list(p.train_input_dirs),
                    "train_date_range": p.train_date_range,
                    "train_date_range_days_ago": (
                        p.train_date_range_days_ago
                    ),
                },
                gate_report=(
                    gate_report.as_dict() if gate_report is not None
                    else None
                ),
                extra=extra,
            )
            self._published_generation = info.generation
            self.logger.info(
                "published generation %d (parent %s, signature %s)",
                info.generation, info.parent, info.signature,
            )
        except RefusedCandidate as e:
            self.logger.warning(
                "candidate REFUSED by validation gate %s; generation "
                "lineage unchanged (refusal recorded at %s)",
                e.verdict, e.refused_dir,
            )

    def _registry_metrics(self):
        p = self.params
        if not (p.retrain_from or p.publish_registry):
            return None
        return {
            "retrain_from": p.retrain_from,
            "parent_generation": (
                self._parent_generation.generation
                if self._parent_generation is not None else None
            ),
            "published_generation": self._published_generation,
            "drift": {
                name: rep.as_dict()
                for name, rep in self._drift_reports.items()
            },
            "gates": (
                self._gate_report.as_dict()
                if self._gate_report is not None else None
            ),
        }

    # -- run ---------------------------------------------------------------

    def _offheap_index_maps(self):
        """{shard_id: index map} resolved like the reference's
        prepareFeatureMaps dispatch (cli/game/GAMEDriver.scala:89-97):
        offheap stores when --offheap-indexmap-dir is set, else
        name-and-term list files when --feature-name-and-term-set-path is
        set, else None (maps built from the training data)."""
        p = self.params
        if p.offheap_indexmap_dir:
            from photon_ml_tpu.utils.native_index import (
                load_offheap_index_maps,
            )

            maps = load_offheap_index_maps(
                p.offheap_indexmap_dir,
                [cfg.shard_id for cfg in p.feature_shards],
                num_partitions=p.offheap_indexmap_num_partitions,
            )
            for sid, m in maps.items():
                self.logger.info(
                    "offheap index map %s: %d features", sid, m.size
                )
            return maps
        if p.feature_name_and_term_set_path:
            from photon_ml_tpu.io.name_term_list import (
                index_maps_from_name_term_lists,
            )

            maps = index_maps_from_name_term_lists(
                p.feature_name_and_term_set_path, p.feature_shards
            )
            for sid, m in maps.items():
                self.logger.info(
                    "name-term list index map %s: %d features", sid, m.size
                )
            return maps
        return None

    # -- streaming (out-of-core) path --------------------------------------

    def _run_streaming(self) -> None:
        """Out-of-core run: scan -> stage -> streamed CD per combo, with
        streamed validation and the model written through the standard
        save_game_model layout (the scoring driver reads it unchanged)."""
        from photon_ml_tpu.game.data import ShardData
        from photon_ml_tpu.game.streaming import train_streaming_game
        from photon_ml_tpu.utils.profiling import peak_rss_bytes

        p = self.params
        train_paths = self._expand_dated(
            p.train_input_dirs, p.train_date_range,
            p.train_date_range_days_ago,
        )
        validate_paths = None
        if p.validate_input_dirs:
            validate_paths = self._expand_dated(
                p.validate_input_dirs, p.validate_date_range,
                p.validate_date_range_days_ago,
            )
        combos = expand_config_grid(
            {**p.fixed_effect_opt_configs, **p.random_effect_opt_configs}
        )
        self.logger.info(
            "streaming GAME training: %d configuration combo(s), "
            "%d B memory budget",
            len(combos), p.stream_memory_budget,
        )
        maximize = p.task_type == TaskType.LOGISTIC_REGRESSION
        best = None
        best_extras = None
        best_orig_idx = None
        guard = None
        if p.checkpoint_dir is not None:
            from photon_ml_tpu.utils.preemption import PreemptionGuard

            guard = PreemptionGuard().install()
        preempted = False
        try:
            for ci, combo in enumerate(combos):
                if guard is not None and guard.requested:
                    self.logger.warning(
                        "preemption requested: not starting combo %d/%d",
                        ci + 1, len(combos),
                    )
                    preempted = True
                    break
                combo_ckpt_dir = None
                if p.checkpoint_dir is not None:
                    # combo-content keyed directory, like the in-memory
                    # sweep: a changed grid can never resume foreign
                    # staged chunks or CD snapshots
                    fp = hashlib.sha1(
                        "|".join(
                            f"{name}:{cfg.render()}"
                            for name, cfg in sorted(combo.items())
                        ).encode()
                    ).hexdigest()[:12]
                    combo_ckpt_dir = os.path.join(
                        p.checkpoint_dir, f"combo-{fp}"
                    )
                with self.timer.time(f"train-combo-{ci}"), profile_trace(
                    p.profile_dir if ci == 0 else None
                ):
                    result, extras = train_streaming_game(
                        train_paths,
                        p.feature_shards,
                        p.fixed_effect_data_configs,
                        p.random_effect_data_configs,
                        combo,
                        p.task_type,
                        num_iterations=p.num_iterations,
                        update_sequence=p.updating_sequence,
                        memory_budget_bytes=p.stream_memory_budget,
                        index_maps=self._offheap_index_maps(),
                        validate_paths=validate_paths,
                        evaluator_types=p.evaluator_types or None,
                        compute_variance=p.compute_variance,
                        diagnostic_reservoir_rows=p.diagnostic_reservoir_rows,
                        diagnostic_reservoir_bytes=p.diagnostic_reservoir_bytes,
                        logger=self.logger,
                        checkpoint_dir=combo_ckpt_dir,
                        preemption_guard=guard,
                        entity_mesh=self._entity_mesh(),
                    )
                self.results.append((combo, result, ci))
                metric = result.best_metric
                if metric is None:
                    if best is None or (
                        best[0].best_metric is None and ci < best_orig_idx
                    ):
                        best, best_extras, best_orig_idx = result, extras, ci
                        self.best_config = combo
                elif (
                    best is None
                    or best[0].best_metric is None
                    or (maximize and metric > best[0].best_metric)
                    or (not maximize and metric < best[0].best_metric)
                ):
                    best, best_extras, best_orig_idx = result, extras, ci
                    self.best_config = combo
                if result.preempted:
                    self.logger.warning(
                        "stopping streaming combo sweep after preemption "
                        "(combo %d/%d)", ci + 1, len(combos),
                    )
                    preempted = True
                    break
        finally:
            if guard is not None:
                guard.uninstall()
        if preempted:
            # best-so-far still publishes (mirroring the in-memory sweep);
            # the checkpoints carry everything needed to resume and finish
            self.logger.warning(
                "preempted: publishing best-so-far; rerun with the same "
                "args to resume the sweep from the checkpoints"
            )
        self.best_result = (best, best.best_metric if best else None)
        if p.model_output_mode != "NONE" and best is not None:
            # a shell dataset carrying ONLY what save_game_model reads:
            # per-shard index maps + entity indexes (no row data)
            shells = {
                sid: ShardData(
                    indices=np.zeros((0, 1), np.int32),
                    values=np.zeros((0, 1), np.float32),
                    index_map=imap,
                    intercept_index=None,
                )
                for sid, imap in best_extras["index_maps"].items()
            }
            shell = GameDataset(
                uids=[],
                labels=np.zeros(0, np.float32),
                offsets=np.zeros(0, np.float32),
                weights=np.zeros(0, np.float32),
                shards=shells,
                entity_codes={},
                entity_indexes=best_extras["entity_indexes"],
                num_real_rows=0,
            )
            with self.timer.time("save-model"):
                save_game_model(
                    best.game_model, shell,
                    os.path.join(p.output_dir, "best-model"),
                    model_spec="\n".join(
                        f"{name} -> {cfg.render()}"
                        for name, cfg in self.best_config.items()
                    ),
                    num_re_output_files=(
                        p.num_output_files_for_random_effect_model
                    ),
                )
        sample = best_extras["diagnostics_sample"] if best_extras else None
        diag = None
        if sample is not None and len(sample["lab"]):
            diag = {
                "reservoir_rows": int(len(sample["lab"])),
                "label_mean": float(np.mean(sample["lab"])),
                "weight_sum": float(np.sum(sample["wgt"])),
            }
        from photon_ml_tpu.reliability import (
            atomic_write_json,
            reliability_metrics,
        )

        atomic_write_json(
            os.path.join(p.output_dir, "metrics.json"),
            {
                "objective_history": (
                    best.objective_history if best else []
                ),
                "validation_history": (
                    best.validation_history if best else []
                ),
                "best_metric": best.best_metric if best else None,
                "timers": self.timer.durations,
                "streaming": {
                    "memory_budget_bytes": p.stream_memory_budget,
                    "rows_per_chunk": (
                        best_extras["rows_per_chunk"]
                        if best_extras else None
                    ),
                    "num_chunks": (
                        best_extras["store"].count
                        if best_extras else None
                    ),
                    "peak_rss_bytes": peak_rss_bytes(),
                    "diagnostics": diag,
                },
                "reliability": reliability_metrics(),
                **(
                    {"obs": self.obs.finish()}
                    if self.obs.enabled else {}
                ),
            },
        )
        self.logger.info("timers:\n%s", self.timer.summary())

    def run(self) -> None:
        p = self.params
        self.logger.info("application: %s", p.application_name)
        if p.streaming:
            self._run_streaming()
            return
        with self.timer.time("load-train"):
            dataset = self._load_dataset(
                self._expand_dated(
                    p.train_input_dirs, p.train_date_range,
                    p.train_date_range_days_ago,
                ),
                index_maps=self._offheap_index_maps(),
            )
        self._train_dataset = dataset
        self.logger.info(
            "GAME train data: %d rows, shards %s",
            dataset.num_real_rows,
            {s: d.dim for s, d in dataset.shards.items()},
        )
        with self.timer.time("re-datasets"):
            re_datasets = {
                name: build_random_effect_dataset(dataset, cfg)
                for name, cfg in p.random_effect_data_configs.items()
            }
        self._load_parent()
        warm_model = self._warm_start_model(dataset, re_datasets)
        vdata = None
        validation_fn = None
        if p.validate_input_dirs:
            with self.timer.time("load-validate"):
                index_maps = {
                    s: d.index_map for s, d in dataset.shards.items()
                }
                vdata = self._load_dataset(
                    self._expand_dated(
                        p.validate_input_dirs, p.validate_date_range,
                        p.validate_date_range_days_ago,
                    ),
                    index_maps,
                )
            validation_fn = self._validation_fn(vdata)

        combos = expand_config_grid(
            {**p.fixed_effect_opt_configs, **p.random_effect_opt_configs}
        )
        self.logger.info("training %d configuration combo(s)", len(combos))
        maximize = p.task_type == TaskType.LOGISTIC_REGRESSION
        if self._fe_grid_lambdas(combos) is not None:
            # pure FE lambda sweep: every combo's fixed effect solves in
            # ONE vmapped grid program (--grid-mode batched/auto)
            self._train_fe_grid_batched(
                combos, dataset, re_datasets, validation_fn, maximize
            )
        else:
            # Cross-combo warm start: train the most-regularized combo first
            # and seed each subsequent combo's coordinate models from the
            # previous fit — the GLM lambda-grid warm start
            # (ModelTraining.scala:183-208) lifted to the GAME grid, which the
            # reference retrains from scratch per combo. Original grid indices
            # ride along so timer labels and metric-less best selection keep
            # the user's configured order.
            order = sorted(
                range(len(combos)),
                key=lambda i: -sum(
                    cfg.reg_weight for cfg in combos[i].values()
                ),
            )
            guard = None
            run_manifest = None
            if p.checkpoint_dir is not None:
                from photon_ml_tpu.utils.preemption import PreemptionGuard

                guard = PreemptionGuard().install()
                run_manifest = {
                    "train_input_dirs": list(p.train_input_dirs),
                    "train_date_range": p.train_date_range,
                    "train_date_range_days_ago": p.train_date_range_days_ago,
                    "task_type": p.task_type.name,
                    "updating_sequence": list(p.updating_sequence or []),
                    "feature_shards": [repr(s) for s in p.feature_shards],
                    "fixed_effect_data_configs": {
                        k: repr(v)
                        for k, v in sorted(p.fixed_effect_data_configs.items())
                    },
                    "random_effect_data_configs": {
                        k: repr(v)
                        for k, v in sorted(p.random_effect_data_configs.items())
                    },
                    # the feature-map source defines the coefficient index
                    # space — a changed source must not resume old weights
                    "offheap_indexmap_dir": p.offheap_indexmap_dir,
                    "feature_name_and_term_set_path": (
                        p.feature_name_and_term_set_path
                    ),
                }
            # retrain warm start: the aligned parent model seeds the
            # FIRST (most-regularized) combo exactly like the cross-
            # combo warm start seeds the rest
            prev_model = warm_model
            best_orig_idx = None
            build_futures: Dict[int, object] = {}
            try:
                for ti, ci in enumerate(order):
                    combo = combos[ci]
                    if guard is not None and guard.requested:
                        self.logger.warning(
                            "preemption requested: not starting combo %d/%d",
                            ti + 1,
                            len(combos),
                        )
                        break
                    with self.timer.time(f"train-combo-{ci}"), profile_trace(
                        # trace the FIRST combo actually trained (combos run
                        # in warm-start order, not grid order)
                        p.profile_dir if ti == 0 else None
                    ):
                        from photon_ml_tpu.parallel import overlap

                        fut = build_futures.pop(ci, None)
                        coords = (
                            overlap.wait(fut)
                            if fut is not None
                            else self._build_coordinates(dataset, re_datasets, combo)
                        )
                        if ti + 1 < len(order):
                            # the NEXT combo's problem setup builds on the
                            # background worker UNDER this combo's training
                            # (overlap prefetched dispatch on the grid axis)
                            nci = order[ti + 1]
                            build_futures[nci] = overlap.submit(
                                self._build_coordinates,
                                dataset, re_datasets, combos[nci],
                            )
                        metric_name = None
                        if validation_fn is not None:
                            metric_name = (self._evaluators[0].render())
                        checkpointer = None
                        if p.checkpoint_dir is not None:
                            from photon_ml_tpu.utils.checkpoint import (
                                TrainingCheckpointer,
                            )

                            # key the directory by the combo's CONTENT so a
                            # changed grid cannot silently resume from another
                            # combo's weights (a different config gets a fresh
                            # directory, not a wrong restore)
                            fp = hashlib.sha1(
                                "|".join(
                                    f"{name}:{cfg.render()}"
                                    for name, cfg in sorted(combo.items())
                                ).encode()
                            ).hexdigest()[:12]
                            combo_dir = os.path.join(
                                p.checkpoint_dir, f"combo-{fp}"
                            )
                            # data/shard/sequence changes fail loudly instead
                            # of silently resuming foreign weights
                            _ensure_manifest(combo_dir, run_manifest)
                            checkpointer = TrainingCheckpointer(combo_dir)
                        cd = CoordinateDescent(
                            coords,
                            dataset,
                            p.task_type,
                            update_sequence=p.updating_sequence,
                            validation_fn=validation_fn,
                            validation_metric=metric_name,
                            validation_maximize=maximize,
                            logger=self.logger,
                            checkpointer=checkpointer,
                            preemption_guard=guard,
                        )
                        try:
                            result = cd.run(
                                p.num_iterations, initial_model=prev_model
                            )
                        finally:
                            if checkpointer is not None:
                                from photon_ml_tpu.parallel import overlap

                                # queued step writes must land before close
                                overlap.drain_io()
                                checkpointer.close()
                        prev_model = result.model
                    self.results.append((combo, result, ci))
                    metric = result.best_metric
                    if metric is None:
                        # no validation metric: selection falls back to the
                        # user's configured grid order (parity with the
                        # pre-warm-start sweep), not training order
                        if self.best_result is None or (
                            self.best_result[1] is None and ci < best_orig_idx
                        ):
                            self.best_result = (result, None)
                            self.best_config = combo
                            best_orig_idx = ci
                    elif (
                        self.best_result is None
                        or self.best_result[1] is None
                        or (maximize and metric > self.best_result[1])
                        or (not maximize and metric < self.best_result[1])
                    ):
                        self.best_result = (result, metric)
                        self.best_config = combo
                        best_orig_idx = ci
                    if result.preempted:
                        self.logger.warning(
                            "stopping combo sweep after preemption (combo %d/%d)",
                            ti + 1,
                            len(combos),
                        )
                        break
            finally:
                if guard is not None:
                    guard.uninstall()

        from photon_ml_tpu.parallel.multihost import (
            is_coordinator,
            sync_processes,
        )

        best = self.best_result[0] if self.best_result is not None else None
        if not is_coordinator():
            sync_processes("outputs-written")
            return
        if best is None:
            # preempted before any combo finished: checkpoints (if enabled)
            # carry the partial state; nothing coherent to save as best
            self.logger.warning(
                "no configuration combo completed; skipping model save"
            )
            sync_processes("outputs-written")
            return
        if p.model_output_mode != "NONE":
            with self.timer.time("save-model"):
                spec = "\n".join(
                    f"{name} -> {cfg.render()}"
                    for name, cfg in self.best_config.items()
                )
                save_game_model(
                    best.best_model, dataset,
                    os.path.join(p.output_dir, "best-model"),
                    model_spec=spec,
                    num_re_output_files=(
                        p.num_output_files_for_random_effect_model
                    ),
                )
                if p.model_output_mode == "ALL":
                    # every combo's final model under all/<original grid
                    # index> (cli/game/training/Driver.scala:620-635) —
                    # NOT warm-start training order, so config position i
                    # always maps to all/<i>
                    for combo, result, ci in self.results:
                        save_game_model(
                            result.model, dataset,
                            os.path.join(p.output_dir, "all", str(ci)),
                            model_spec="\n".join(
                                f"{name} -> {cfg.render()}"
                                for name, cfg in combo.items()
                            ),
                            num_re_output_files=(
                                p.num_output_files_for_random_effect_model
                            ),
                        )
        if p.publish_registry and p.model_output_mode != "NONE":
            with self.timer.time("publish-registry"):
                self._publish_to_registry(vdata)
        from photon_ml_tpu.reliability import (
            atomic_write_json,
            reliability_metrics,
        )

        payload = {
            "objective_history": best.objective_history,
            "validation_history": best.validation_history,
            "best_metric": best.best_metric,
            "timers": self.timer.durations,
            "reliability": reliability_metrics(),
        }
        registry_block = self._registry_metrics()
        if registry_block is not None:
            payload["registry"] = registry_block
        obs_summary = self.obs.finish()
        if obs_summary is not None:
            payload["obs"] = obs_summary
        atomic_write_json(
            os.path.join(p.output_dir, "metrics.json"), payload
        )
        sync_processes("outputs-written")
        self.logger.info("timers:\n%s", self.timer.summary())


# ---------------------------------------------------------------------------
# CLI (option names from cli/game/training/Params.scala)
# ---------------------------------------------------------------------------


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="photon-ml-tpu game-training")
    ap.add_argument("--train-input-dirs", required=True)
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--validate-input-dirs", default=None)
    ap.add_argument("--train-date-range", default=None,
                    help="yyyyMMdd-yyyyMMdd; expects <dir>/daily/yyyy/MM/dd")
    ap.add_argument("--train-date-range-days-ago", default=None,
                    help="start-end days ago, e.g. 90-1")
    ap.add_argument("--validate-date-range", default=None)
    ap.add_argument("--validate-date-range-days-ago", default=None)
    ap.add_argument("--task-type", default="LOGISTIC_REGRESSION")
    ap.add_argument("--feature-shard-id-to-feature-section-keys-map", required=True)
    ap.add_argument(
        "--feature-shard-id-to-intercept-map", default=None,
        help="shardId1:true|shardId2:false — whether each shard learns an "
        "intercept (default true; Params.scala:289-300)",
    )
    ap.add_argument(
        "--feature-name-and-term-set-path", default=None,
        help="directory of per-section name<TAB>term feature list files "
        "(the default prepareFeatureMaps source)",
    )
    ap.add_argument("--fixed-effect-data-configurations", default="")
    ap.add_argument("--fixed-effect-optimization-configurations", default="")
    ap.add_argument("--random-effect-data-configurations", default="")
    ap.add_argument("--random-effect-optimization-configurations", default="")
    ap.add_argument("--factored-random-effect-optimization-configurations", default="")
    ap.add_argument("--updating-sequence", default=None)
    ap.add_argument("--num-iterations", type=int, default=1)
    ap.add_argument("--evaluator-types", default=None)
    ap.add_argument("--offheap-indexmap-dir", default=None)
    ap.add_argument("--offheap-indexmap-num-partitions", type=int, default=None)
    ap.add_argument("--compute-variance", default="false")
    ap.add_argument(
        "--model-output-mode", default=None, choices=["ALL", "BEST", "NONE"],
    )
    ap.add_argument(
        "--save-models-to-hdfs", default=None,
        help="DEPRECATED -- use --model-output-mode (true -> ALL)",
    )
    ap.add_argument(
        "--num-output-files-for-random-effect-model", type=int, default=1,
    )
    ap.add_argument("--application-name", default=None)
    ap.add_argument(
        "--min-partitions-for-validation", type=int, default=None,
        help="ignored (Spark-only)",
    )
    ap.add_argument("--delete-output-dir-if-exists", default="false")
    ap.add_argument(
        "--coordinator-address", default=None,
        help="host:port of process 0 for multi-host runs (jax.distributed)",
    )
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument(
        "--distributed", default="auto", choices=["auto", "off", "feature"],
        help="shard FE data axis + RE entity axis over all devices; "
        "feature: run the fixed effect feature-sharded over a "
        "(data, model) mesh (>HBM coefficient vectors)",
    )
    ap.add_argument(
        "--model-shards", type=int, default=None,
        help="model-axis size for --distributed feature (default 2)",
    )
    ap.add_argument(
        "--entity-shards", type=int, default=None,
        help="pod-scale GAME: shard random-effect banks + their "
        "optimizer state over an N-device entity mesh by entity hash "
        "(all_to_all residual routing); -1 = all devices, 0/unset = "
        "replicated banks",
    )
    ap.add_argument(
        "--fault-plan", default=None,
        help="deterministic fault injection, e.g. "
        "'spill_write:2:EIO,ckpt_save:1:ENOSPC' (seam:nth:error[:times])"
        "; also via PHOTON_FAULT_PLAN. Chaos harness: dev-scripts/"
        "chaos.sh",
    )
    ap.add_argument(
        "--retrain-from", default=None,
        help="model-registry directory: warm-start FE vectors and "
        "per-entity RE banks from the latest committed generation with "
        "drift-safe alignment (new terms zero-init, removed terms "
        "dropped with accounting, churned entities prior-mean-init; "
        "bitwise pass-through when nothing drifted)",
    )
    ap.add_argument(
        "--publish-registry", default=None,
        help="model-registry directory: publish best-model as the next "
        "generation, gated against the parent on the validation data "
        "(a failed gate records a named verdict; the candidate is "
        "never loadable)",
    )
    ap.add_argument("--gate-max-auc-drop", type=float, default=0.005)
    ap.add_argument("--gate-max-rmse-increase", type=float, default=0.01)
    ap.add_argument(
        "--gate-max-coef-norm-ratio", type=float, default=10.0
    )
    ap.add_argument(
        "--gate-max-prediction-drift", type=float, default=None,
        help="mean |candidate - parent| holdout margin bound "
        "(default: gate off)",
    )
    ap.add_argument(
        "--checkpoint-dir", default=None,
        help="per-iteration coordinate-descent checkpoints; enables "
        "SIGTERM-safe stop and resume-from-latest on rerun",
    )
    ap.add_argument(
        "--profile-dir", default=None,
        help="write a jax.profiler trace of the first training combo here",
    )
    ap.add_argument(
        "--obs-dir", default=None,
        help="unified telemetry: training-span tracing + flight "
        "recorder; trace.json / flight.json / metrics_snapshot.json "
        "land here atomically",
    )
    ap.add_argument(
        "--tile-cache-dir", default=None,
        help="persistent content-addressed tile-schedule cache directory "
        "(warm GAME sweeps over the same dataset skip the tiled layout "
        "rebuild). Default: $PHOTON_TILE_CACHE_DIR, unset = off",
    )
    ap.add_argument(
        "--no-overlap", default="false",
        help="disable the host-device overlap layer (deferred readbacks, "
        "background host prep, async checkpoint/metrics writes) and run "
        "fully serial — the A/B escape hatch",
    )
    ap.add_argument(
        "--grid-mode", default="auto",
        choices=["batched", "sequential", "auto"],
        help="fixed-effect lambda-tuning policy: when the combo grid is "
        "a pure FE regWeight sweep (one FE coordinate, no REs, 1 CD "
        "iteration), batched solves every combo in ONE vmapped program; "
        "auto applies the --grid-memory-budget fallback; sequential "
        "keeps the warm-started per-combo sweep",
    )
    ap.add_argument(
        "--grid-memory-budget", type=int, default=1 << 30,
        help="byte budget for the batched FE grid's G x d coefficient "
        "bank + vmapped optimizer state (default 1 GiB)",
    )
    ap.add_argument(
        "--streaming", default="false",
        help="true: out-of-core GAME training — the train set streams "
        "once per CD pass through spilled chunks, random effects solve "
        "from disk-backed bucket segments, host peak RSS is bounded by "
        "--stream-memory-budget (IDENTITY-projected plain coordinates)",
    )
    ap.add_argument(
        "--stream-memory-budget", type=int, default=0,
        help="byte budget for the streaming layer (staged-chunk rows + "
        "random-effect segment size); 0 = default chunk sizing "
        "(65536 rows, 1 GiB segments)",
    )
    ap.add_argument(
        "--diagnostic-reservoir-rows", type=int, default=100_000,
        help="max rows in the streaming diagnostics reservoir sample",
    )
    ap.add_argument(
        "--diagnostic-reservoir-bytes", type=int, default=256 << 20,
        help="byte budget for the diagnostics reservoir (rows scale down "
        "for wide multi-shard rows, preserving bounded memory)",
    )
    return ap


def _model_output_mode(ns) -> str:
    """--model-output-mode, with the DEPRECATED --save-models-to-hdfs
    boolean mapping to ALL/NONE (Params.scala:379-386); both together
    conflict."""
    if ns.save_models_to_hdfs is not None:
        if ns.model_output_mode is not None:
            raise ValueError(
                "specifying both save-models-to-hdfs and model-output-mode "
                "is not supported"
            )
        save = str(ns.save_models_to_hdfs).lower() in ("true", "1", "yes")
        return "ALL" if save else "NONE"
    return ns.model_output_mode or "ALL"


def params_from_args(argv=None) -> GameTrainingParams:
    ns = build_arg_parser().parse_args(argv)

    def _bool(s):
        return str(s).lower() in ("true", "1", "yes")

    fe_data = {
        k: FixedEffectDataConfiguration.parse(v)
        for k, v in parse_keyed_map(ns.fixed_effect_data_configurations).items()
    }
    re_data = {
        k: RandomEffectDataConfiguration.parse(v)
        for k, v in parse_keyed_map(ns.random_effect_data_configurations).items()
    }
    factored = {}
    for k, v in parse_keyed_map(
        ns.factored_random_effect_optimization_configurations
    ).items():
        # format: latentDim,numInnerIterations
        parts = [x.strip() for x in v.split(",")]
        factored[k] = FactoredRandomEffectConfiguration(
            latent_space_dimension=int(parts[0]),
            num_inner_iterations=int(parts[1]) if len(parts) > 1 else 2,
        )
    return GameTrainingParams(
        train_input_dirs=ns.train_input_dirs.split(","),
        validate_input_dirs=(
            ns.validate_input_dirs.split(",") if ns.validate_input_dirs else None
        ),
        train_date_range=ns.train_date_range,
        train_date_range_days_ago=ns.train_date_range_days_ago,
        validate_date_range=ns.validate_date_range,
        validate_date_range_days_ago=ns.validate_date_range_days_ago,
        output_dir=ns.output_dir,
        task_type=TaskType.parse(ns.task_type),
        feature_shards=apply_intercept_map(
            parse_shard_map(ns.feature_shard_id_to_feature_section_keys_map),
            ns.feature_shard_id_to_intercept_map,
        ),
        feature_name_and_term_set_path=ns.feature_name_and_term_set_path,
        fixed_effect_data_configs=fe_data,
        fixed_effect_opt_configs=parse_keyed_map(
            ns.fixed_effect_optimization_configurations
        ),
        random_effect_data_configs=re_data,
        random_effect_opt_configs=parse_keyed_map(
            ns.random_effect_optimization_configurations
        ),
        factored_re_configs=factored,
        updating_sequence=(
            ns.updating_sequence.split(",") if ns.updating_sequence else None
        ),
        num_iterations=ns.num_iterations,
        evaluator_types=(
            [EvaluatorType.parse(s) for s in ns.evaluator_types.split(",")]
            if ns.evaluator_types
            else []
        ),
        compute_variance=_bool(ns.compute_variance),
        model_output_mode=_model_output_mode(ns),
        num_output_files_for_random_effect_model=(
            ns.num_output_files_for_random_effect_model
        ),
        application_name=(
            ns.application_name or "photon-ml-tpu-game-training"
        ),
        offheap_indexmap_dir=ns.offheap_indexmap_dir,
        offheap_indexmap_num_partitions=ns.offheap_indexmap_num_partitions,
        delete_output_dir_if_exists=_bool(ns.delete_output_dir_if_exists),
        distributed=ns.distributed,
        model_shards=ns.model_shards,
        entity_shards=ns.entity_shards,
        coordinator_address=ns.coordinator_address,
        num_processes=ns.num_processes,
        process_id=ns.process_id,
        checkpoint_dir=ns.checkpoint_dir,
        fault_plan=ns.fault_plan,
        profile_dir=ns.profile_dir,
        obs_dir=ns.obs_dir,
        tile_cache_dir=ns.tile_cache_dir,
        no_overlap=_bool(ns.no_overlap),
        grid_mode=ns.grid_mode,
        grid_memory_budget=ns.grid_memory_budget,
        streaming=_bool(ns.streaming),
        stream_memory_budget=ns.stream_memory_budget,
        diagnostic_reservoir_rows=ns.diagnostic_reservoir_rows,
        diagnostic_reservoir_bytes=ns.diagnostic_reservoir_bytes,
        retrain_from=ns.retrain_from,
        publish_registry=ns.publish_registry,
        gate_max_auc_drop=ns.gate_max_auc_drop,
        gate_max_rmse_increase=ns.gate_max_rmse_increase,
        gate_max_coef_norm_ratio=ns.gate_max_coef_norm_ratio,
        gate_max_prediction_drift=ns.gate_max_prediction_drift,
    )


def main(argv=None) -> None:
    GameTrainingDriver(params_from_args(argv)).run()


if __name__ == "__main__":
    main()
