"""GLM training driver: the end-to-end single-model pipeline + CLI.

Reference: photon-ml Driver.scala — staged pipeline
INIT -> PREPROCESSED -> TRAINED -> VALIDATED -> DIAGNOSED
(DriverStage.scala:47-51; stage methods at Driver.scala:267-292 preprocess,
294-327 train, 329-413 validate, 525-552 diagnose, 618-638 report, main at
590-616), PhotonMLCmdLineParser.scala + OptionNames.scala (CLI option
names kept verbatim), Params.scala:200-222 (cross-field validation).

The Spark context is replaced by a jax device context; everything between
load and model write-out runs on device.
"""

from __future__ import annotations

import argparse
import enum
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.data.stats import compute_summary
from photon_ml_tpu.data.validators import DataValidationType, sanity_check_data
from photon_ml_tpu.evaluation import (
    area_under_roc_curve,
    mean_pointwise_loss,
    root_mean_squared_error,
)
from photon_ml_tpu.events import (
    EventEmitter,
    PhotonOptimizationLogEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container
from photon_ml_tpu.io.input_format import LoadedData, create_input_format
from photon_ml_tpu.io.model_io import save_glm_models_avro, write_models_in_text
from photon_ml_tpu.models.glm import compute_margins, compute_means
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.normalization import (
    NormalizationContext,
    NormalizationType,
    build_normalization,
)
from photon_ml_tpu.optim import CONVERGENCE_REASON_NAMES, OptimizerType, RegularizationType
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.training import train_generalized_linear_model
from photon_ml_tpu.utils.index_map import split_feature_key
from photon_ml_tpu.utils.logging_util import PhotonLogger, Timer


class DriverStage(enum.IntEnum):
    INIT = 0
    PREPROCESSED = 1
    TRAINED = 2
    VALIDATED = 3
    DIAGNOSED = 4


class DiagnosticMode(enum.Enum):
    NONE = "NONE"
    TRAIN = "TRAIN"
    VALIDATE = "VALIDATE"
    ALL = "ALL"

    @classmethod
    def parse(cls, s: str) -> "DiagnosticMode":
        return cls(s.strip().upper())


@dataclass
class GLMParams:
    """Mirror of the reference's Params bean (Params.scala)."""

    train_dir: str = ""
    output_dir: str = ""
    validate_dir: Optional[str] = None
    # Dated-input coordinates (DateRange.scala / IOUtils.scala:84+): when a
    # range is given the directory is expected in daily format
    # <dir>/daily/yyyy/MM/dd and expands to the days in range.
    train_date_range: Optional[str] = None
    train_date_range_days_ago: Optional[str] = None
    validate_date_range: Optional[str] = None
    validate_date_range_days_ago: Optional[str] = None
    # Per-iteration validation metrics (validatePerIteration,
    # Driver.scala:329-372); requires a validation directory.
    validate_per_iteration: bool = False
    task: TaskType = TaskType.LOGISTIC_REGRESSION
    input_format: str = "AVRO"  # AVRO | LIBSVM (INPUT_FILE_FORMAT)
    # Avro field-name convention (io/FieldNamesType.scala): the response
    # field is "label" for TRAINING_EXAMPLE, "response" for
    # RESPONSE_PREDICTION.
    field_names: str = "TRAINING_EXAMPLE"
    # Pre-declared LibSVM dimension (--feature-dimension,
    # LibSVMInputDataFormat.scala:32-39): indices are ids, no vocab scan.
    feature_dimension: Optional[int] = None
    # Per-iteration optimizer state logging (OPTIMIZATION_STATE_TRACKER
    # option): writes optimization-log.txt under the output directory.
    enable_optimization_tracker: bool = True
    add_intercept: bool = True
    regularization_weights: List[float] = field(default_factory=lambda: [0.0])
    regularization_type: RegularizationType = RegularizationType.L2
    elastic_net_alpha: Optional[float] = None
    optimizer_type: OptimizerType = OptimizerType.LBFGS
    max_num_iterations: Optional[int] = None
    tolerance: Optional[float] = None
    normalization_type: NormalizationType = NormalizationType.NONE
    data_validation_type: DataValidationType = DataValidationType.VALIDATE_FULL
    constraint_string: Optional[str] = None
    selected_features_file: Optional[str] = None
    summarization_output_dir: Optional[str] = None
    # Prebuilt partitioned feature-index store (OptionNames.scala:47-48,
    # PalDBIndexMapLoader analog): skip the vocabulary build and use the
    # store for name<->index lookup. Built by the feature-indexing job.
    offheap_indexmap_dir: Optional[str] = None
    offheap_indexmap_num_partitions: Optional[int] = None
    diagnostic_mode: DiagnosticMode = DiagnosticMode.NONE
    compute_variances: bool = False
    delete_output_dirs_if_exist: bool = False
    job_name: str = "photon-ml-tpu"
    event_listeners: List[str] = field(default_factory=list)
    # objective kernel: "auto" (tiled Pallas on accelerators, scatter on
    # CPU), "tiled", or "scatter" — see optim.problem.resolve_kernel
    kernel: str = "auto"
    # "auto": train data-parallel under shard_map whenever >1 device is
    # visible (the reference is distributed by construction — every Spark
    # driver runs on a cluster); "off": single-device; "feature":
    # feature-sharded coefficients over a 2-D (data, model) mesh — the
    # >HBM-coefficient path (SURVEY §2.3 coefficient parallelism)
    distributed: str = "auto"
    model_shards: Optional[int] = None  # model-axis size for "feature"
    # Stream the training data from disk per objective evaluation
    # (io/streaming.py): datasets larger than host RAM train with bounded
    # memory — the GLMSuite/Spark MEMORY_AND_DISK analog. Avro (native
    # chunked decode) or LibSVM (line-at-a-time) input; host-driven
    # L-BFGS/OWL-QN/TRON; validation data still loads in memory.
    streaming: bool = False
    # Explicit host-memory byte budget for the streaming layer: fixes the
    # staged-chunk row count (budget // bytes-per-row) AND the chunk/
    # sharded cache tiers, and is reported against the measured peak-RSS
    # high-water in metrics.json. 0 keeps the historical default sizing
    # (65536-row chunks, 2 GiB cache tiers).
    stream_memory_budget: int = 0
    # jax.profiler trace of the training stage into this directory
    # (SURVEY §7.11 upgrade over Timer-only observability); conventionally
    # <output-dir>/profile, viewable in TensorBoard/Perfetto.
    profile_dir: Optional[str] = None
    # Unified telemetry (ISSUE 13): --obs-dir enables training-span
    # tracing (CD iterations, per-lambda solves, streaming passes) +
    # the flight recorder; trace.json/flight.json land here at exit.
    obs_dir: Optional[str] = None
    # Persistent content-addressed tile-schedule cache directory
    # (ops/schedule_cache.py): warm reruns over the same dataset load the
    # tiled layout instead of paying the multi-second rebuild. None falls
    # back to the PHOTON_TILE_CACHE_DIR env var; unset = off.
    tile_cache_dir: Optional[str] = None
    # Escape hatch for the host-device overlap layer (parallel/overlap.py):
    # True runs fully serial — eager readbacks, inline host prep,
    # synchronous artifact writes (the pre-overlap behavior, and the A/B
    # baseline for dev-scripts/bench_overlap.sh).
    no_overlap: bool = False
    # Diagnostics reservoir bounds for the streaming path: the sample is
    # rows x max_nnz dense (int32+float32), so wide-row datasets must not
    # blow the bounded-memory contract — rows are scaled down to fit the
    # byte budget (ADVICE.md round 5).
    diagnostic_reservoir_rows: int = 100_000
    diagnostic_reservoir_bytes: int = 256 << 20
    # λ-grid execution policy (training.resolve_grid_mode): "batched"
    # stacks the grid into a [G, d] bank and runs ONE vmapped optimizer
    # program over a grid-fused objective (1 compile / 1 loop / 1
    # readback round for the whole grid, no cross-λ warm starts);
    # "sequential" keeps the warm-started one-solve-per-λ path; "auto"
    # picks batched when the in-memory grid has >1 member and the G×d
    # state bank fits --grid-memory-budget, and falls back to sequential
    # otherwise (streaming/out-of-core always runs sequential).
    grid_mode: str = "auto"
    grid_memory_budget: int = 1 << 30
    # Multi-host orchestration (the SparkContextConfiguration analog):
    # address of process 0's coordination service. None = single-process.
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    # Crash-safe λ-grid resume (reliability.GridCheckpointer): when set,
    # every completed λ snapshots here (warm-start means + model +
    # result), a SIGTERM stops the sweep at the next λ boundary, and a
    # rerun with the same args resumes mid-path with bitwise-identical
    # final models. Sequential, batched, and streaming grids all resume;
    # feature-sharded paths run without snapshots (warned, not failed).
    checkpoint_dir: Optional[str] = None
    # Deterministic fault plan (reliability.faults): inject transient
    # IO errors / corruption at named seams, e.g.
    # "chunk_read:3:EIO,ckpt_save:1:ENOSPC". Also via PHOTON_FAULT_PLAN.
    fault_plan: Optional[str] = None
    # Continuous retraining (registry/): --retrain-from warm-starts the
    # coefficient vector from the latest committed generation of a model
    # registry with drift-safe alignment (new vocab terms zero-init,
    # removed terms dropped with accounting — bitwise pass-through when
    # nothing drifted); --publish-registry publishes the trained best
    # model as the next generation, gated against the parent on the
    # validating directory (AUC/RMSE non-regression, coefficient-norm
    # sanity, optional prediction-drift bound). A failed gate records a
    # named terminal verdict and the candidate is never loadable.
    retrain_from: Optional[str] = None
    publish_registry: Optional[str] = None
    gate_max_auc_drop: float = 0.005
    gate_max_rmse_increase: float = 0.01
    gate_max_coef_norm_ratio: float = 10.0
    gate_max_prediction_drift: Optional[float] = None
    # Append-only per-partition scan/stats cache (registry/stats_cache):
    # the streaming preprocess scan re-reads ONLY partitions without a
    # cache entry — for an hourly retrain over appended data, exactly
    # the new ones (counted in metrics.json scan_cache).
    scan_cache_dir: Optional[str] = None

    def validate(self) -> None:
        """Cross-field checks (Params.validate, Params.scala:200-222)."""
        if not self.train_dir:
            raise ValueError("training-data-directory is required")
        if not self.output_dir:
            raise ValueError("output-directory is required")
        if self.kernel not in ("auto", "tiled", "scatter"):
            raise ValueError(f"unknown kernel {self.kernel!r}")
        if (
            self.feature_dimension is not None
            and self.input_format.strip().upper() != "LIBSVM"
        ):
            raise ValueError(
                "feature-dimension only applies to the LIBSVM input format"
            )
        if self.distributed not in ("auto", "off", "feature"):
            raise ValueError(f"unknown distributed mode {self.distributed!r}")
        if self.optimizer_type == OptimizerType.TRON and self.regularization_type in (
            RegularizationType.L1,
            RegularizationType.ELASTIC_NET,
        ):
            raise ValueError(
                f"Combination of optimizer {self.optimizer_type.value} and "
                f"regularization {self.regularization_type.value} is not allowed"
            )
        if (
            self.task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM
            and self.optimizer_type == OptimizerType.TRON
        ):
            raise ValueError("TRON is not supported for the smoothed hinge loss")
        if self.constraint_string is not None and self.normalization_type != NormalizationType.NONE:
            raise ValueError(
                "box constraints with normalization are not supported"
            )
        if any(w < 0 for w in self.regularization_weights):
            raise ValueError("regularization weights must be non-negative")
        if self.grid_mode not in ("batched", "sequential", "auto"):
            raise ValueError(
                f"unknown grid mode {self.grid_mode!r}; expected "
                "batched | sequential | auto"
            )
        if self.grid_mode == "batched" and self.streaming:
            # surface the incompatibility at parse time, not mid-train
            # (training.resolve_grid_mode enforces the same rule)
            raise ValueError(
                "--grid-mode batched is incompatible with --streaming: "
                "streamed objectives evaluate through host IO, which one "
                "vmapped optimizer program cannot trace; the streaming "
                "path always runs the warm-started sequential grid"
            )
        if self.grid_memory_budget < 1:
            raise ValueError("grid-memory-budget must be >= 1")
        if self.diagnostic_reservoir_rows < 1:
            raise ValueError("diagnostic-reservoir-rows must be >= 1")
        if self.diagnostic_reservoir_bytes < 1:
            raise ValueError("diagnostic-reservoir-bytes must be >= 1")
        # Exclusivity AND range-string format validated up front (a
        # malformed range should fail here, not mid-preprocess).
        from photon_ml_tpu.utils.date_range import resolve_date_range

        resolve_date_range(self.train_date_range, self.train_date_range_days_ago)
        resolve_date_range(
            self.validate_date_range, self.validate_date_range_days_ago
        )
        if self.validate_per_iteration and not self.validate_dir:
            raise ValueError(
                "validate-per-iteration requires a validating data directory"
            )
        if self.streaming:
            # Round 5 closed most of the streaming guards and round 7
            # deleted the feature-sharding exclusion: every driver stage
            # is a bounded-memory pass over staged chunks, like the
            # reference's everything-is-an-RDD-pass design
            # (Driver.scala:525-552); --distributed feature now re-stages
            # each streamed chunk per feature block on the (data, model)
            # mesh (io.streaming.FeatureShardedStreamingObjective), with
            # one streamed sharded Hv pass per TRON CG step. What remains
            # unsupported is structural:
            unsupported = []
            if self.distributed == "feature":
                if self.normalization_type != NormalizationType.NONE:
                    unsupported.append(
                        "normalization with streaming feature-sharded "
                        "training (the shift/factor extras are not "
                        "threaded through the per-chunk sharded programs)"
                    )
                if self.coordinator_address is not None:
                    unsupported.append(
                        "multi-process streaming feature-sharded training"
                    )
            if (
                self.coordinator_address is not None
                and not self.offheap_indexmap_dir
            ):
                unsupported.append(
                    "multi-process streaming without a prebuilt offheap "
                    "index map (no single process sees the vocabulary)"
                )
            if unsupported:
                raise ValueError(
                    "streaming training does not support: "
                    + ", ".join(unsupported)
                )
        if self.stream_memory_budget and not self.streaming:
            raise ValueError(
                "stream-memory-budget requires --streaming true"
            )
        if self.scan_cache_dir and not self.streaming:
            raise ValueError(
                "scan-cache-dir caches the streaming preprocess scan; "
                "it requires --streaming true"
            )
        if self.scan_cache_dir and self.input_format.strip().upper() != (
            "AVRO"
        ):
            raise ValueError(
                "scan-cache-dir requires the AVRO input format (the "
                "per-partition moment partials use the native decoder)"
            )
        if self.gate_max_coef_norm_ratio <= 0:
            raise ValueError("gate-max-coef-norm-ratio must be > 0")
        if (
            self.retrain_from
            and self.publish_registry
            and not self.validate_dir
        ):
            raise ValueError(
                "validation-gated promotion (--retrain-from + "
                "--publish-registry) requires a validating data "
                "directory: the gates compare candidate vs parent on a "
                "held-out stream"
            )
        if self.retrain_from and self.distributed == "feature":
            raise ValueError(
                "--retrain-from warm starts are not wired through the "
                "feature-sharded trainers yet; use --distributed auto|off"
            )


def budgeted_reservoir_rows(
    max_rows: int, budget_bytes: int, max_nnz: int
) -> int:
    """Diagnostics-reservoir row count under a byte budget: the sample is
    rows x max_nnz dense (int32 indices + float32 values = 8 B/slot, plus
    12 B/row of label/offset/weight), so wide-row datasets scale rows
    DOWN to fit instead of allocating multiple GB on the host — the
    streaming path's bounded-memory contract (ADVICE.md round 5). The
    shared core lives in io.streaming.budgeted_rows; the GAME driver
    budgets its (multi-shard-wide) reservoir through the same helper."""
    from photon_ml_tpu.io.streaming import budgeted_rows, sparse_row_bytes

    return budgeted_rows(max_rows, budget_bytes, sparse_row_bytes(max_nnz))


def _glm_artifact_means(model_dir: str) -> Dict[str, float]:
    """The coefficient dict {feature key: value} of a published GLM
    generation (``model.avro``, one best-model record) — the KEY-space
    view drift-safe alignment consumes."""
    from photon_ml_tpu.io.avro_codec import read_container
    from photon_ml_tpu.utils.index_map import feature_key

    path = os.path.join(model_dir, "model.avro")
    _, records = read_container(path)
    for record in records:
        return {
            feature_key(m["name"], m["term"]): float(m["value"])
            for m in record["means"]
        }
    raise ValueError(f"no model record in {path}")


class GLMDriver:
    """Staged GLM pipeline. After run(): ``stage_history`` lists completed
    stages, ``models`` maps lambda->model, ``best_model`` /
    ``validation_metrics`` filled when a validation dir was given."""

    def __init__(
        self,
        params: GLMParams,
        logger: Optional[PhotonLogger] = None,
        emitter: Optional[EventEmitter] = None,
    ):
        params.validate()
        self.params = params
        # Join the coordination service BEFORE any other JAX use so
        # jax.devices() spans all hosts (multihost.initialize_multihost is
        # a no-op single-process). Output-dir guard must precede logger
        # creation (the logger opens photon.log inside the output dir) —
        # IOUtils.processOutputDir analog (Driver.scala:148-151).
        from photon_ml_tpu.parallel.multihost import (
            initialize_multihost,
            is_coordinator,
            prepare_output_dir,
        )

        initialize_multihost(
            params.coordinator_address, params.num_processes, params.process_id
        )
        if params.tile_cache_dir is not None:
            # process-wide so every stage's tiled conversion (train,
            # validation, diagnostics) shares the same persistent tier
            from photon_ml_tpu.ops.schedule_cache import configure

            configure(params.tile_cache_dir)
        if params.no_overlap:
            from photon_ml_tpu.parallel import overlap

            overlap.set_overlap(False)
        if params.fault_plan:
            from photon_ml_tpu.reliability import install_plan

            install_plan(params.fault_plan)
        prepare_output_dir(
            params.output_dir,
            delete_if_exists=params.delete_output_dirs_if_exist,
            hint="pass --delete-output-dirs-if-exist to overwrite",
        )
        # Every process logs; only the coordinator's photon.log is the log
        # of record (the reference copies exactly one driver log to HDFS).
        self.logger = logger or PhotonLogger(
            params.output_dir if is_coordinator() else None
        )
        self.emitter = emitter or EventEmitter()
        for name in params.event_listeners:
            self.emitter.register_by_name(name)
        from photon_ml_tpu.obs import ObsSession

        self.obs = ObsSession(params.obs_dir, signal_dump=False)
        self.timer = Timer()
        self.stage = DriverStage.INIT
        self.stage_history: List[DriverStage] = []
        self.models = {}
        self.results = {}
        self.best_model = None
        self.best_lambda: Optional[float] = None
        self.validation_metrics: Dict[float, Dict[str, float]] = {}
        self.per_iteration_metrics: Dict[float, List[Dict[str, float]]] = {}
        # single-writer published references: set by the (sequential)
        # train stage before the async summary write is submitted, then
        # never reassigned while the IO worker can see them
        self._data = None  # photon: guarded-by(atomic)
        self._norm: Optional[NormalizationContext] = None
        self._summary = None  # photon: guarded-by(atomic)
        # bounded reservoir sample of a streamed train set (diagnostics)
        self._stream_sample = None
        # tile-schedule cache counters captured after the train stage
        self._schedule_cache_stats: Dict[str, float] = {}
        # per-partition scan-cache counters (--scan-cache-dir)
        self._scan_cache_stats: Dict[str, int] = {}
        # continuous retraining state (--retrain-from / --publish-registry)
        self._parent_generation = None   # registry.GenerationInfo
        self._parent_means: Optional[Dict[str, float]] = None
        self._drift_report = None        # registry.DriftReport
        self._published_generation: Optional[int] = None
        self._gate_report = None

    # -- stages ------------------------------------------------------------

    def _advance(self, stage: DriverStage) -> None:
        self.stage_history.append(stage)
        self.stage = stage

    def preprocess(self) -> None:
        p = self.params
        with self.timer.time("preprocess"):
            selected = None
            if p.selected_features_file:
                with open(p.selected_features_file) as f:
                    selected = [line.strip() for line in f if line.strip()]
            kwargs = dict(
                add_intercept=p.add_intercept, selected_features=selected
            )
            if p.input_format.strip().upper() == "AVRO":
                kwargs["field_names"] = p.field_names
            elif p.feature_dimension is not None:
                kwargs["feature_dimension"] = p.feature_dimension
            fmt = create_input_format(p.input_format, **kwargs)
            self._fmt = fmt
            train_paths = self._dated_paths(
                p.train_dir, p.train_date_range, p.train_date_range_days_ago
            )
            # Multi-host note: every process loads the SAME input (the
            # cross-process device_put contract: identical global value on
            # all hosts, each placing only its addressable shards). True
            # per-process streaming needs a pre-built shared index map
            # (the FeatureIndexingJob store) + global-array assembly via
            # jax.make_array_from_process_local_data — see
            # parallel/multihost.process_shard for the path split.
            prebuilt = None
            if p.offheap_indexmap_dir:
                from photon_ml_tpu.utils.native_index import (
                    load_offheap_index_map,
                )

                prebuilt = load_offheap_index_map(
                    p.offheap_indexmap_dir,
                    num_partitions=p.offheap_indexmap_num_partitions,
                )
                self.logger.info(
                    "offheap index map: %d features from %s",
                    prebuilt.size, p.offheap_indexmap_dir,
                )
            if p.streaming:
                # one bounded-memory pass: vocabulary + staging shape
                # (no full materialization — the train data may exceed
                # RAM); a prebuilt offheap store skips the vocabulary scan
                # (and is required for multi-process streaming)
                import jax

                from photon_ml_tpu.io.streaming import (
                    scan_stream,
                    scan_stream_with_summary,
                )
                from photon_ml_tpu.utils.index_map import intercept_key

                needs_summary = (
                    p.normalization_type != NormalizationType.NONE
                    or bool(p.summarization_output_dir)
                    or p.diagnostic_mode != DiagnosticMode.NONE
                )
                # FUSED scan: vocabulary + stats + colStats in ONE pass
                # over the train dir (stream_scan_with_summary) instead
                # of scan + streamed-summary re-reading it back to back.
                # Falls back to two passes when the summary pass must
                # ALSO draw the diagnostics reservoir (row-level sample
                # in final index space) or reduce across processes.
                fused_summary = None
                use_fused = (
                    needs_summary
                    and p.diagnostic_mode == DiagnosticMode.NONE
                    and jax.process_count() == 1
                    and hasattr(fmt, "stream_scan_with_summary")
                )
                use_scan_cache = (
                    p.scan_cache_dir is not None
                    and jax.process_count() == 1
                )
                if use_scan_cache:
                    # append-only per-partition cache: identical
                    # (index_map, stats) to the uncached scan, touching
                    # only partitions without a valid entry — the
                    # incremental-retrain contract, counted below
                    from photon_ml_tpu.registry import (
                        cached_scan_stream,
                        cached_scan_stream_with_summary,
                    )

                    if use_fused:
                        index_map, stats, fused_summary, cache_stats = (
                            cached_scan_stream_with_summary(
                                train_paths, fmt, p.scan_cache_dir,
                                index_map=prebuilt,
                            )
                        )
                    else:
                        index_map, stats, cache_stats = cached_scan_stream(
                            train_paths, fmt, p.scan_cache_dir,
                            index_map=prebuilt,
                        )
                    self._scan_cache_stats = cache_stats.as_dict()
                    self.logger.info(
                        "scan cache: %d partition(s), %d cached, "
                        "%d scanned, %d quarantined",
                        cache_stats.partitions, cache_stats.cached,
                        cache_stats.scanned, cache_stats.quarantined,
                    )
                elif use_fused:
                    index_map, stats, fused_summary = (
                        scan_stream_with_summary(
                            train_paths, fmt, index_map=prebuilt
                        )
                    )
                else:
                    index_map, stats = scan_stream(
                        train_paths, fmt, index_map=prebuilt
                    )
                icept = (
                    index_map.get_index(intercept_key())
                    if p.add_intercept else -1
                )
                from photon_ml_tpu.io.input_format import (
                    parse_constraint_string,
                )

                constraints = parse_constraint_string(
                    p.constraint_string, index_map, index_map.size,
                    icept if icept >= 0 else None,
                )
                self._data = LoadedData(
                    batch=None,
                    index_map=index_map,
                    num_features=index_map.size,
                    intercept_index=icept if icept >= 0 else None,
                    constraints=constraints,
                )
                self._stream = (train_paths, stats)
                self.logger.info(
                    "streaming scan: %d examples, %d features, "
                    "max %d nnz/row",
                    stats.num_rows, index_map.size, stats.max_nnz,
                )
                if needs_summary:
                    if fused_summary is not None:
                        # the fused pass already collected the colStats —
                        # no second read of the train dir
                        self._summary = fused_summary
                    else:
                        # one more bounded-memory pass: streamed colStats
                        # (+ a reservoir sample of rows when diagnostics
                        # will need row-level resampling).
                        # streaming_summary all-reduces moments across
                        # processes, so each process must scan only ITS
                        # file shard — passing the full set would multiply
                        # every moment by the process count.
                        from photon_ml_tpu.io.streaming import (
                            streaming_summary,
                        )

                        summary_paths = train_paths
                        if jax.process_count() > 1:
                            from photon_ml_tpu.io.streaming import (
                                shard_stream_files,
                            )

                            summary_paths = shard_stream_files(
                                train_paths, fmt
                            )
                        reservoir = 0
                        if p.diagnostic_mode != DiagnosticMode.NONE:
                            reservoir = budgeted_reservoir_rows(
                                p.diagnostic_reservoir_rows,
                                p.diagnostic_reservoir_bytes,
                                stats.max_nnz,
                            )
                            if reservoir < p.diagnostic_reservoir_rows:
                                self.logger.info(
                                    "diagnostics reservoir scaled to %d "
                                    "rows (%d B budget at %d nnz/row)",
                                    reservoir,
                                    p.diagnostic_reservoir_bytes,
                                    stats.max_nnz,
                                )
                        self._summary, self._stream_sample = (
                            streaming_summary(
                                summary_paths, fmt, index_map, stats,
                                reservoir_rows=reservoir,
                            )
                        )
                    self._norm = build_normalization(
                        p.normalization_type,
                        mean=self._summary.mean,
                        std=self._summary.std,
                        max_magnitude=self._summary.max_magnitude,
                        intercept_index=self._data.intercept_index,
                    )
                    if p.summarization_output_dir:
                        from photon_ml_tpu.parallel.multihost import (
                            is_coordinator,
                        )

                        if is_coordinator():
                            # async artifact IO (overlap): the summary
                            # write runs off the critical path; run()
                            # drains before the output barrier
                            from photon_ml_tpu.parallel import overlap

                            overlap.submit_io(  # photon: allow(undrained-io) — run() owns the drain barrier
                                self._write_summary,
                                p.summarization_output_dir,
                                artifact="feature summary",
                            )
                if p.data_validation_type != DataValidationType.VALIDATE_DISABLED:
                    # chunk-wise sanity checks — same DataValidators rules
                    # as the in-memory path, still bounded memory; each
                    # process checks only ITS file shard (the checks are
                    # per-chunk, no cross-host reduce needed)
                    import jax

                    from photon_ml_tpu.io.streaming import iter_chunks

                    check_paths = train_paths
                    if jax.process_count() > 1:
                        from photon_ml_tpu.io.streaming import (
                            shard_stream_files,
                        )

                        check_paths = shard_stream_files(train_paths, fmt)
                    for chunk in iter_chunks(
                        check_paths, fmt, index_map,
                        rows_per_chunk=65536, nnz_width=stats.max_nnz,
                    ):
                        sanity_check_data(
                            chunk, p.task, p.data_validation_type
                        )
                self._advance(DriverStage.PREPROCESSED)
                return
            data = fmt.load(
                train_paths,
                index_map=prebuilt,
                constraint_string=p.constraint_string,
            )
            self._data = data
            self.logger.info(
                "loaded %d examples, %d features",
                int(np.asarray(data.batch.weights > 0).sum()),
                data.num_features,
            )
            sanity_check_data(data.batch, p.task, p.data_validation_type)
            self._summary = compute_summary(data.batch, data.num_features)
            self._norm = build_normalization(
                p.normalization_type,
                mean=self._summary.mean,
                std=self._summary.std,
                max_magnitude=self._summary.max_magnitude,
                intercept_index=data.intercept_index,
            )
            if p.summarization_output_dir:
                from photon_ml_tpu.parallel.multihost import is_coordinator

                if is_coordinator():
                    from photon_ml_tpu.parallel import overlap

                    overlap.submit_io(  # photon: allow(undrained-io) — run() owns the drain barrier
                        self._write_summary, p.summarization_output_dir,
                        artifact="feature summary",
                    )
        self._advance(DriverStage.PREPROCESSED)

    def _dated_paths(self, base_dir, date_range, days_ago):
        """Expand a base dir to its daily paths when a date range is given
        (IOUtils.getInputPathsWithinDateRange analog); otherwise the dir
        itself."""
        from photon_ml_tpu.utils.date_range import (
            input_paths_within_date_range,
            resolve_date_range,
        )

        rng = resolve_date_range(date_range, days_ago)
        if rng is None:
            return base_dir
        paths = input_paths_within_date_range(base_dir, rng)
        self.logger.info(
            "date range %s expanded %s to %d daily paths", rng, base_dir,
            len(paths),
        )
        return paths

    def _mesh(self):
        """Data-parallel mesh over all visible devices (Driver.scala's
        cluster-by-construction analog); None when single-device or off."""
        from photon_ml_tpu.parallel.mesh import maybe_make_mesh

        return maybe_make_mesh(
            self.params.distributed, self.params.model_shards
        )

    def _grid_checkpoint_setup(self):
        """(GridCheckpointer, PreemptionGuard) for --checkpoint-dir, or
        (None, None). The run manifest fingerprints everything that
        shapes the λ iterate chain — resuming under a changed config
        fails loudly instead of mixing foreign snapshots in."""
        p = self.params
        if p.checkpoint_dir is None:
            return None, None
        if p.distributed == "feature":
            self.logger.warning(
                "--checkpoint-dir is not wired through the feature-"
                "sharded trainers yet; training without λ snapshots"
            )
            return None, None
        from photon_ml_tpu.reliability import GridCheckpointer
        from photon_ml_tpu.utils.preemption import PreemptionGuard

        run_config = {
            "train_dir": p.train_dir,
            "train_date_range": p.train_date_range,
            "train_date_range_days_ago": p.train_date_range_days_ago,
            "task": p.task.name,
            "optimizer": p.optimizer_type.value,
            "regularization_type": p.regularization_type.value,
            "regularization_weights": sorted(
                set(float(w) for w in p.regularization_weights)
            ),
            "elastic_net_alpha": p.elastic_net_alpha,
            "max_num_iterations": p.max_num_iterations,
            "tolerance": p.tolerance,
            "normalization_type": p.normalization_type.value,
            "intercept": p.add_intercept,
            "kernel": p.kernel,
            "grid_mode": p.grid_mode,
            "streaming": p.streaming,
            "constraint_string": p.constraint_string,
        }
        if p.retrain_from:
            # the warm start changes the iterate chain: a resumed sweep
            # must come from the SAME parent generation
            run_config["retrain_parent_signature"] = (
                self._parent_generation.signature
                if self._parent_generation is not None
                else None
            )
        guard = PreemptionGuard().install()
        return GridCheckpointer(p.checkpoint_dir, run_config), guard

    # -- continuous retraining (registry/) ----------------------------------

    def _load_parent(self) -> None:
        """Resolve --retrain-from to the latest committed generation and
        its coefficient dict (by feature KEY — alignment never trusts
        indices across vocabularies). A registry with no committed
        generation is a cold start, not an error: the first cron tick
        of a retrain loop trains from zeros and publishes generation 1."""
        p = self.params
        if not p.retrain_from:
            return
        from photon_ml_tpu.registry import ModelRegistry

        registry = ModelRegistry(p.retrain_from)
        info = registry.latest()
        if info is None:
            self.logger.info(
                "retrain-from registry %s has no committed generation; "
                "cold start", p.retrain_from,
            )
            return
        self._parent_generation = info
        self._parent_means = _glm_artifact_means(info.model_dir)
        self.logger.info(
            "retraining from generation %d (lineage %s, %d parent "
            "coefficients, gate verdict %s)",
            info.generation,
            registry.lineage(info.generation),
            len(self._parent_means),
            info.gate_verdict,
        )

    def _retrain_initial(self):
        """The drift-safe warm-start vector in the CURRENT index space
        (None when not retraining): new terms zero-init, removed terms
        dropped with accounting, bitwise the parent when nothing
        drifted. The report lands in metrics.json."""
        if self._parent_means is None:
            return None
        from photon_ml_tpu.registry import DriftReport, align_coefficients

        report = DriftReport()
        initial = align_coefficients(
            self._parent_means, self._data.index_map, report=report
        )
        self._drift_report = report
        self.logger.info(
            "warm-start alignment: %d kept, %d new (zero-init), "
            "%d dropped%s",
            report.kept, report.new_zero_init, report.dropped,
            "" if report.no_drift else " [DRIFT]",
        )
        return initial

    def _run_gates(self, candidate_model):
        """Candidate-vs-parent gates on the validating stream; returns
        the GateReport whose verdict decides the publish."""
        import jax.numpy as jnp

        from photon_ml_tpu.registry import (
            GateConfig,
            align_coefficients,
            evaluate_gates,
        )

        p = self.params
        config = GateConfig(
            max_auc_drop=p.gate_max_auc_drop,
            max_rmse_increase=p.gate_max_rmse_increase,
            max_coef_norm_ratio=p.gate_max_coef_norm_ratio,
            max_prediction_drift=p.gate_max_prediction_drift,
        )
        # the parent scored through TODAY's featurization: shared terms
        # contribute identically, vanished terms contribute nothing
        parent_vec = align_coefficients(
            self._parent_means, self._data.index_map
        )
        candidate_means = np.asarray(candidate_model.means)
        validate_paths = self._dated_paths(
            p.validate_dir, p.validate_date_range,
            p.validate_date_range_days_ago,
        )
        if p.streaming:
            from photon_ml_tpu.io.streaming import scan_stream
            from photon_ml_tpu.registry.gates import glm_gate_chunks

            _, vstats = scan_stream(
                validate_paths, self._fmt, index_map=self._data.index_map
            )
            chunks = glm_gate_chunks(
                jnp.asarray(candidate_means),
                jnp.asarray(parent_vec),
                validate_paths,
                self._fmt,
                self._data.index_map,
                vstats.max_nnz,
            )
        else:
            from photon_ml_tpu.parallel import overlap

            vdata = self._validation_data
            cm, pm, labels, weights = overlap.device_get(
                (
                    compute_margins(
                        jnp.asarray(candidate_means), vdata.batch
                    ),
                    compute_margins(jnp.asarray(parent_vec), vdata.batch),
                    vdata.batch.labels,
                    vdata.batch.weights,
                )
            )
            chunks = [(cm, pm, labels, weights)]
        report = evaluate_gates(
            chunks,
            p.task,
            config=config,
            candidate_norm=float(np.linalg.norm(candidate_means)),
            parent_norm=float(np.linalg.norm(parent_vec)),
        )
        self._gate_report = report
        self.logger.info(
            "validation gates: %s %s", report.verdict,
            {k: v.get("passed") for k, v in report.checks.items()},
        )
        return report

    def _publish_to_registry(self) -> None:
        """Publish the trained model as the next generation. A failed
        gate is an EXPECTED terminal outcome of the retrain loop: the
        refusal (named verdict) is recorded in the registry and in
        metrics.json, and the driver exits cleanly without a new
        generation."""
        p = self.params
        if self.best_model is not None:
            lam, model = self.best_lambda, self.best_model
        elif len(self.models) == 1:
            lam, model = next(iter(self.models.items()))
        else:
            raise ValueError(
                "publishing a multi-lambda grid requires a validating "
                "directory to select the best model"
            )
        gate_report = None
        if self._parent_generation is not None:
            gate_report = self._run_gates(model)
        candidate_dir = os.path.join(p.output_dir, "registry-candidate")
        save_glm_models_avro(
            {lam: model},
            os.path.join(candidate_dir, "model.avro"),
            self._data.index_map,
        )
        # the index map rides with the artifact so the NEXT retrain (and
        # any scorer) aligns by key without this run's output tree
        self._data.index_map.save(
            os.path.join(candidate_dir, "feature-index", "index.json")
        )
        from photon_ml_tpu.registry import ModelRegistry, RefusedCandidate

        registry = ModelRegistry(p.publish_registry)
        extra = {
            "task": p.task.name,
            "lambda": float(lam),
            "num_features": int(self._data.num_features),
        }
        if self._drift_report is not None:
            extra["drift"] = self._drift_report.as_dict()
        try:
            info = registry.publish(
                candidate_dir,
                parent=(
                    self._parent_generation.generation
                    if self._parent_generation is not None
                    else None
                ),
                data_ranges={
                    "train_dir": p.train_dir,
                    "train_date_range": p.train_date_range,
                    "train_date_range_days_ago": (
                        p.train_date_range_days_ago
                    ),
                },
                gate_report=(
                    gate_report.as_dict() if gate_report is not None
                    else None
                ),
                extra=extra,
            )
            self._published_generation = info.generation
            self.logger.info(
                "published generation %d (parent %s, signature %s)",
                info.generation, info.parent, info.signature,
            )
        except RefusedCandidate as e:
            self.logger.warning(
                "candidate REFUSED by validation gate %s; generation "
                "lineage unchanged (refusal recorded at %s)",
                e.verdict, e.refused_dir,
            )

    def train(self) -> None:
        p = self.params
        self.emitter.send(TrainingStartEvent(p.job_name))
        from photon_ml_tpu.utils.profiling import profile_trace

        self._load_parent()
        grid_ckpt, guard = self._grid_checkpoint_setup()
        self._preempted = False
        with self.timer.time("train"), profile_trace(p.profile_dir):
            data = self._data
            mesh = self._mesh()
            retrain_initial = self._retrain_initial()
            if p.streaming:
                from photon_ml_tpu.io.streaming import (
                    sparse_row_bytes,
                    stream_budget_rows,
                )

                train_paths, stats = self._stream
                rows_per_chunk = stream_budget_rows(
                    p.stream_memory_budget, sparse_row_bytes(stats.max_nnz)
                )
                cache_bytes = (
                    p.stream_memory_budget
                    if p.stream_memory_budget > 0
                    else 2 << 30
                )
                if p.stream_memory_budget:
                    self.logger.info(
                        "stream memory budget %d B -> %d rows/chunk, "
                        "%d B cache tiers",
                        p.stream_memory_budget, rows_per_chunk, cache_bytes,
                    )
                if p.distributed == "feature" and mesh is not None:
                    from photon_ml_tpu.training import (
                        train_streaming_feature_sharded,
                    )

                    self.logger.info(
                        "training in streaming FEATURE-SHARDED mode over "
                        "mesh %s (%d rows per full-batch pass)",
                        dict(mesh.shape), stats.num_rows,
                    )
                    self.models, self.results, _ = (
                        train_streaming_feature_sharded(
                            train_paths,
                            p.task,
                            mesh=mesh,
                            regularization_type=p.regularization_type,
                            regularization_weights=p.regularization_weights,
                            elastic_net_alpha=p.elastic_net_alpha,
                            max_iter=p.max_num_iterations,
                            tolerance=p.tolerance,
                            rows_per_chunk=rows_per_chunk,
                            cache_bytes=cache_bytes,
                            sharded_cache_bytes=cache_bytes,
                            optimizer_type=p.optimizer_type,
                            compute_variances=p.compute_variances,
                            box=data.constraints,
                            track_models=p.validate_per_iteration,
                            fmt=self._fmt,
                            index_map=data.index_map,
                            stats=stats,
                        )
                    )
                else:
                    if mesh is not None:
                        self.logger.warning(
                            "streaming training computes on one device "
                            "per process (the %d-device mesh is not used "
                            "for the chunk passes); across PROCESSES the "
                            "input files shard and gradients reduce "
                            "automatically",
                            mesh.devices.size,
                        )
                    self.logger.info(
                        "training in streaming mode (%d rows per "
                        "full-batch pass)",
                        stats.num_rows,
                    )
                    from photon_ml_tpu.training import train_streaming_glm

                    self.models, self.results, _ = train_streaming_glm(
                        train_paths,
                        p.task,
                        regularization_type=p.regularization_type,
                        regularization_weights=p.regularization_weights,
                        elastic_net_alpha=p.elastic_net_alpha,
                        max_iter=p.max_num_iterations,
                        tolerance=p.tolerance,
                        rows_per_chunk=rows_per_chunk,
                        cache_bytes=cache_bytes,
                        kernel=p.kernel,
                        optimizer_type=p.optimizer_type,
                        normalization=self._norm,
                        compute_variances=p.compute_variances,
                        box=data.constraints,
                        track_models=p.validate_per_iteration,
                        fmt=self._fmt,
                        index_map=data.index_map,
                        stats=stats,
                        tile_cache_dir=p.tile_cache_dir,
                        grid_checkpointer=grid_ckpt,
                        preemption_guard=guard,
                        initial=retrain_initial,
                    )
            elif p.distributed == "feature" and mesh is not None:
                grid_mode = self._resolved_grid_mode(data.num_features)
                if grid_mode == "batched":
                    from photon_ml_tpu.training import (
                        train_grid_batched_feature_sharded,
                    )

                    self.logger.info(
                        "training feature-sharded over mesh %s with a "
                        "BATCHED %d-member lambda grid (one vmapped "
                        "program)",
                        dict(mesh.shape),
                        len(set(p.regularization_weights)),
                    )
                    self.models, self.results = (
                        train_grid_batched_feature_sharded(
                            data.batch,
                            p.task,
                            data.num_features,
                            mesh=mesh,
                            regularization_type=p.regularization_type,
                            regularization_weights=p.regularization_weights,
                            elastic_net_alpha=p.elastic_net_alpha,
                            max_iter=p.max_num_iterations,
                            tolerance=p.tolerance,
                            normalization=self._norm,
                            compute_variances=p.compute_variances,
                            box=data.constraints,
                            intercept_index=data.intercept_index,
                            kernel=p.kernel,
                            optimizer_type=p.optimizer_type,
                            track_models=p.validate_per_iteration,
                            tile_cache_dir=p.tile_cache_dir,
                        )
                    )
                else:
                    from photon_ml_tpu.training import train_feature_sharded

                    self.logger.info(
                        "training feature-sharded over mesh %s",
                        dict(mesh.shape),
                    )
                    self.models, self.results = train_feature_sharded(
                        data.batch,
                        p.task,
                        data.num_features,
                        mesh=mesh,
                        regularization_type=p.regularization_type,
                        regularization_weights=p.regularization_weights,
                        elastic_net_alpha=p.elastic_net_alpha,
                        max_iter=p.max_num_iterations,
                        tolerance=p.tolerance,
                        normalization=self._norm,
                        compute_variances=p.compute_variances,
                        box=data.constraints,
                        intercept_index=data.intercept_index,
                        kernel=p.kernel,
                        optimizer_type=p.optimizer_type,
                        track_models=p.validate_per_iteration,
                        tile_cache_dir=p.tile_cache_dir,
                    )
            else:
                if mesh is not None:
                    self.logger.info(
                        "training data-parallel over %d devices",
                        mesh.devices.size,
                    )
                grid_mode = self._resolved_grid_mode(data.num_features)
                if grid_mode == "batched":
                    from photon_ml_tpu.training import train_grid_batched

                    self.logger.info(
                        "training a BATCHED %d-member lambda grid (one "
                        "vmapped optimizer program; no cross-lambda warm "
                        "starts)",
                        len(set(p.regularization_weights)),
                    )
                    self.models, self.results = train_grid_batched(
                        data.batch,
                        p.task,
                        data.num_features,
                        optimizer_type=p.optimizer_type,
                        regularization_type=p.regularization_type,
                        regularization_weights=p.regularization_weights,
                        elastic_net_alpha=p.elastic_net_alpha,
                        max_iter=p.max_num_iterations,
                        tolerance=p.tolerance,
                        normalization=self._norm,
                        compute_variances=p.compute_variances,
                        box=data.constraints,
                        intercept_index=data.intercept_index,
                        kernel=p.kernel,
                        mesh=mesh,
                        track_models=p.validate_per_iteration,
                        tile_cache_dir=p.tile_cache_dir,
                        grid_checkpointer=grid_ckpt,
                        initial=retrain_initial,
                    )
                else:
                    self.models, self.results = train_generalized_linear_model(
                        data.batch,
                        p.task,
                        data.num_features,
                        optimizer_type=p.optimizer_type,
                        regularization_type=p.regularization_type,
                        regularization_weights=p.regularization_weights,
                        elastic_net_alpha=p.elastic_net_alpha,
                        max_iter=p.max_num_iterations,
                        tolerance=p.tolerance,
                        normalization=self._norm,
                        compute_variances=p.compute_variances,
                        box=data.constraints,
                        intercept_index=data.intercept_index,
                        kernel=p.kernel,
                        mesh=mesh,
                        track_models=p.validate_per_iteration,
                        tile_cache_dir=p.tile_cache_dir,
                        grid_checkpointer=grid_ckpt,
                        preemption_guard=guard,
                        initial=retrain_initial,
                    )
            self._log_results()
        if guard is not None:
            self._preempted = guard.requested
            guard.uninstall()
            if self._preempted:
                self.logger.warning(
                    "preemption requested: lambda sweep stopped at a "
                    "lambda boundary (%d snapshot(s) on disk); rerun "
                    "with the same args to resume", len(self.models),
                )
        self._log_schedule_cache()
        self.emitter.send(TrainingFinishEvent(p.job_name))
        self._advance(DriverStage.TRAINED)

    def _resolved_grid_mode(self, dim: int) -> str:
        """Resolve --grid-mode for the in-memory training stage (the
        streaming branches never call this — out-of-core always runs the
        warm-started sequential path)."""
        from photon_ml_tpu.training import resolve_grid_mode

        p = self.params
        mode = resolve_grid_mode(
            p.grid_mode,
            num_weights=len(set(p.regularization_weights)),
            dim=dim,
            optimizer_type=p.optimizer_type,
            memory_budget_bytes=p.grid_memory_budget,
            streaming=False,
        )
        if p.grid_mode == "auto" and mode == "sequential" and (
            len(set(p.regularization_weights)) > 1
        ):
            self.logger.info(
                "grid-mode auto: %d-member grid over %d features does "
                "not fit the %d-byte bank budget; using the warm-started "
                "sequential path",
                len(set(p.regularization_weights)), dim,
                p.grid_memory_budget,
            )
        return mode

    def _log_schedule_cache(self) -> None:
        """Surface the tile-schedule cache outcome of the training stage
        (build/load/hit-miss timers) to the log and the event stream."""
        from photon_ml_tpu.events import ScheduleCacheEvent
        from photon_ml_tpu.ops.schedule_cache import stats

        s = stats()
        if not (s.builds or s.hits or s.misses):
            return  # scatter kernel / no tiled conversion this run
        self._schedule_cache_stats = s.as_dict()
        self.emitter.send(ScheduleCacheEvent(stats=self._schedule_cache_stats))
        self.logger.info(
            "tile-schedule cache: %d hit(s), %d miss(es), %d build(s) "
            "(build %.2fs, load %.3fs, store %.2fs, hash %.2fs)",
            s.hits, s.misses, s.builds,
            s.build_s, s.load_s, s.store_s, s.hash_s,
        )

    def _log_results(self) -> None:
        # The lambda grid's (iterations, value, reason) scalars live on
        # device; ONE batched fetch materializes the whole grid instead
        # of three scalar pulls per lambda (deferred-readback discipline,
        # parallel/overlap.py via training.grid_result_scalars).
        from photon_ml_tpu.training import grid_result_scalars

        for lam, (iters, value, reason) in grid_result_scalars(
            self.results
        ).items():
            self.emitter.send(
                PhotonOptimizationLogEvent(
                    reg_weight=lam,
                    iterations=iters,
                    convergence_reason=CONVERGENCE_REASON_NAMES.get(
                        reason, "?"
                    ),
                    final_value=value,
                )
            )
            self.logger.info(
                "lambda=%g: %d iters, f=%g, reason=%s",
                lam,
                iters,
                value,
                CONVERGENCE_REASON_NAMES.get(reason, "?"),
            )

    def _metrics_for(self, model, batch) -> Dict[str, float]:
        task = self.params.task
        margins = compute_margins(model.means, batch)
        loss = loss_for_task(task)
        metrics = {
            f"{loss.name}_loss": float(
                mean_pointwise_loss(loss, margins, batch.labels, batch.weights)
            )
        }
        if task == TaskType.LOGISTIC_REGRESSION or (
            task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM
        ):
            metrics["AUC"] = float(
                area_under_roc_curve(margins, batch.labels, batch.weights)
            )
        if task in (TaskType.LINEAR_REGRESSION, TaskType.POISSON_REGRESSION):
            means = compute_means(task, model.means, batch)
            metrics["RMSE"] = float(
                root_mean_squared_error(means, batch.labels, batch.weights)
            )
        return metrics

    def _streamed_metrics_for(self, means, validate_paths, vstats) -> Dict[str, float]:
        """One bounded pass over the validate stream for ONE model: the
        driver's metric set via streaming accumulators (AUC fixed-bin
        histogram, RMSE/losses exact) — evaluation/streaming.py."""
        import jax

        from photon_ml_tpu.evaluation.streaming import (
            finalize_metrics,
            glm_streaming_metrics,
            update_glm_metrics,
        )
        from photon_ml_tpu.io.streaming import iter_chunks

        p = self.params
        loss = loss_for_task(p.task)
        accs = glm_streaming_metrics(p.task, loss)
        margins_fn = self.__dict__.get("_stream_margins_fn")
        if margins_fn is None:
            # jit the named def directly: a jit(lambda ...) here would
            # mint a fresh compile cache per driver instance for nothing
            margins_fn = jax.jit(compute_margins)
            self._stream_margins_fn = margins_fn
        for chunk in iter_chunks(
            validate_paths, self._fmt, self._data.index_map,
            rows_per_chunk=65536, nnz_width=vstats.max_nnz,
        ):
            update_glm_metrics(
                accs, loss, margins_fn(means, chunk),
                chunk.labels, chunk.weights,
            )
        return finalize_metrics(accs)

    def _validate_streaming(self, validate_paths) -> None:
        """Streamed validation (one pass per model over the validate dir,
        never materialized): per-lambda metrics, best-model selection,
        and --validate-per-iteration metrics all consume the stream
        through iter_chunks — the reference's evaluate-as-one-more-
        RDD-pass shape (Driver.scala:329-413)."""
        from photon_ml_tpu.io.streaming import iter_chunks, scan_stream

        p = self.params
        _, vstats = scan_stream(
            validate_paths, self._fmt, index_map=self._data.index_map
        )
        self.logger.info(
            "streamed validation scan: %d examples, max %d nnz/row",
            vstats.num_rows, vstats.max_nnz,
        )
        if p.data_validation_type != DataValidationType.VALIDATE_DISABLED:
            for chunk in iter_chunks(
                validate_paths, self._fmt, self._data.index_map,
                rows_per_chunk=65536, nnz_width=vstats.max_nnz,
            ):
                sanity_check_data(chunk, p.task, p.data_validation_type)
        if p.validate_per_iteration:
            from photon_ml_tpu.training import iteration_models

            for lam, result in self.results.items():
                models = iteration_models(
                    result, p.task, self._norm, self._data.intercept_index
                )
                per_iter = [
                    self._streamed_metrics_for(
                        m.means, validate_paths, vstats
                    )
                    for m in models
                ]
                self.per_iteration_metrics[lam] = per_iter
                msg = "\n".join(
                    f"Iteration: [{i:6d}] " + " ".join(
                        f"Metric: [{k}] value: {v}"
                        for k, v in sorted(metrics.items())
                    )
                    for i, metrics in enumerate(per_iter)
                )
                self.logger.info("Model with lambda = %g:\n%s", lam, msg)
        maximize = p.task == TaskType.LOGISTIC_REGRESSION
        best = None
        for lam, model in self.models.items():
            metrics = self._streamed_metrics_for(
                model.means, validate_paths, vstats
            )
            self.validation_metrics[lam] = metrics
            key = (
                "AUC"
                if maximize
                else ("RMSE" if "RMSE" in metrics else next(iter(metrics)))
            )
            score = metrics[key]
            self.logger.info("lambda=%g validation %s", lam, metrics)
            if (
                best is None
                or (maximize and score > best[2])
                or (not maximize and score < best[2])
            ):
                best = (lam, model, score)
        self.best_lambda, self.best_model, _ = best

    def validate(self) -> None:
        p = self.params
        with self.timer.time("validate"):
            validate_paths = self._dated_paths(
                p.validate_dir, p.validate_date_range,
                p.validate_date_range_days_ago,
            )
            if p.streaming:
                # bounded-memory validation: the validate dir streams
                # through iter_chunks per model instead of loading whole
                self._validate_streaming(validate_paths)
                self._advance(DriverStage.VALIDATED)
                return
            vdata = self._fmt.load(
                validate_paths, index_map=self._data.index_map
            )
            sanity_check_data(vdata.batch, p.task, p.data_validation_type)
            self._validation_data = vdata
            if p.validate_per_iteration:
                self._validate_per_iteration(vdata)
            # Select by AUC for classification, RMSE/loss otherwise
            # (ModelSelection.scala:36-63).
            maximize = p.task == TaskType.LOGISTIC_REGRESSION
            best = None
            for lam, model in self.models.items():
                metrics = self._metrics_for(model, vdata.batch)
                self.validation_metrics[lam] = metrics
                key = (
                    "AUC"
                    if maximize
                    else ("RMSE" if "RMSE" in metrics else next(iter(metrics)))
                )
                score = metrics[key]
                self.logger.info("lambda=%g validation %s", lam, metrics)
                if (
                    best is None
                    or (maximize and score > best[2])
                    or (not maximize and score < best[2])
                ):
                    best = (lam, model, score)
            self.best_lambda, self.best_model, _ = best
        self._advance(DriverStage.VALIDATED)

    def _validate_per_iteration(self, vdata) -> None:
        """Metrics for every (lambda, iteration) model
        (computeAndLogModelMetrics, Driver.scala:330-349)."""
        from photon_ml_tpu.training import iteration_models

        p = self.params
        for lam, result in self.results.items():
            models = iteration_models(
                result, p.task, self._norm, self._data.intercept_index
            )
            per_iter = [self._metrics_for(m, vdata.batch) for m in models]
            self.per_iteration_metrics[lam] = per_iter
            msg = "\n".join(
                f"Iteration: [{i:6d}] " + " ".join(
                    f"Metric: [{k}] value: {v}"
                    for k, v in sorted(metrics.items())
                )
                for i, metrics in enumerate(per_iter)
            )
            self.logger.info("Model with lambda = %g:\n%s", lam, msg)

    def diagnose(self) -> None:
        """Model diagnostics + HTML report (Driver.scala:525-552, 618-638)."""
        from photon_ml_tpu.diagnostics.report import run_glm_diagnostics

        with self.timer.time("diagnose"):
            run_glm_diagnostics(self)
        self._advance(DriverStage.DIAGNOSED)

    # -- outputs -----------------------------------------------------------

    def _write_summary(self, out_dir: str) -> None:
        os.makedirs(out_dir, exist_ok=True)
        s = self._summary
        records = []
        for key, i in self._data.index_map.items():
            name, term = split_feature_key(key)
            records.append(
                {
                    "featureName": name,
                    "featureTerm": term,
                    "metrics": {
                        "mean": float(s.mean[i]),
                        "variance": float(s.variance[i]),
                        "numNonzeros": float(s.num_nonzeros[i]),
                        "max": float(s.max[i]),
                        "min": float(s.min[i]),
                        "normL1": float(s.norm_l1[i]),
                        "normL2": float(s.norm_l2[i]),
                        "meanAbs": float(s.mean_abs[i]),
                    },
                }
            )
        write_container(
            os.path.join(out_dir, "part-00000.avro"),
            schemas.FEATURE_SUMMARIZATION_RESULT_AVRO,
            records,
        )

    def _write_outputs(self) -> None:
        p = self.params
        out = p.output_dir
        os.makedirs(out, exist_ok=True)
        self._data.index_map.save(os.path.join(out, "feature-index", "index.json"))
        write_models_in_text(
            self.models, os.path.join(out, "models-text"), self._data.index_map
        )
        save_glm_models_avro(
            self.models, os.path.join(out, "models", "models.avro"),
            self._data.index_map,
        )
        if self.best_model is not None:
            save_glm_models_avro(
                {self.best_lambda: self.best_model},
                os.path.join(out, "best-model", "model.avro"),
                self._data.index_map,
            )
        if p.enable_optimization_tracker:
            from photon_ml_tpu.reliability import atomic_writer

            with atomic_writer(os.path.join(out, "optimization-log.txt")) as f:
                for lam, res in sorted(self.results.items()):
                    t = res.tracker
                    n = int(t.count)
                    f.write(
                        f"lambda={lam} iterations={int(res.iterations)} "
                        f"converged={res.reason_name}\n"
                    )
                    # slot 0 is the pre-optimization initial point
                    for i in range(n):
                        f.write(
                            f"  iter={i} value={float(t.values[i]):.8g} "
                            f"|grad|={float(t.grad_norms[i]):.8g}\n"
                        )
        from photon_ml_tpu.utils.profiling import peak_rss_bytes

        payload = {
            "validation": {
                str(k): v for k, v in self.validation_metrics.items()
            },
            "per_iteration_validation": {
                str(k): v
                for k, v in self.per_iteration_metrics.items()
            },
            "best_lambda": self.best_lambda,
            "timers": self.timer.durations,
            "schedule_cache": self._schedule_cache_stats,
        }
        if self._scan_cache_stats:
            # the "touched only new partitions" counters (scan cache)
            payload["scan_cache"] = self._scan_cache_stats
        if p.retrain_from or p.publish_registry:
            payload["registry"] = {
                "retrain_from": p.retrain_from,
                "parent_generation": (
                    self._parent_generation.generation
                    if self._parent_generation is not None else None
                ),
                "published_generation": self._published_generation,
                "drift": (
                    self._drift_report.as_dict()
                    if self._drift_report is not None else None
                ),
                "gates": (
                    self._gate_report.as_dict()
                    if self._gate_report is not None else None
                ),
            }
        if p.streaming:
            # the out-of-core contract made observable: configured budget
            # vs the measured host high-water
            payload["streaming"] = {
                "memory_budget_bytes": p.stream_memory_budget,
                "peak_rss_bytes": peak_rss_bytes(),
            }
        # fault/retry/quarantine accounting: every injected fault, retry
        # and quarantined artifact this run performed, by seam
        from photon_ml_tpu.reliability import (
            atomic_write_json,
            reliability_metrics,
        )

        payload["reliability"] = reliability_metrics()
        atomic_write_json(os.path.join(out, "metrics.json"), payload)

    def run(self) -> None:
        from photon_ml_tpu.parallel.multihost import (
            is_coordinator,
            sync_processes,
        )

        p = self.params
        self.preprocess()
        self.train()
        if getattr(self, "_preempted", False):
            # stopped mid-sweep on SIGTERM: the λ snapshots carry the
            # partial state; publishing models/metrics from a partial
            # grid would let a half-result masquerade as a full one
            from photon_ml_tpu.parallel import overlap

            overlap.drain_io()
            sync_processes("outputs-written")
            self.logger.info("preempted: outputs withheld; resume to finish")
            self.obs.finish(reason="preempted")
            self.emitter.close()
            return
        if p.validate_dir:
            self.validate()
        if p.diagnostic_mode != DiagnosticMode.NONE and is_coordinator():
            self.diagnose()
        if is_coordinator():
            if p.publish_registry:
                # gates + publish run BEFORE metrics so the verdict and
                # the published generation land in metrics.json
                with self.timer.time("publish-registry"):
                    self._publish_to_registry()
            self._write_outputs()
        from photon_ml_tpu.parallel import overlap

        overlap.drain_io()  # queued artifact writes land before the barrier
        sync_processes("outputs-written")
        self.logger.info("stages: %s", [s.name for s in self.stage_history])
        self.logger.info("timers:\n%s", self.timer.summary())
        self.obs.finish()
        self.emitter.close()


# ---------------------------------------------------------------------------
# CLI (option names from OptionNames.scala)
# ---------------------------------------------------------------------------


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="photon-ml-tpu glm",
        description="TPU-native GLM training driver (Photon ML parity)",
    )
    ap.add_argument("--training-data-directory", required=True)
    ap.add_argument("--output-directory", required=True)
    ap.add_argument("--validating-data-directory", default=None)
    ap.add_argument("--train-date-range", default=None,
                    help="yyyyMMdd-yyyyMMdd; expects <dir>/daily/yyyy/MM/dd")
    ap.add_argument("--train-date-range-days-ago", default=None,
                    help="start-end days ago, e.g. 90-1")
    ap.add_argument("--validate-date-range", default=None)
    ap.add_argument("--validate-date-range-days-ago", default=None)
    ap.add_argument("--validate-per-iteration", default="false")
    ap.add_argument("--task", default="LOGISTIC_REGRESSION")
    ap.add_argument(
        "--format", default="TRAINING_EXAMPLE",
        help="Avro field-name convention: TRAINING_EXAMPLE | "
        "RESPONSE_PREDICTION (FieldNamesType). Legacy values AVRO|LIBSVM "
        "are accepted as --input-file-format.",
    )
    ap.add_argument(
        "--input-file-format", default=None, help="AVRO | LIBSVM"
    )
    ap.add_argument("--feature-dimension", type=int, default=None)
    ap.add_argument("--optimization-tracker", default="true")
    ap.add_argument(
        "--training-diagnostics", default=None,
        help="DEPRECATED -- use --diagnostic-mode (true -> ALL)",
    )
    # Spark-runtime tuning knobs, accepted for invocation compatibility
    # and ignored: serialization, input splits and treeAggregate depth
    # have no analog under XLA (psum replaces treeAggregate).
    ap.add_argument("--kryo", default=None, help="ignored (Spark-only)")
    ap.add_argument(
        "--min-partitions", type=int, default=None,
        help="ignored (Spark-only)",
    )
    ap.add_argument(
        "--tree-aggregate-depth", type=int, default=None,
        help="ignored (psum replaces treeAggregate)",
    )
    ap.add_argument("--intercept", default="true")
    ap.add_argument("--regularization-weights", default="0")
    ap.add_argument("--regularization-type", default="L2")
    ap.add_argument("--elastic-net-alpha", type=float, default=None)
    ap.add_argument("--optimizer", default="LBFGS")
    ap.add_argument("--num-iterations", type=int, default=None)
    ap.add_argument("--convergence-tolerance", type=float, default=None)
    ap.add_argument("--normalization-type", default="NONE")
    ap.add_argument("--data-validation-type", default="VALIDATE_FULL")
    ap.add_argument("--coefficient-box-constraints", default=None)
    ap.add_argument("--selected-features-file", default=None)
    ap.add_argument("--summarization-output-dir", default=None)
    ap.add_argument("--offheap-indexmap-dir", default=None)
    ap.add_argument("--offheap-indexmap-num-partitions", type=int, default=None)
    ap.add_argument("--diagnostic-mode", default=None)
    ap.add_argument("--compute-variances", default="false")
    ap.add_argument("--delete-output-dirs-if-exist", default="false")
    ap.add_argument("--job-name", default="photon-ml-tpu")
    ap.add_argument("--event-listeners", default=None)
    ap.add_argument(
        "--kernel", default="auto", choices=["auto", "tiled", "scatter"],
        help="objective kernel (auto: tiled Pallas on accelerators)",
    )
    ap.add_argument(
        "--distributed", default="auto",
        choices=["auto", "off", "feature"],
        help="auto: data-parallel when >1 device; feature: feature-sharded "
        "coefficients over a (data, model) mesh (>HBM models)",
    )
    ap.add_argument(
        "--model-shards", type=int, default=None,
        help="model-axis size for --distributed feature (default 2)",
    )
    ap.add_argument(
        "--streaming", default="false",
        help="true: stream the training data from disk per evaluation "
        "(bounded memory for >RAM datasets; Avro + L-BFGS/OWL-QN; "
        "composes with --distributed feature for >HBM models)",
    )
    ap.add_argument(
        "--stream-memory-budget", type=int, default=0,
        help="host-memory byte budget for the streaming layer: fixes "
        "the staged-chunk rows and cache tiers; peak RSS is reported "
        "against it in metrics.json. 0 = default sizing",
    )
    ap.add_argument(
        "--profile-dir", default=None,
        help="write a jax.profiler trace of the training stage here "
        "(TensorBoard/Perfetto-viewable)",
    )
    ap.add_argument(
        "--obs-dir", default=None,
        help="unified telemetry: training-span tracing + flight "
        "recorder; trace.json (Chrome trace-event), flight.json and "
        "metrics_snapshot.json land here atomically",
    )
    ap.add_argument(
        "--tile-cache-dir", default=None,
        help="persistent content-addressed tile-schedule cache directory: "
        "warm reruns over the same dataset load the tiled layout instead "
        "of rebuilding it (multi-host: process 0 writes, others read). "
        "Default: $PHOTON_TILE_CACHE_DIR, unset = off",
    )
    ap.add_argument(
        "--diagnostic-reservoir-rows", type=int, default=100_000,
        help="max rows in the streaming diagnostics reservoir sample",
    )
    ap.add_argument(
        "--diagnostic-reservoir-bytes", type=int, default=256 << 20,
        help="byte budget for the diagnostics reservoir (rows scale down "
        "when max nnz/row is large, preserving bounded memory)",
    )
    ap.add_argument(
        "--coordinator-address", default=None,
        help="host:port of process 0 for multi-host runs (jax.distributed)",
    )
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument(
        "--no-overlap", default="false",
        help="disable the host-device overlap layer (deferred readbacks, "
        "background host prep, async artifact writes) and run fully "
        "serial — the A/B escape hatch",
    )
    ap.add_argument(
        "--grid-mode", default="auto",
        choices=["batched", "sequential", "auto"],
        help="lambda-grid execution: batched = ONE vmapped optimizer "
        "program over a [G, d] coefficient bank (1 compile / 1 loop / 1 "
        "readback round, no cross-lambda warm starts); sequential = "
        "warm-started one-solve-per-lambda; auto = batched when the "
        "in-memory grid has >1 member and the bank fits "
        "--grid-memory-budget (streaming always runs sequential)",
    )
    ap.add_argument(
        "--grid-memory-budget", type=int, default=1 << 30,
        help="byte budget for the batched grid's G x d coefficient bank "
        "+ vmapped optimizer state; auto falls back to sequential above "
        "it (default 1 GiB)",
    )
    ap.add_argument(
        "--checkpoint-dir", default=None,
        help="crash-safe lambda-grid resume: completed lambdas snapshot "
        "here, SIGTERM stops at the next lambda boundary, and a rerun "
        "with the same args resumes mid-path (bitwise-identical final "
        "models)",
    )
    ap.add_argument(
        "--fault-plan", default=None,
        help="deterministic fault injection, e.g. "
        "'chunk_read:3:EIO,ckpt_save:1:ENOSPC:2' (seam:nth:error[:times]"
        "); also via PHOTON_FAULT_PLAN. Chaos harness: dev-scripts/"
        "chaos.sh",
    )
    ap.add_argument(
        "--retrain-from", default=None,
        help="model-registry directory: warm-start the coefficients "
        "from the latest committed generation with drift-safe "
        "alignment (new terms zero-init, removed terms dropped with "
        "accounting; bitwise pass-through when nothing drifted)",
    )
    ap.add_argument(
        "--publish-registry", default=None,
        help="model-registry directory: publish the trained best model "
        "as the next generation — gated against the parent on the "
        "validating directory when --retrain-from resolved one (a "
        "failed gate records a named verdict; the candidate is never "
        "loadable)",
    )
    ap.add_argument(
        "--scan-cache-dir", default=None,
        help="append-only per-partition scan/stats cache: the "
        "streaming preprocess re-reads ONLY partitions without a "
        "cache entry (the incremental-retrain fast path; counters in "
        "metrics.json)",
    )
    ap.add_argument("--gate-max-auc-drop", type=float, default=0.005)
    ap.add_argument("--gate-max-rmse-increase", type=float, default=0.01)
    ap.add_argument(
        "--gate-max-coef-norm-ratio", type=float, default=10.0
    )
    ap.add_argument(
        "--gate-max-prediction-drift", type=float, default=None,
        help="mean |candidate - parent| holdout margin bound "
        "(default: gate off)",
    )
    return ap


def _bool(s) -> bool:
    return str(s).strip().lower() in ("true", "1", "yes")


def params_from_args(argv=None) -> GLMParams:
    ns = build_arg_parser().parse_args(argv)
    # --format carries the FieldNamesType (reference semantics); legacy
    # invocations that passed AVRO|LIBSVM there are routed to
    # --input-file-format instead.
    fmt = (ns.format or "TRAINING_EXAMPLE").strip().upper()
    file_format = ns.input_file_format
    field_names = "TRAINING_EXAMPLE"
    if fmt in ("AVRO", "LIBSVM"):
        file_format = file_format or fmt
    elif fmt in ("TRAINING_EXAMPLE", "RESPONSE_PREDICTION", "NONE"):
        field_names = fmt
    else:
        raise ValueError(f"unknown --format {ns.format!r}")
    if ns.training_diagnostics is not None:
        # deprecated boolean (PhotonMLCmdLineParser.scala:68-69,184-186):
        # exclusive with --diagnostic-mode, maps to ALL/NONE
        if ns.diagnostic_mode is not None:
            raise ValueError(
                "specifying both training-diagnostics and diagnostic-mode "
                "is not supported"
            )
        ns.diagnostic_mode = (
            "ALL" if _bool(ns.training_diagnostics) else "NONE"
        )
    return GLMParams(
        train_dir=ns.training_data_directory,
        output_dir=ns.output_directory,
        validate_dir=ns.validating_data_directory,
        train_date_range=ns.train_date_range,
        train_date_range_days_ago=ns.train_date_range_days_ago,
        validate_date_range=ns.validate_date_range,
        validate_date_range_days_ago=ns.validate_date_range_days_ago,
        validate_per_iteration=_bool(ns.validate_per_iteration),
        task=TaskType.parse(ns.task),
        input_format=file_format or "AVRO",
        field_names=field_names,
        feature_dimension=ns.feature_dimension,
        enable_optimization_tracker=_bool(ns.optimization_tracker),
        add_intercept=_bool(ns.intercept),
        regularization_weights=[
            float(x) for x in ns.regularization_weights.split(",") if x
        ],
        regularization_type=RegularizationType.parse(ns.regularization_type),
        elastic_net_alpha=ns.elastic_net_alpha,
        optimizer_type=OptimizerType.parse(ns.optimizer),
        max_num_iterations=ns.num_iterations,
        tolerance=ns.convergence_tolerance,
        normalization_type=NormalizationType(ns.normalization_type.strip().upper()),
        data_validation_type=DataValidationType.parse(ns.data_validation_type),
        constraint_string=ns.coefficient_box_constraints,
        selected_features_file=ns.selected_features_file,
        summarization_output_dir=ns.summarization_output_dir,
        offheap_indexmap_dir=ns.offheap_indexmap_dir,
        offheap_indexmap_num_partitions=ns.offheap_indexmap_num_partitions,
        diagnostic_mode=DiagnosticMode.parse(ns.diagnostic_mode or "NONE"),
        compute_variances=_bool(ns.compute_variances),
        delete_output_dirs_if_exist=_bool(ns.delete_output_dirs_if_exist),
        job_name=ns.job_name,
        kernel=ns.kernel,
        distributed=ns.distributed,
        streaming=_bool(ns.streaming),
        stream_memory_budget=ns.stream_memory_budget,
        profile_dir=ns.profile_dir,
        obs_dir=ns.obs_dir,
        tile_cache_dir=ns.tile_cache_dir,
        no_overlap=_bool(ns.no_overlap),
        grid_mode=ns.grid_mode,
        grid_memory_budget=ns.grid_memory_budget,
        diagnostic_reservoir_rows=ns.diagnostic_reservoir_rows,
        diagnostic_reservoir_bytes=ns.diagnostic_reservoir_bytes,
        model_shards=ns.model_shards,
        coordinator_address=ns.coordinator_address,
        num_processes=ns.num_processes,
        process_id=ns.process_id,
        checkpoint_dir=ns.checkpoint_dir,
        fault_plan=ns.fault_plan,
        retrain_from=ns.retrain_from,
        publish_registry=ns.publish_registry,
        scan_cache_dir=ns.scan_cache_dir,
        gate_max_auc_drop=ns.gate_max_auc_drop,
        gate_max_rmse_increase=ns.gate_max_rmse_increase,
        gate_max_coef_norm_ratio=ns.gate_max_coef_norm_ratio,
        gate_max_prediction_drift=ns.gate_max_prediction_drift,
        event_listeners=(
            ns.event_listeners.split(",") if ns.event_listeners else []
        ),
    )


def main(argv=None) -> None:
    params = params_from_args(argv)
    driver = GLMDriver(params)
    driver.run()


if __name__ == "__main__":
    main()
