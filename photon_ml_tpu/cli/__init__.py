"""Driver entry points (the reference's cli/ job mains):

- ``glm_driver`` — staged GLM pipeline (train/validate/diagnose).
- ``game_training_driver`` — GAME coordinate descent over config grids.
- ``game_scoring_driver`` — offline batch scoring + evaluation.
- ``serving_driver`` — the online low-latency request path
  (photon_ml_tpu/serving): device-resident banks, micro-batching,
  hot model swaps.
- ``feature_indexing_driver`` — off-heap feature index build.

Each is runnable as ``python -m photon_ml_tpu.cli.<name>`` with
reference-parity option names where a reference job exists.
"""
