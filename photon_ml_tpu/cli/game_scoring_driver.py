"""GAME scoring driver: load model dir -> score Avro data -> write
ScoringResultAvro -> optional evaluation.

Reference: photon-ml .../cli/game/scoring/Driver.scala:171-204 (run:
prepareFeatureMaps -> prepareGameDataSet(isResponseRequired=false) ->
loadGameModelFromHDFS -> score -> saveScoresToHDFS -> evaluateScores) and
cli/game/scoring/Params.scala (option names kept), ScoredItem.scala.
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.evaluation import Evaluator, EvaluatorType
from photon_ml_tpu.game.data import build_game_dataset_from_files
from photon_ml_tpu.game.config import FeatureShardConfiguration
from photon_ml_tpu.game.model_io import load_game_model
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.utils.logging_util import PhotonLogger, Timer


@dataclass
class GameScoringParams:
    input_dirs: List[str] = field(default_factory=list)
    game_model_input_dir: str = ""
    output_dir: str = ""
    # Dated-input expansion over the input dirs (scoring Params
    # date-range / date-range-days-ago).
    date_range: Optional[str] = None
    date_range_days_ago: Optional[str] = None
    # Extra entity-id columns to extract and write with each score
    # (randomEffectTypeSet: ScoredItem carries idTypeToValueMap,
    # cli/game/scoring/Driver.scala:42,152).
    random_effect_id_set: List[str] = field(default_factory=list)
    # Split the scores output across N part files (numOutputFilesForScores).
    num_files: int = 1
    delete_output_dir_if_exists: bool = False
    application_name: str = "photon-ml-tpu-game-scoring"
    task_type: TaskType = TaskType.LOGISTIC_REGRESSION
    feature_shards: List[FeatureShardConfiguration] = field(default_factory=list)
    evaluator_types: List[EvaluatorType] = field(default_factory=list)
    model_id: str = ""
    has_response: bool = True
    # Feature-map sources (prepareFeatureMaps analog, shared with the
    # training driver; cli/game/GAMEDriver.scala:89-97): offheap stores
    # take precedence, then name-and-term list files, then maps built
    # from the scoring data.
    offheap_indexmap_dir: Optional[str] = None
    offheap_indexmap_num_partitions: Optional[int] = None
    feature_name_and_term_set_path: Optional[str] = None
    # jax.profiler trace of the scoring pass (SURVEY §7.11)
    profile_dir: Optional[str] = None
    # Unified telemetry (ISSUE 13): span tracing + flight recorder
    # under --obs-dir (trace.json / flight.json at exit).
    obs_dir: Optional[str] = None
    # Persistent content-addressed tile-schedule cache directory
    # (ops/schedule_cache.py), shared with the training drivers so a
    # scoring run over an already-trained dataset reuses its tiled
    # layout. None falls back to PHOTON_TILE_CACHE_DIR; unset = off.
    tile_cache_dir: Optional[str] = None
    # Escape hatch for the host-device overlap layer (parallel/overlap.py):
    # True writes score part files synchronously (serial A/B baseline).
    no_overlap: bool = False
    # Chunked scoring for inputs larger than memory (the reference scores
    # RDD partitions without collecting — Spark's memory profile by
    # construction); requires prebuilt feature maps, pointwise/global
    # evaluators only.
    streaming: bool = False
    rows_per_chunk: int = 100_000
    # Optional byte budget (the training drivers' --stream-memory-budget):
    # caps rows_per_chunk by the scored row's staged bytes so one flag
    # bounds the whole pipeline's host memory consistently.
    stream_memory_budget: int = 0
    # Deterministic fault plan (reliability.faults); also via
    # PHOTON_FAULT_PLAN. Chaos harness: dev-scripts/chaos.sh.
    fault_plan: Optional[str] = None

    def validate(self):
        if not self.input_dirs:
            raise ValueError("input-data-dirs is required")
        if self.stream_memory_budget and not self.streaming:
            raise ValueError(
                "stream-memory-budget requires --streaming true"
            )
        if self.streaming:
            # all param-detectable streaming misconfigurations fail HERE,
            # before __init__ touches (or deletes) the output directory
            if self.rows_per_chunk < 1:
                raise ValueError("rows-per-chunk must be >= 1")
            if not (
                self.offheap_indexmap_dir
                or self.feature_name_and_term_set_path
            ):
                raise ValueError(
                    "streaming scoring requires prebuilt feature maps "
                    "(--offheap-indexmap-dir or "
                    "--feature-name-and-term-set-path): no single chunk "
                    "sees the whole vocabulary"
                )
            for et in self.evaluator_types:
                if et.is_sharded:
                    raise ValueError(
                        f"sharded evaluator {et.render()!r} needs global "
                        "per-group data; use in-memory scoring"
                    )
        if not self.game_model_input_dir:
            raise ValueError("game-model-input-dir is required")
        if not self.output_dir:
            raise ValueError("output-dir is required")


class _ScoreRecordRows:
    """Sliceable, re-iterable score-record sequence over column arrays.

    ``__iter__`` streams one dict per row to the Avro writer (nothing
    row-shaped is materialized up front); ``[i::n]`` — the
    ``_write_parts`` round-robin split — returns another column view;
    re-iteration rebuilds rows from the columns, which keeps retried
    async writes idempotent (a consumed generator would silently write
    an empty part on retry)."""

    def __init__(self, uids, labels, scores, weights, meta_cols, model_id):
        self._uids = uids
        self._labels = labels
        self._scores = scores
        self._weights = weights
        self._meta_cols = meta_cols
        self._model_id = model_id

    def __len__(self) -> int:
        return len(self._uids)

    def __getitem__(self, sl):
        if not isinstance(sl, slice):
            raise TypeError("row views only slice")
        return _ScoreRecordRows(
            uids=self._uids[sl],
            labels=self._labels[sl] if self._labels is not None else None,
            scores=self._scores[sl],
            weights=self._weights[sl],
            meta_cols=[
                (t, vals[sl], mask[sl]) for t, vals, mask in self._meta_cols
            ],
            model_id=self._model_id,
        )

    def __iter__(self):
        labels = self._labels
        for i, uid in enumerate(self._uids):
            meta = {
                t: vals[i]
                for t, vals, mask in self._meta_cols
                if mask[i]
            }
            yield {
                "uid": uid,
                "label": labels[i] if labels is not None else None,
                "modelId": self._model_id,
                "predictionScore": self._scores[i],
                "weight": self._weights[i],
                "metadataMap": meta or None,
            }


class GameScoringDriver:
    def __init__(self, params: GameScoringParams, logger=None):
        params.validate()
        self.params = params
        if params.tile_cache_dir is not None:
            from photon_ml_tpu.ops.schedule_cache import configure

            configure(params.tile_cache_dir)
        if params.no_overlap:
            from photon_ml_tpu.parallel import overlap

            overlap.set_overlap(False)
        if params.fault_plan:
            from photon_ml_tpu.reliability import install_plan

            install_plan(params.fault_plan)
        from photon_ml_tpu.parallel.multihost import prepare_output_dir

        prepare_output_dir(
            params.output_dir,
            delete_if_exists=params.delete_output_dir_if_exists,
        )
        self.logger = logger or PhotonLogger(params.output_dir)
        self.timer = Timer()
        from photon_ml_tpu.obs import ObsSession

        self.obs = ObsSession(params.obs_dir, signal_dump=False)
        self.metrics: Dict[str, float] = {}

    def run(self) -> None:
        p = self.params
        self.logger.info("application: %s", p.application_name)
        with self.timer.time("load-model"):
            model = load_game_model(p.game_model_input_dir)
        self.logger.info("loaded coordinates: %s", model.coordinate_names())

        # id columns needed: RE types + MF types + sharded evaluator ids
        # + explicitly requested pass-through ids
        id_types = set(p.random_effect_id_set)
        for _, (re_type, _, _) in model.random_effects.items():
            id_types.add(re_type)
        for _, (rt, ct, _, _) in model.matrix_factorizations.items():
            id_types.update((rt, ct))
        for et in p.evaluator_types:
            if et.id_type:
                id_types.add(et.id_type)

        index_maps = None
        if p.offheap_indexmap_dir:
            from photon_ml_tpu.utils.native_index import load_offheap_index_maps

            index_maps = load_offheap_index_maps(
                p.offheap_indexmap_dir,
                [cfg.shard_id for cfg in p.feature_shards],
                num_partitions=p.offheap_indexmap_num_partitions,
            )
        elif p.feature_name_and_term_set_path:
            from photon_ml_tpu.io.name_term_list import (
                index_maps_from_name_term_lists,
            )

            index_maps = index_maps_from_name_term_lists(
                p.feature_name_and_term_set_path, p.feature_shards
            )
        from photon_ml_tpu.utils.date_range import expand_dated_paths

        input_paths = expand_dated_paths(
            p.input_dirs, p.date_range, p.date_range_days_ago, self.logger
        )
        from photon_ml_tpu.parallel.multihost import (
            is_coordinator,
            sync_processes,
        )
        from photon_ml_tpu.utils.profiling import profile_trace

        if p.streaming:
            self._run_streaming(model, sorted(id_types), index_maps, input_paths)
            self.obs.finish()
            sync_processes("scores-written")
            self.logger.info("timers:\n%s", self.timer.summary())
            return
        with self.timer.time("load-data"):
            dataset = build_game_dataset_from_files(
                input_paths,
                p.feature_shards,
                sorted(id_types),
                index_maps=index_maps,
                is_response_required=p.has_response,
            )
        with self.timer.time("score"), profile_trace(p.profile_dir):
            raw_scores = model.score(dataset, p.task_type)
            scores = raw_scores + jnp.asarray(dataset.offsets)

        if is_coordinator():
            with self.timer.time("write-scores"):
                from photon_ml_tpu.parallel import overlap

                # counted seam instead of a raw np.asarray readback
                self._write_scores(dataset, overlap.device_get(scores))
        if p.evaluator_types and p.has_response:
            with self.timer.time("evaluate"):
                self._evaluate(dataset, scores)
            if is_coordinator():
                from photon_ml_tpu.reliability import (
                    atomic_write_json,
                    reliability_metrics,
                )

                atomic_write_json(
                    os.path.join(p.output_dir, "metrics.json"),
                    {**self.metrics,
                     "reliability": reliability_metrics()},
                )
        self.obs.finish()
        sync_processes("scores-written")
        self.logger.info("timers:\n%s", self.timer.summary())

    def _run_streaming(self, model, id_types, index_maps, input_paths) -> None:
        """Chunked scoring: ONE input file loads at a time (through the
        native column decoder — the file is the natural partition unit,
        exactly like io/streaming.py's >RAM training path), then scores
        and writes in ``rows_per_chunk`` row slices. Peak memory is one
        file's features — the partition-streamed profile the reference
        gets from Spark by construction (cli/game/scoring/
        Driver.scala:171-204 scores RDD partitions without collecting).
        Pointwise + global-rank metrics accumulate on [n] float arrays;
        param-level guards (prebuilt maps, no sharded evaluators) live
        in GameScoringParams.validate."""
        from photon_ml_tpu.game.data import slice_game_dataset
        from photon_ml_tpu.io.paths import expand_input_paths
        from photon_ml_tpu.parallel import overlap
        from photon_ml_tpu.parallel.multihost import is_coordinator
        from photon_ml_tpu.utils.profiling import profile_trace

        p = self.params
        if p.num_files != 1:
            self.logger.warning(
                "--num-files is ignored in streaming mode: one scores "
                "part file is written per %d-row chunk", p.rows_per_chunk
            )
        # expand sorts within each directory and preserves the caller's
        # dir order — identical global order to the in-memory path (a
        # global re-sort would reassign fallback uids across dirs)
        files = expand_input_paths(
            input_paths, lambda fn: fn.endswith(".avro")
        )
        all_scores: List[np.ndarray] = []
        all_labels: List[np.ndarray] = []
        all_weights: List[np.ndarray] = []
        n_rows = 0
        part = 0
        rows_per_chunk = p.rows_per_chunk
        with self.timer.time("score-stream"), profile_trace(p.profile_dir):
            for path in files:
                try:
                    ds_file = build_game_dataset_from_files(
                        [path], p.feature_shards, id_types,
                        index_maps=index_maps,
                        is_response_required=p.has_response,
                        row_offset=n_rows,
                    )
                except ValueError as e:
                    if "empty GAME dataset" in str(e):
                        continue  # zero-record part file
                    raise
                if p.stream_memory_budget and n_rows == 0:
                    # one budget flag bounds the whole pipeline: cap the
                    # chunk rows by the scored row's staged bytes (every
                    # shard's padded slots + the scalar columns), like
                    # the training drivers' --stream-memory-budget
                    from photon_ml_tpu.game.streaming import game_row_bytes
                    from photon_ml_tpu.io.streaming import (
                        stream_budget_rows,
                    )

                    row_bytes = game_row_bytes(
                        {
                            sid: sd.indices.shape[1]
                            for sid, sd in ds_file.shards.items()
                        },
                        len(id_types),
                    )
                    rows_per_chunk = min(
                        rows_per_chunk,
                        stream_budget_rows(
                            p.stream_memory_budget, row_bytes,
                            default_rows=rows_per_chunk,
                        ),
                    )
                    self.logger.info(
                        "stream memory budget %d B -> %d rows/chunk",
                        p.stream_memory_budget, rows_per_chunk,
                    )
                for a in range(0, ds_file.num_real_rows, rows_per_chunk):
                    ds = slice_game_dataset(
                        ds_file, a, a + rows_per_chunk
                    )
                    scores = overlap.device_get(
                        model.score(ds, p.task_type)
                        + jnp.asarray(ds.offsets)
                    )[: ds.num_real_rows]
                    if is_coordinator():
                        # async artifact IO (overlap): chunk i's part
                        # file writes while chunk i+1 loads and scores;
                        # drained before the completion log/barrier
                        overlap.submit_io(
                            write_container,
                            os.path.join(
                                p.output_dir, "scores",
                                f"part-{part:05d}.avro",
                            ),
                            schemas.SCORING_RESULT_AVRO,
                            self._score_records(ds, scores),
                            artifact=f"scores/part-{part:05d}.avro",
                        )
                    part += 1
                    n_rows += ds.num_real_rows
                    if p.evaluator_types and p.has_response:
                        all_scores.append(scores)
                        all_labels.append(
                            np.asarray(ds.labels[: ds.num_real_rows])
                        )
                        all_weights.append(
                            np.asarray(ds.weights[: ds.num_real_rows])
                        )
        overlap.drain_io()  # every queued part file is on disk
        if n_rows == 0:
            raise ValueError("empty GAME dataset")  # in-memory parity
        self.logger.info(
            "streamed %d rows in %d chunk(s) from %d file(s)",
            n_rows, part, len(files),
        )
        if p.evaluator_types and p.has_response:
            with self.timer.time("evaluate"):
                self._evaluate_pointwise(
                    jnp.asarray(np.concatenate(all_scores)),
                    jnp.asarray(np.concatenate(all_labels)),
                    jnp.asarray(np.concatenate(all_weights)),
                )
            if is_coordinator():
                from photon_ml_tpu.reliability import (
                    atomic_write_json,
                    reliability_metrics,
                )

                atomic_write_json(
                    os.path.join(p.output_dir, "metrics.json"),
                    {**self.metrics,
                     "reliability": reliability_metrics()},
                )

    def _score_records(self, dataset, scores: np.ndarray) -> "_ScoreRecordRows":
        """Score records as a lazy column view: the scalar columns are
        materialized ONCE with vectorized numpy ops (`.tolist()` instead
        of a per-row/per-cell `float()`/`int()` cascade — the old hot
        path cost ~10us/row of Python casts) and each record dict is
        built only as the Avro writer consumes it. The view re-iterates
        from the columns, so async-write retries (reliability io_worker
        seam) replay it safely, and `_write_parts`' ``[i::n]`` split
        slices columns, not dicts."""
        n = dataset.num_real_rows
        id_types = sorted(dataset.entity_indexes)
        meta_cols = []
        for t in id_types:
            codes = np.asarray(dataset.entity_codes[t][:n])
            ids_arr = np.asarray(dataset.entity_indexes[t].ids, dtype=object)
            vals = (
                ids_arr[np.maximum(codes, 0)]
                if ids_arr.size
                else np.empty((n,), dtype=object)
            )
            meta_cols.append((t, vals, codes >= 0))
        return _ScoreRecordRows(
            uids=list(dataset.uids[:n]),
            labels=(
                np.asarray(dataset.labels[:n]).tolist()
                if self.params.has_response
                else None
            ),
            scores=np.asarray(scores[:n]).tolist(),
            weights=np.asarray(dataset.weights[:n]).tolist(),
            meta_cols=meta_cols,
            model_id=self.params.model_id or "game-model",
        )

    def _write_scores(self, dataset, scores: np.ndarray) -> None:
        from photon_ml_tpu.game.model_io import _write_parts

        _write_parts(
            os.path.join(self.params.output_dir, "scores"),
            schemas.SCORING_RESULT_AVRO,
            self._score_records(dataset, scores),
            self.params.num_files,
        )

    def _evaluate(self, dataset, scores) -> None:
        p = self.params
        lab = jnp.asarray(dataset.labels)
        w = jnp.asarray(dataset.weights)
        for et in p.evaluator_types:
            if et.is_sharded:
                gids = dataset.entity_codes[et.id_type]
                ev = Evaluator(
                    et, num_groups=dataset.entity_indexes[et.id_type].num_entities
                )
                value = float(
                    ev.evaluate(scores, lab, w, jnp.maximum(jnp.asarray(gids), 0))
                )
                self.metrics[et.render()] = value
                self.logger.info("%s = %g", et.render(), value)
            else:
                self._evaluate_pointwise(scores, lab, w, evaluators=[et])

    def _evaluate_pointwise(self, scores, lab, w, evaluators=None) -> None:
        """Non-sharded metrics — ONE definition shared by the in-memory
        and streaming paths so a metric change cannot diverge them."""
        p = self.params
        loss = loss_for_task(p.task_type)
        for et in evaluators if evaluators is not None else p.evaluator_types:
            metric_in = loss.mean(scores) if et.name == "RMSE" else scores
            value = float(Evaluator(et).evaluate(metric_in, lab, w))
            self.metrics[et.render()] = value
            self.logger.info("%s = %g", et.render(), value)


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="photon-ml-tpu game-scoring")
    ap.add_argument("--input-data-dirs", required=True)
    ap.add_argument("--game-model-input-dir", required=True)
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--task-type", default="LOGISTIC_REGRESSION")
    ap.add_argument("--feature-shard-id-to-feature-section-keys-map", required=True)
    ap.add_argument("--evaluator-types", default=None)
    ap.add_argument("--game-model-id", default=None)
    ap.add_argument("--model-id", default=None, help="alias of --game-model-id")
    ap.add_argument("--has-response", default="true")
    ap.add_argument("--offheap-indexmap-dir", default=None)
    ap.add_argument("--offheap-indexmap-num-partitions", type=int, default=None)
    ap.add_argument("--feature-name-and-term-set-path", default=None)
    ap.add_argument("--feature-shard-id-to-intercept-map", default=None)
    ap.add_argument("--date-range", default=None)
    ap.add_argument("--date-range-days-ago", default=None)
    ap.add_argument("--random-effect-id-set", default=None)
    ap.add_argument("--num-files", type=int, default=1)
    ap.add_argument("--delete-output-dir-if-exists", default="false")
    ap.add_argument("--application-name", default=None)
    ap.add_argument(
        "--obs-dir", default=None,
        help="unified telemetry: span tracing + flight recorder; "
        "trace.json / flight.json land here atomically",
    )
    ap.add_argument(
        "--profile-dir", default=None,
        help="write a jax.profiler trace of the scoring pass here",
    )
    ap.add_argument(
        "--tile-cache-dir", default=None,
        help="persistent tile-schedule cache directory shared with the "
        "training drivers. Default: $PHOTON_TILE_CACHE_DIR, unset = off",
    )
    ap.add_argument(
        "--streaming", default="false",
        help="true: score in bounded-memory chunks (needs prebuilt "
        "feature maps; sharded evaluators unsupported)",
    )
    ap.add_argument("--rows-per-chunk", type=int, default=100_000)
    ap.add_argument(
        "--stream-memory-budget", type=int, default=0,
        help="byte budget capping --rows-per-chunk by the scored row's "
        "staged bytes (one flag bounds the whole pipeline's host "
        "memory); 0 = use --rows-per-chunk as-is",
    )
    ap.add_argument(
        "--no-overlap", default="false",
        help="disable the host-device overlap layer (async score-part "
        "writes) and run fully serial",
    )
    ap.add_argument(
        "--fault-plan", default=None,
        help="deterministic fault injection "
        "(seam:nth:error[:times], comma-separated); also via "
        "PHOTON_FAULT_PLAN",
    )
    return ap


def params_from_args(argv=None) -> GameScoringParams:
    from photon_ml_tpu.cli.game_training_driver import (
        apply_intercept_map,
        parse_shard_map,
    )

    ns = build_arg_parser().parse_args(argv)
    return GameScoringParams(
        input_dirs=ns.input_data_dirs.split(","),
        game_model_input_dir=ns.game_model_input_dir,
        output_dir=ns.output_dir,
        task_type=TaskType.parse(ns.task_type),
        feature_shards=apply_intercept_map(
            parse_shard_map(ns.feature_shard_id_to_feature_section_keys_map),
            ns.feature_shard_id_to_intercept_map,
        ),
        evaluator_types=(
            [EvaluatorType.parse(s) for s in ns.evaluator_types.split(",")]
            if ns.evaluator_types
            else []
        ),
        model_id=ns.game_model_id or ns.model_id or "",
        profile_dir=ns.profile_dir,
        obs_dir=ns.obs_dir,
        tile_cache_dir=ns.tile_cache_dir,
        no_overlap=str(ns.no_overlap).lower() in ("true", "1", "yes"),
        streaming=str(ns.streaming).lower() in ("true", "1", "yes"),
        rows_per_chunk=ns.rows_per_chunk,
        stream_memory_budget=ns.stream_memory_budget,
        fault_plan=ns.fault_plan,
        has_response=str(ns.has_response).lower() in ("true", "1", "yes"),
        date_range=ns.date_range,
        date_range_days_ago=ns.date_range_days_ago,
        random_effect_id_set=(
            [s for s in ns.random_effect_id_set.split(",") if s]
            if ns.random_effect_id_set
            else []
        ),
        num_files=ns.num_files,
        delete_output_dir_if_exists=(
            str(ns.delete_output_dir_if_exists).lower()
            in ("true", "1", "yes")
        ),
        application_name=ns.application_name or "photon-ml-tpu-game-scoring",
        offheap_indexmap_dir=ns.offheap_indexmap_dir,
        offheap_indexmap_num_partitions=ns.offheap_indexmap_num_partitions,
        feature_name_and_term_set_path=ns.feature_name_and_term_set_path,
    )


def main(argv=None) -> None:
    GameScoringDriver(params_from_args(argv)).run()


if __name__ == "__main__":
    main()
