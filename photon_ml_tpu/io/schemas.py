"""The Photon ML Avro data contract, as Python schema objects.

Wire-format parity with the reference module `photon-avro-schemas`
(photon-avro-schemas/src/main/avro/*.avsc): same record/field names,
namespaces, types and defaults, so files written by either system are
readable by the other. Authored here from the documented contract.
"""

NAMESPACE = "com.linkedin.photon.avro.generated"

NAME_TERM_VALUE_AVRO = {
    "name": "NameTermValueAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

FEATURE_AVRO = {
    "name": "FeatureAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE_AVRO = {
    "name": "TrainingExampleAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE_AVRO}},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}

BAYESIAN_LINEAR_MODEL_AVRO = {
    "name": "BayesianLinearModelAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE_AVRO}},
        {
            "name": "variances",
            "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
            "default": None,
        },
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}

LATENT_FACTOR_AVRO = {
    "name": "LatentFactorAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "effectId", "type": "string"},
        {"name": "latentFactor", "type": {"type": "array", "items": "double"}},
    ],
}

SCORING_RESULT_AVRO = {
    "name": "ScoringResultAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "modelId", "type": "string"},
        {"name": "predictionScore", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
    ],
}

FEATURE_SUMMARIZATION_RESULT_AVRO = {
    "name": "FeatureSummarizationResultAvro",
    "namespace": NAMESPACE,
    "type": "record",
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": "string"},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}
