"""GLM model persistence: text + BayesianLinearModelAvro.

Reference: photon-ml .../util/IOUtils.scala:206-259 (writeModelsInText —
per-lambda files of ``name TAB term TAB value TAB lambda`` rows sorted by
coefficient value descending) and avro/AvroUtils / ModelProcessingUtils'
BayesianLinearModelAvro conversion (means/variances as NameTermValue lists,
modelClass = the reference's GLM class names for cross-compat).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import numpy as np

from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import read_container, write_container
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel, create_model
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.utils.index_map import IndexMap, split_feature_key

# Cross-compat class names (the reference writes/reads these in
# BayesianLinearModelAvro.modelClass).
_MODEL_CLASS_BY_TASK = {
    TaskType.LOGISTIC_REGRESSION:
        "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
    TaskType.LINEAR_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    TaskType.POISSON_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
}
_TASK_BY_MODEL_CLASS = {v: k for k, v in _MODEL_CLASS_BY_TASK.items()}


def write_models_in_text(
    models: Dict[float, GeneralizedLinearModel],
    model_dir: str,
    index_map: IndexMap,
) -> None:
    """One ``<lambda>.txt`` per model; rows sorted by value descending
    (IOUtils.writeModelsInText parity)."""
    os.makedirs(model_dir, exist_ok=True)
    for lam, model in models.items():
        means = np.asarray(model.means)
        order = np.argsort(-means)
        lines = []
        for i in order:
            key = index_map.get_feature_name(int(i))
            if key is None:
                continue
            name, term = split_feature_key(key)
            lines.append(f"{name}\t{term}\t{means[i]}\t{lam}")
        from photon_ml_tpu.reliability.artifacts import atomic_writer

        with atomic_writer(os.path.join(model_dir, f"{lam}.txt")) as f:
            f.write("\n".join(lines) + "\n")


def model_to_bayesian_avro(
    model: GeneralizedLinearModel,
    model_id: str,
    index_map: IndexMap,
) -> dict:
    means = np.asarray(model.coefficients.means)
    variances = (
        None
        if model.coefficients.variances is None
        else np.asarray(model.coefficients.variances)
    )

    def ntv_list(values: np.ndarray):
        out = []
        for i, v in enumerate(values):
            key = index_map.get_feature_name(int(i))
            if key is None:
                continue
            name, term = split_feature_key(key)
            out.append({"name": name, "term": term, "value": float(v)})
        return out

    return {
        "modelId": model_id,
        "modelClass": _MODEL_CLASS_BY_TASK[model.task],
        "means": ntv_list(means),
        "variances": None if variances is None else ntv_list(variances),
        "lossFunction": None,
    }


def bayesian_avro_to_model(
    record: dict,
    index_map: IndexMap,
    *,
    task: Optional[TaskType] = None,
    dim: Optional[int] = None,
) -> Tuple[str, GeneralizedLinearModel]:
    """-> (modelId, model). Unknown feature keys are dropped (reference
    behavior when loading with a narrower index map)."""
    import jax.numpy as jnp
    from photon_ml_tpu.utils.index_map import feature_key

    d = dim if dim is not None else index_map.size
    means = np.zeros((d,), np.float32)
    for ntv in record["means"]:
        i = index_map.get_index(feature_key(ntv["name"], ntv["term"]))
        if 0 <= i < d:
            means[i] = ntv["value"]
    variances = None
    if record.get("variances"):
        variances = np.zeros((d,), np.float32)
        for ntv in record["variances"]:
            i = index_map.get_index(feature_key(ntv["name"], ntv["term"]))
            if 0 <= i < d:
                variances[i] = ntv["value"]
    if task is None:
        cls = record.get("modelClass")
        task = _TASK_BY_MODEL_CLASS.get(cls, TaskType.LINEAR_REGRESSION)
    coefficients = Coefficients(
        jnp.asarray(means),
        None if variances is None else jnp.asarray(variances),
    )
    return record["modelId"], create_model(task, coefficients)


def save_glm_models_avro(
    models: Dict[float, GeneralizedLinearModel],
    path: str,
    index_map: IndexMap,
) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    records = [
        model_to_bayesian_avro(model, str(lam), index_map)
        for lam, model in models.items()
    ]
    write_container(path, schemas.BAYESIAN_LINEAR_MODEL_AVRO, records)


def load_glm_models_avro(
    path: str,
    index_map: IndexMap,
    *,
    task: Optional[TaskType] = None,
) -> Dict[str, GeneralizedLinearModel]:
    _, it = read_container(path)
    out = {}
    for record in it:
        model_id, model = bayesian_avro_to_model(record, index_map, task=task)
        out[model_id] = model
    return out
