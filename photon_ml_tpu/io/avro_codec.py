"""Pure-Python Avro binary codec + object container files.

The runtime image ships no Avro library, so this is a from-scratch
implementation of the subset of the Avro 1.x spec the Photon ML data
contract needs (reference wire formats: photon-avro-schemas/src/main/avro/
*.avsc — records, unions with null, arrays, maps, enums, fixed, and all
primitives; container files with null/deflate codecs).

Reads are tolerant: any writer schema expressible in the supported subset
round-trips. Datum values map to plain Python types:
record -> dict, array -> list, map -> dict, union -> member value,
bytes/fixed -> bytes, null -> None.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterable, Iterator, List, Optional, Tuple, Union

MAGIC = b"Obj\x01"
SYNC_SIZE = 16
DEFAULT_SYNC_INTERVAL = 16 * 1024

_PRIMITIVES = {
    "null", "boolean", "int", "long", "float", "double", "bytes", "string"
}

SchemaType = Union[str, dict, list]


def parse_schema(
    schema: Union[str, SchemaType],
    named: Optional[Dict[str, dict]] = None,
) -> SchemaType:
    """Normalize a schema (JSON string or python structure), resolving named
    type references into a flat registry carried on the schema objects."""
    if isinstance(schema, str) and schema.lstrip().startswith(("{", "[", '"')):
        schema = json.loads(schema)
    named = named if named is not None else {}
    return _resolve(schema, named)


def _fullname(schema: dict) -> str:
    name = schema["name"]
    ns = schema.get("namespace")
    if ns and "." not in name:
        return f"{ns}.{name}"
    return name


def _resolve(schema: SchemaType, named: Dict[str, dict]) -> SchemaType:
    if isinstance(schema, str):
        if schema in _PRIMITIVES:
            return schema
        # named-type reference: try short and full name
        for key in (schema,):
            if key in named:
                return named[key]
        for full, s in named.items():
            if full.split(".")[-1] == schema:
                return s
        raise ValueError(f"unresolved schema reference: {schema}")
    if isinstance(schema, list):  # union
        return [_resolve(s, named) for s in schema]
    t = schema.get("type")
    if t in ("record", "error"):
        named[_fullname(schema)] = schema
        named[schema["name"]] = schema
        for f in schema["fields"]:
            f["type"] = _resolve(f["type"], named)
        return schema
    if t in ("enum", "fixed"):
        named[_fullname(schema)] = schema
        named[schema["name"]] = schema
        return schema
    if t == "array":
        schema["items"] = _resolve(schema["items"], named)
        return schema
    if t == "map":
        schema["values"] = _resolve(schema["values"], named)
        return schema
    if isinstance(t, (dict, list)):
        return _resolve(t, named)
    if t in _PRIMITIVES:
        return t
    raise ValueError(f"unsupported schema: {schema!r}")


# ---------------------------------------------------------------------------
# Binary encoding (Avro spec: zigzag varints, IEEE754 little-endian floats)
# ---------------------------------------------------------------------------


class BinaryEncoder:
    def __init__(self, out: BinaryIO):
        self.out = out

    def write_long(self, n: int) -> None:
        n = (n << 1) ^ (n >> 63)  # zigzag
        while (n & ~0x7F) != 0:
            self.out.write(bytes((n & 0x7F | 0x80,)))
            n >>= 7
        self.out.write(bytes((n,)))

    write_int = write_long

    def write_null(self, _=None) -> None:
        pass

    def write_boolean(self, b: bool) -> None:
        self.out.write(b"\x01" if b else b"\x00")

    def write_float(self, x: float) -> None:
        self.out.write(struct.pack("<f", x))

    def write_double(self, x: float) -> None:
        self.out.write(struct.pack("<d", x))

    def write_bytes(self, b: bytes) -> None:
        self.write_long(len(b))
        self.out.write(b)

    def write_string(self, s: str) -> None:
        self.write_bytes(s.encode("utf-8"))


class BinaryDecoder:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # un-zigzag

    read_int = read_long

    def read_null(self):
        return None

    def read_boolean(self) -> bool:
        b = self.buf[self.pos]
        self.pos += 1
        return b != 0

    def read_float(self) -> float:
        v = struct.unpack_from("<f", self.buf, self.pos)[0]
        self.pos += 4
        return v

    def read_double(self) -> float:
        v = struct.unpack_from("<d", self.buf, self.pos)[0]
        self.pos += 8
        return v

    def read_bytes(self) -> bytes:
        n = self.read_long()
        v = self.buf[self.pos : self.pos + n]
        self.pos += n
        return v

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")


# ---------------------------------------------------------------------------
# Datum read/write
# ---------------------------------------------------------------------------


def _schema_type(schema: SchemaType) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    t = schema["type"]
    return t if isinstance(t, str) else _schema_type(t)


def write_datum(enc: BinaryEncoder, schema: SchemaType, datum: Any) -> None:
    t = _schema_type(schema)
    if t == "null":
        enc.write_null()
    elif t == "boolean":
        enc.write_boolean(bool(datum))
    elif t in ("int", "long"):
        enc.write_long(int(datum))
    elif t == "float":
        enc.write_float(float(datum))
    elif t == "double":
        enc.write_double(float(datum))
    elif t == "bytes":
        enc.write_bytes(bytes(datum))
    elif t == "string":
        enc.write_string(str(datum))
    elif t == "fixed":
        enc.out.write(bytes(datum))
    elif t == "enum":
        enc.write_long(schema["symbols"].index(datum))
    elif t == "union":
        idx = _pick_union_branch(schema, datum)
        enc.write_long(idx)
        write_datum(enc, schema[idx], datum)
    elif t == "array":
        items = list(datum)
        if items:
            enc.write_long(len(items))
            for it in items:
                write_datum(enc, schema["items"], it)
        enc.write_long(0)
    elif t == "map":
        entries = dict(datum)
        if entries:
            enc.write_long(len(entries))
            for k, v in entries.items():
                enc.write_string(k)
                write_datum(enc, schema["values"], v)
        enc.write_long(0)
    elif t == "record":
        for f in schema["fields"]:
            name = f["name"]
            if name in datum:
                value = datum[name]
            elif "default" in f:
                value = f["default"]
            else:
                raise ValueError(
                    f"missing field {name!r} for record {schema.get('name')}"
                )
            write_datum(enc, f["type"], value)
    else:
        raise ValueError(f"unsupported type: {t}")


def _pick_union_branch(union: list, datum: Any) -> int:
    def matches(s: SchemaType) -> bool:
        st = _schema_type(s)
        if datum is None:
            return st == "null"
        if isinstance(datum, bool):
            return st == "boolean"
        if isinstance(datum, int):
            return st in ("int", "long", "float", "double")
        if isinstance(datum, float):
            return st in ("float", "double")
        if isinstance(datum, str):
            return st in ("string", "enum")
        if isinstance(datum, bytes):
            return st in ("bytes", "fixed")
        if isinstance(datum, dict):
            return st in ("record", "map")
        if isinstance(datum, (list, tuple)):
            return st == "array"
        return False

    for i, s in enumerate(union):
        if matches(s):
            return i
    raise ValueError(f"no union branch for {type(datum)} in {union}")


def read_datum(dec: BinaryDecoder, schema: SchemaType) -> Any:
    t = _schema_type(schema)
    if t == "null":
        return None
    if t == "boolean":
        return dec.read_boolean()
    if t in ("int", "long"):
        return dec.read_long()
    if t == "float":
        return dec.read_float()
    if t == "double":
        return dec.read_double()
    if t == "bytes":
        return dec.read_bytes()
    if t == "string":
        return dec.read_string()
    if t == "fixed":
        size = schema["size"]
        v = dec.buf[dec.pos : dec.pos + size]
        dec.pos += size
        return v
    if t == "enum":
        return schema["symbols"][dec.read_long()]
    if t == "union":
        return read_datum(dec, schema[dec.read_long()])
    if t == "array":
        out: List[Any] = []
        while True:
            n = dec.read_long()
            if n == 0:
                break
            if n < 0:  # block with byte size
                n = -n
                dec.read_long()
            for _ in range(n):
                out.append(read_datum(dec, schema["items"]))
        return out
    if t == "map":
        entries: Dict[str, Any] = {}
        while True:
            n = dec.read_long()
            if n == 0:
                break
            if n < 0:
                n = -n
                dec.read_long()
            for _ in range(n):
                k = dec.read_string()
                entries[k] = read_datum(dec, schema["values"])
        return entries
    if t == "record":
        return {f["name"]: read_datum(dec, f["type"]) for f in schema["fields"]}
    raise ValueError(f"unsupported type: {t}")


# ---------------------------------------------------------------------------
# Object container files
# ---------------------------------------------------------------------------


def write_container(
    path: str,
    schema: Union[str, SchemaType],
    records: Iterable[dict],
    *,
    codec: str = "deflate",
    sync_interval: int = DEFAULT_SYNC_INTERVAL,
) -> int:
    """Write an Avro object container file; returns the record count.

    Atomic: bytes land in a same-directory temp file and ``os.replace``
    publishes them, so a reader (or a resumed run) never sees a torn
    container — part files, model files and summaries are all artifacts
    a crash must not leave half-written (reliability layer contract,
    enforced by lint rule PL006)."""
    # parse_schema mutates nested dicts while resolving references — give it
    # a copy so the caller's schema object stays pristine.
    parsed = parse_schema(
        json.loads(json.dumps(schema)) if isinstance(schema, (dict, list)) else schema
    )
    schema_json = json.dumps(schema) if isinstance(schema, (dict, list)) else schema
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported codec: {codec}")
    # DETERMINISTIC sync marker (was os.urandom): the marker only
    # delimits blocks (the reader walks block counts/sizes and checks
    # it), so deriving it from the schema alone makes the container
    # byte-reproducible for identical records — the chaos matrix and
    # the kill-9 resume tests assert fault-injected / resumed runs are
    # BITWISE equal to clean runs, artifact files included (which also
    # means it must NOT depend on the output path).
    import hashlib

    sync = hashlib.blake2b(
        f"photon-avro-sync|{schema_json}".encode(), digest_size=SYNC_SIZE
    ).digest()
    count_total = 0
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    from photon_ml_tpu.reliability.artifacts import atomic_writer

    with atomic_writer(path, "wb") as f:
        f.write(MAGIC)
        meta_enc = BinaryEncoder(f)
        write_datum(
            meta_enc,
            {"type": "map", "values": "bytes"},
            {
                "avro.schema": schema_json.encode("utf-8"),
                "avro.codec": codec.encode("utf-8"),
            },
        )
        f.write(sync)

        buf = io.BytesIO()
        enc = BinaryEncoder(buf)
        block_count = 0

        def flush_block():
            nonlocal block_count, count_total
            if block_count == 0:
                return
            raw = buf.getvalue()
            payload = (
                raw if codec == "null" else zlib.compress(raw)[2:-4]
            )  # deflate = zlib minus header/checksum
            out = BinaryEncoder(f)
            out.write_long(block_count)
            out.write_long(len(payload))
            f.write(payload)
            f.write(sync)
            count_total += block_count
            block_count = 0
            buf.seek(0)
            buf.truncate()

        for rec in records:
            write_datum(enc, parsed, rec)
            block_count += 1
            if buf.tell() >= sync_interval:
                flush_block()
        flush_block()
    return count_total


def read_container(path: str) -> Tuple[SchemaType, Iterator[dict]]:
    """Read an Avro object container file -> (schema, record iterator)."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != MAGIC:
        raise ValueError(f"{path}: not an Avro container file")
    dec = BinaryDecoder(data, 4)
    meta = read_datum(dec, {"type": "map", "values": "bytes"})
    schema = parse_schema(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported codec: {codec}")
    sync = data[dec.pos : dec.pos + SYNC_SIZE]
    dec.pos += SYNC_SIZE

    def it() -> Iterator[dict]:
        pos = dec.pos
        while pos < len(data):
            d = BinaryDecoder(data, pos)
            n = d.read_long()
            size = d.read_long()
            block = data[d.pos : d.pos + size]
            d.pos += size
            if data[d.pos : d.pos + SYNC_SIZE] != sync:
                raise ValueError(f"{path}: sync marker mismatch")
            pos = d.pos + SYNC_SIZE
            if codec == "deflate":
                block = zlib.decompress(block, -15)
            bd = BinaryDecoder(block)
            for _ in range(n):
                yield read_datum(bd, schema)

    return schema, it()


def read_container_schema(path: str) -> SchemaType:
    """Parse ONLY the header schema without slurping the whole file —
    reads a growing prefix until the metadata map decodes cleanly."""
    size = 1 << 16
    while True:
        with open(path, "rb") as f:
            data = f.read(size)
        if data[:4] != MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        try:
            dec = BinaryDecoder(data, 4)
            meta = read_datum(dec, {"type": "map", "values": "bytes"})
            if dec.pos > len(data):
                raise IndexError("truncated header")
            return parse_schema(meta["avro.schema"].decode("utf-8"))
        except (IndexError, KeyError, UnicodeDecodeError, ValueError) as e:
            if len(data) < size:  # whole file read and still bad
                raise ValueError(f"{path}: bad container header") from e
            size *= 4


def read_avro_records(paths: Union[str, List[str]]) -> Iterator[dict]:
    """Iterate records across one or many container files / directories
    (AvroUtils.readAvroFiles analog; directories expand to their *.avro,
    skipping hidden/marker files)."""
    from photon_ml_tpu.io.paths import expand_input_paths

    for p in expand_input_paths(paths, lambda fn: fn.endswith(".avro")):
        _, it = read_container(p)
        yield from it
