"""Feature name-and-term list files: per-section feature vocabularies.

Reference: photon-ml .../avro/data/NameAndTermFeatureSetContainer.scala —
a directory with one subdirectory per feature section key holding text
files of ``name TAB term`` lines (one feature per line, term optional,
:101-126); the GAME drivers' default (pre-PalDB) feature-map source
(cli/game/GAMEDriver.scala:49-69 prepareFeatureMapsDefault): a shard's
index map is the union of its section keys' feature sets, indexed
deterministically, with an optional intercept appended. The container's
``main`` is a standalone list-generation job over response-prediction
Avro data (:128-160) — here :func:`generate_name_and_term_lists`.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, Mapping, Sequence, Set

from photon_ml_tpu.utils.index_map import IndexMap, feature_key


def read_name_and_term_set(path: str) -> Set[str]:
    """One section directory (or file) -> set of feature keys.
    Lines are ``name TAB term`` or just ``name`` (empty term)."""
    from photon_ml_tpu.io.paths import expand_input_paths

    keys: Set[str] = set()
    for p in expand_input_paths([path]):
        with open(p, "r", encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                parts = line.split("\t")
                if len(parts) == 1:
                    keys.add(feature_key(parts[0]))
                elif len(parts) == 2:
                    keys.add(feature_key(parts[0], parts[1]))
                else:
                    raise ValueError(
                        f"{p}: expected 'name' or 'name<TAB>term', got "
                        f"{line!r}"
                    )
    return keys


def read_name_and_term_feature_sets(
    input_dir: str, section_keys: Iterable[str]
) -> Dict[str, Set[str]]:
    """``<input_dir>/<sectionKey>`` per section -> {section: feature keys}
    (readNameAndTermFeatureSetContainerFromTextFiles)."""
    out: Dict[str, Set[str]] = {}
    for section in section_keys:
        path = os.path.join(input_dir, section)
        if not os.path.exists(path):
            raise OSError(
                f"no feature list for section {section!r} at {path}"
            )
        out[section] = read_name_and_term_set(path)
    return out


def save_name_and_term_feature_sets(
    sets: Mapping[str, Iterable[str]], output_dir: str
) -> None:
    """{section: feature keys} -> one text file per section
    (saveAsTextFiles layout: ``<output_dir>/<section>/part-00000``)."""
    for section, keys in sets.items():
        d = os.path.join(output_dir, section)
        os.makedirs(d, exist_ok=True)
        from photon_ml_tpu.reliability.artifacts import atomic_writer

        with atomic_writer(
            os.path.join(d, "part-00000"), encoding="utf-8"
        ) as f:
            for key in sorted(set(keys)):
                f.write(key + "\n")  # key is already name<TAB>term


def index_map_from_sections(
    sets: Mapping[str, Set[str]],
    section_keys: Sequence[str],
    *,
    add_intercept: bool = True,
) -> IndexMap:
    """Union of the given sections' feature sets -> IndexMap
    (getFeatureNameAndTermToIndexMap; deterministic sorted order instead
    of the reference's set-iteration order, intercept last)."""
    union: Set[str] = set()
    for section in section_keys:
        union |= sets[section]
    return IndexMap.build(union, add_intercept=add_intercept)


def index_maps_from_name_term_lists(
    path: str, feature_shards
) -> Dict[str, IndexMap]:
    """{shard_id: IndexMap} for a list of FeatureShardConfiguration —
    the drivers' --feature-name-and-term-set-path source (union of each
    shard's section lists, per-shard intercept flag)."""
    all_sections = sorted({b for cfg in feature_shards for b in cfg.feature_bags})
    sets = read_name_and_term_feature_sets(path, all_sections)
    return {
        cfg.shard_id: index_map_from_sections(
            sets, list(cfg.feature_bags), add_intercept=cfg.add_intercept
        )
        for cfg in feature_shards
    }


def generate_name_and_term_lists(
    input_paths,
    section_keys: Sequence[str],
    output_dir: str,
) -> Dict[str, Set[str]]:
    """Scan Avro data's feature bags and write per-section list files
    (the NameAndTermFeatureSetContainer.main job analog). Returns the
    sets it wrote."""
    from photon_ml_tpu.io.avro_codec import read_avro_records

    sets: Dict[str, Set[str]] = {s: set() for s in section_keys}
    for record in read_avro_records(input_paths):
        for section in section_keys:
            for f in record.get(section) or []:
                sets[section].add(feature_key(f["name"], f["term"]))
    save_name_and_term_feature_sets(sets, output_dir)
    return sets
