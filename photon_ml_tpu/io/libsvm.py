"""LibSVM text reader.

Reference: photon-ml .../io/LibSVMInputDataFormat.scala:43-75 — lines of
``label idx:value idx:value ...``; indices are 1-based in the classic
format; labels in {-1,+1} or {0,1} are mapped to {0,1}. Feature keys become
``str(idx)`` names with empty terms so one IndexMap vocabulary serves both
input formats.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Optional, Tuple

from photon_ml_tpu.utils.index_map import feature_key

Row = Tuple[List[int], List[float]]


def parse_libsvm_line(
    line: str, *, zero_based: bool = False
) -> Optional[Tuple[float, List[Tuple[int, float]]]]:
    """-> (label, [(index, value), ...]) or None for blank/comment lines."""
    line = line.split("#", 1)[0].strip()
    if not line:
        return None
    parts = line.split()
    label = float(parts[0])
    if label < 0:  # {-1,+1} -> {0,1}
        label = 0.0
    pairs = []
    for tok in parts[1:]:
        idx_s, _, val_s = tok.partition(":")
        idx = int(idx_s)
        if not zero_based:
            idx -= 1
        pairs.append((idx, float(val_s)))
    return label, pairs


def read_libsvm(
    paths, *, zero_based: bool = False
) -> Iterator[Tuple[float, List[Tuple[int, float]]]]:
    """Iterate (label, [(index, value)]) over one or many files;
    directories expand to their visible regular files (hidden and
    underscore-marker files like _SUCCESS are skipped)."""
    from photon_ml_tpu.io.paths import expand_input_paths

    for path in expand_input_paths(paths):
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                parsed = parse_libsvm_line(line, zero_based=zero_based)
                if parsed is not None:
                    yield parsed


def libsvm_feature_keys(
    examples: Iterable[Tuple[float, List[Tuple[int, float]]]]
) -> Iterator[str]:
    for _, pairs in examples:
        for idx, _ in pairs:
            yield feature_key(str(idx))
