"""Input-path expansion shared by the data readers.

The reference's readers get this from Hadoop's FileInputFormat, which skips
hidden ("." prefix) and marker ("_" prefix, e.g. _SUCCESS) files; daily
dated directories routinely contain both, so the filter is load-bearing for
the date-range path (IOUtils.scala:84+).
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, Union


def _visible(fn: str) -> bool:
    return not fn.startswith(".") and not fn.startswith("_")


def expand_input_paths(
    paths: Union[str, Sequence[str]],
    predicate: Optional[Callable[[str], bool]] = None,
) -> List[str]:
    """Expand files-or-directories to a sorted flat file list.

    Directories expand to their visible regular files accepted by
    ``predicate`` (default: all); explicit file paths pass through
    unfiltered (the caller named them on purpose).
    """
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(
                sorted(
                    os.path.join(p, fn)
                    for fn in os.listdir(p)
                    if _visible(fn)
                    and os.path.isfile(os.path.join(p, fn))
                    and (predicate is None or predicate(fn))
                )
            )
        else:
            out.append(p)
    return out
