"""I/O: Avro codec + Photon schemas, LibSVM, input formats, model I/O."""

from photon_ml_tpu.io.avro_codec import (
    read_avro_records,
    read_container,
    write_container,
)
from photon_ml_tpu.io.input_format import (
    AvroInputDataFormat,
    LibSVMInputDataFormat,
    LoadedData,
    create_input_format,
    parse_constraint_string,
)

__all__ = [
    "read_avro_records",
    "read_container",
    "write_container",
    "AvroInputDataFormat",
    "LibSVMInputDataFormat",
    "LoadedData",
    "create_input_format",
    "parse_constraint_string",
]
