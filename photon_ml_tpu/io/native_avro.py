"""Plan-driven native Avro column decoder (ctypes binding).

Reference role: avro/AvroUtils.scala:54+ and avro/data/
DataProcessingUtils.scala:57-143 decode Avro GenericRecords on the JVM
inside Spark executors; the pure-Python fallback here is
photon_ml_tpu.io.avro_codec. This binding compiles the record schema
into a compact uint32 "plan" (see native/avro_reader.cpp for the
bytecode) and lets the C++ interpreter materialize ONLY the requested
columns: numeric scalars as float64, string scalars / metadataMap
lookups as interned int32 ids, and feature bags as
(row_ptr, key_ids, values) with a per-file string table.

Use :func:`decode_columns` directly, or the higher-level helpers in the
input formats which fall back to the Python codec when the native build
or the schema shape is unsupported.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.io.avro_codec import read_container

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO_ROOT, "native", "avro_reader.cpp")
_LIB_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_LIB_DIR, "libavro_reader.so")
_COMPILE_LOCK = threading.Lock()
_lib_handle = None

# bytecode opcodes — keep in sync with native/avro_reader.cpp
_OPS = {
    "null": 0, "boolean": 1, "int": 2, "long": 3, "float": 4,
    "double": 5, "bytes": 6, "string": 7,
}
_OP_UNION, _OP_RECORD, _OP_ARRAY, _OP_MAP = 8, 9, 10, 11
_CAP_NUM, _CAP_STR, _CAP_BAG, _CAP_MAP = 16, 17, 18, 19
_NUMERIC = {"boolean", "int", "long", "float", "double"}


class PlanError(ValueError):
    """Schema shape the native decoder cannot handle; callers fall back."""


def _lib():
    global _lib_handle
    if _lib_handle is not None:
        return _lib_handle
    with _COMPILE_LOCK:
        if _lib_handle is not None:
            return _lib_handle
        if not (
            os.path.isfile(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
        ):
            os.makedirs(_LIB_DIR, exist_ok=True)
            subprocess.run(
                [
                    "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                    _SRC, "-o", _LIB, "-lz",
                ],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_LIB)
        lib.pavro_decode.restype = ctypes.c_void_p
        lib.pavro_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_uint32,
        ]
        lib.pavro_last_error.restype = ctypes.c_char_p
        lib.pavro_nrecords.restype = ctypes.c_int64
        lib.pavro_nrecords.argtypes = [ctypes.c_void_p]
        lib.pavro_col_f64.restype = ctypes.c_int64
        lib.pavro_col_f64.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
        ]
        lib.pavro_col_i32.restype = ctypes.c_int64
        lib.pavro_col_i32.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
        ]
        lib.pavro_bag.restype = ctypes.c_int64
        lib.pavro_bag.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.pavro_strings.restype = ctypes.c_int64
        lib.pavro_strings.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
        ]
        lib.pavro_free.argtypes = [ctypes.c_void_p]
        _lib_handle = lib
        return lib


def available() -> bool:
    try:
        _lib()
        return True
    except Exception:
        return False


# ---------------------------------------------------------------------------
# plan compilation
# ---------------------------------------------------------------------------


def _type_name(schema) -> Optional[str]:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, dict):
        return schema.get("type")
    return None


def _emit_plain(schema, out: List[int]) -> None:
    """Emit ops that DECODE (skip) a value of this schema."""
    t = _type_name(schema)
    if isinstance(schema, list):
        out.append(_OP_UNION)
        out.append(len(schema))
        for branch in schema:
            sub: List[int] = []
            _emit_plain(branch, sub)
            out.append(len(sub))
            out.extend(sub)
        return
    if t in _OPS:
        out.append(_OPS[t])
        return
    if t == "record":
        fields = schema["fields"]
        out.append(_OP_RECORD)
        out.append(len(fields))
        for f in fields:
            _emit_plain(f["type"], out)
        return
    if t == "array":
        sub = []
        _emit_plain(schema["items"], sub)
        out.append(_OP_ARRAY)
        out.append(len(sub))
        out.extend(sub)
        return
    if t == "map":
        sub = []
        _emit_plain(schema["values"], sub)
        out.append(_OP_MAP)
        out.append(len(sub))
        out.extend(sub)
        return
    if t == "enum":
        out.append(_OPS["long"])  # enums encode as int
        return
    if t == "fixed":
        raise PlanError("fixed not supported by native decoder")
    raise PlanError(f"unsupported schema node: {schema!r}")


# Every scalar type is capturable under a string sink: numeric/bool
# branches render as their Python-str form (C++ Sink::render_double /
# %lld / True|False) — the metronome TrainingExample schema types uid as
# [null, string, long, int] and GAME id columns are frequently plain ints.
_STR_CAPTURABLE = {"string", "bytes"} | _NUMERIC


def _is_stringish(schema) -> bool:
    t = _type_name(schema)
    if t in _STR_CAPTURABLE:
        return True
    if isinstance(schema, list):
        return all(
            _type_name(b) == "null" or _type_name(b) in _STR_CAPTURABLE
            for b in schema
        )
    return False


def _is_numeric(schema) -> bool:
    """Capturable under a numeric sink. Union branches beyond the numeric
    ones are tolerated when a numeric branch exists: a string branch
    parses via strtod when it holds a number and reads as NaN-missing
    otherwise (the metronome label union is
    [double,float,int,long,boolean,string])."""
    t = _type_name(schema)
    if t in _NUMERIC:
        return True
    if isinstance(schema, list):
        names = [_type_name(b) for b in schema]
        if not any(n in _NUMERIC for n in names):
            return False
        return all(
            n in _NUMERIC or n in ("null", "string", "bytes") for n in names
        )
    return False


def _bag_item_record(schema):
    """array-of-record (possibly behind [null, array]) -> record schema."""
    if isinstance(schema, list):
        non_null = [b for b in schema if _type_name(b) != "null"]
        if len(non_null) != 1:
            raise PlanError("bag union must be [null, array]")
        schema = non_null[0]
    if _type_name(schema) != "array":
        raise PlanError("bag field is not an array")
    item = schema["items"]
    if _type_name(item) != "record":
        raise PlanError("bag items are not records")
    return schema, item


class Plan:
    """Compiled column plan for one record schema."""

    def __init__(self, schema):
        if _type_name(schema) != "record":
            raise PlanError("top-level schema must be a record")
        self.schema = schema
        self.ops: List[int] = []
        self.num_slots: Dict[str, int] = {}
        self.str_slots: Dict[str, int] = {}
        self.bag_slots: Dict[str, int] = {}
        self.map_keys: List[str] = []
        self._n_num = 0
        self._n_str = 0
        self._n_bag = 0

    def compile(
        self,
        numeric_fields: Sequence[str] = (),
        string_fields: Sequence[str] = (),
        bag_fields: Sequence[str] = (),
        map_field: Optional[str] = None,
        map_keys: Sequence[str] = (),
    ) -> "Plan":
        fields = self.schema["fields"]
        by_name = {f["name"]: f for f in fields}
        for name in list(numeric_fields) + list(string_fields) + list(bag_fields):
            if name not in by_name:
                raise PlanError(f"field {name!r} not in schema")
        if map_field is not None and map_field not in by_name:
            raise PlanError(f"map field {map_field!r} not in schema")
        self.map_keys = list(map_keys)

        out = self.ops
        out.append(_OP_RECORD)
        out.append(len(fields))
        for f in fields:
            name, ftype = f["name"], f["type"]
            if name in numeric_fields:
                if not _is_numeric(ftype):
                    raise PlanError(f"{name!r} is not numeric")
                slot = self._n_num
                self._n_num += 1
                self.num_slots[name] = slot
                out.extend([_CAP_NUM, slot])
                _emit_plain(ftype, out)
            elif name in string_fields:
                if not _is_stringish(ftype):
                    raise PlanError(f"{name!r} is not a string")
                slot = self._n_str
                self._n_str += 1
                self.str_slots[name] = slot
                out.extend([_CAP_STR, slot])
                _emit_plain(ftype, out)
            elif name in bag_fields:
                arr, item = _bag_item_record(ftype)
                if isinstance(ftype, list):
                    # [null, array]: decode the union head, capture inside
                    non_null_idx = next(
                        i for i, b in enumerate(ftype)
                        if _type_name(b) != "null"
                    )
                    out.append(_OP_UNION)
                    out.append(len(ftype))
                    for i, branch in enumerate(ftype):
                        sub: List[int] = []
                        if i == non_null_idx:
                            self._emit_bag(name, item, sub)
                        else:
                            _emit_plain(branch, sub)
                        out.append(len(sub))
                        out.extend(sub)
                else:
                    self._emit_bag(name, item, out)
            elif name == map_field:
                t = _type_name(ftype)
                inner = ftype
                if isinstance(ftype, list):
                    non_null = [
                        b for b in ftype if _type_name(b) != "null"
                    ]
                    if len(non_null) != 1 or _type_name(non_null[0]) != "map":
                        raise PlanError("map union must be [null, map]")
                    out.append(_OP_UNION)
                    out.append(len(ftype))
                    for branch in ftype:
                        sub = []
                        inner_pos = None
                        if _type_name(branch) == "map":
                            self._emit_map(branch, sub)
                            inner_pos = self._map_out_pos
                        else:
                            _emit_plain(branch, sub)
                        out.append(len(sub))
                        out.extend(sub)
                        if inner_pos is not None:
                            # _emit_map recorded the slot-operand position
                            # relative to `sub`; rebase onto the full stream
                            self._map_out_pos = len(out) - len(sub) + inner_pos
                    continue
                if t != "map":
                    raise PlanError(f"{map_field!r} is not a map")
                self._emit_map(inner, out)
            else:
                _emit_plain(ftype, out)
        return self

    def _emit_bag(self, name: str, item, out: List[int]) -> None:
        slot = self._n_bag
        self._n_bag += 1
        self.bag_slots[name] = slot
        ifields = item["fields"]
        roles = {}
        for i, f in enumerate(ifields):
            if f["name"] == "name":
                roles[i] = 1
            elif f["name"] == "term":
                roles[i] = 2
            elif f["name"] == "value":
                roles[i] = 3
        if 1 not in roles.values() or 3 not in roles.values():
            raise PlanError(f"bag {name!r} items lack name/value fields")
        if 2 in roles.values():
            name_i = next(i for i, r in roles.items() if r == 1)
            term_i = next(i for i, r in roles.items() if r == 2)
            if term_i < name_i:
                raise PlanError("term field precedes name field")
        out.extend([_CAP_BAG, slot, len(ifields)])
        for i, f in enumerate(ifields):
            role = roles.get(i, 0)
            if role in (1, 2) and not _is_stringish(f["type"]):
                raise PlanError("bag name/term must be strings")
            if role == 3 and not _is_numeric(f["type"]):
                raise PlanError("bag value must be numeric")
            sub: List[int] = []
            _emit_plain(f["type"], sub)
            out.append(role)
            out.append(len(sub))
            out.extend(sub)

    def _emit_map(self, schema, out: List[int]) -> None:
        if not _is_stringish(schema["values"]):
            raise PlanError("metadata map values must be scalar")
        sub: List[int] = []
        _emit_plain(schema["values"], sub)
        # map ids land in i32 slots AFTER the named string slots; the
        # final slot base is fixed in finalize()
        self._map_out_pos = len(out) + 1  # position of slot_base operand
        out.extend([_CAP_MAP, 0, len(sub)])
        out.extend(sub)

    def finalize(self) -> np.ndarray:
        if self.map_keys and hasattr(self, "_map_out_pos"):
            self.ops[self._map_out_pos] = self._n_str
        return np.asarray(self.ops, dtype=np.uint32)

    def map_slot(self, key: str) -> int:
        return self._n_str + self.map_keys.index(key)


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------


class DecodedColumns:
    """One file's requested columns + the interned string table."""

    def __init__(self, handle, lib, plan: Plan):
        self._h = handle
        self._lib = lib
        self.plan = plan
        self.num_records = int(lib.pavro_nrecords(handle))
        blob_p = ctypes.c_char_p()
        off_p = ctypes.POINTER(ctypes.c_uint64)()
        n = lib.pavro_strings(handle, ctypes.byref(blob_p), ctypes.byref(off_p))
        offs = np.ctypeslib.as_array(off_p, shape=(n + 1,)).copy() if n else np.zeros(1, np.uint64)
        blob = ctypes.string_at(blob_p, int(offs[-1])) if n else b""
        self.strings: List[str] = [
            blob[int(offs[i]):int(offs[i + 1])].decode("utf-8")
            for i in range(n)
        ]

    def f64(self, field: str) -> np.ndarray:
        slot = self.plan.num_slots[field]
        p = ctypes.POINTER(ctypes.c_double)()
        n = self._lib.pavro_col_f64(self._h, slot, ctypes.byref(p))
        return np.ctypeslib.as_array(p, shape=(n,)).copy() if n > 0 else np.zeros(0)

    def str_ids(self, field: str) -> np.ndarray:
        slot = self.plan.str_slots[field]
        return self._i32(slot)

    def map_ids(self, key: str) -> np.ndarray:
        return self._i32(self.plan.map_slot(key))

    def _i32(self, slot: int) -> np.ndarray:
        p = ctypes.POINTER(ctypes.c_int32)()
        n = self._lib.pavro_col_i32(self._h, slot, ctypes.byref(p))
        return (
            np.ctypeslib.as_array(p, shape=(n,)).copy()
            if n > 0
            else np.zeros(0, np.int32)
        )

    def bag(self, field: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """-> (row_ptr [n+1], key_ids [nnz], values [nnz])."""
        slot = self.plan.bag_slots[field]
        rp = ctypes.POINTER(ctypes.c_int64)()
        ki = ctypes.POINTER(ctypes.c_int32)()
        vs = ctypes.POINTER(ctypes.c_double)()
        nnz = ctypes.c_int64()
        n = self._lib.pavro_bag(
            self._h, slot, ctypes.byref(rp), ctypes.byref(ki),
            ctypes.byref(vs), ctypes.byref(nnz),
        )
        row_ptr = (
            np.ctypeslib.as_array(rp, shape=(n,)).copy()
            if n > 0
            else np.zeros(1, np.int64)
        )
        k = int(nnz.value)
        key_ids = (
            np.ctypeslib.as_array(ki, shape=(k,)).copy()
            if k
            else np.zeros(0, np.int32)
        )
        values = (
            np.ctypeslib.as_array(vs, shape=(k,)).copy()
            if k
            else np.zeros(0)
        )
        return row_ptr, key_ids, values

    def close(self) -> None:
        if self._h:
            self._lib.pavro_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def decode_columns(path: str, plan: Plan) -> DecodedColumns:
    """Decode one container file according to a compiled plan."""
    lib = _lib()
    with open(path, "rb") as f:
        data = f.read()
    ops = plan.finalize()
    keys = (ctypes.c_char_p * len(plan.map_keys))(
        *[k.encode("utf-8") for k in plan.map_keys]
    )
    h = lib.pavro_decode(
        data,
        len(data),
        ops.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        len(ops),
        keys,
        len(plan.map_keys),
    )
    if not h:
        raise ValueError(
            f"{path}: {lib.pavro_last_error().decode('utf-8', 'replace')}"
        )
    return DecodedColumns(h, lib, plan)


def plan_for_file(
    path: str,
    *,
    numeric_fields: Sequence[str] = (),
    string_fields: Sequence[str] = (),
    bag_fields: Sequence[str] = (),
    map_field: Optional[str] = None,
    map_keys: Sequence[str] = (),
) -> Plan:
    """Read a file's schema (header only via the Python codec) and compile
    a plan; raises PlanError when the shape is unsupported."""
    schema, _ = read_container(path)
    return Plan(schema).compile(
        numeric_fields=numeric_fields,
        string_fields=string_fields,
        bag_fields=bag_fields,
        map_field=map_field,
        map_keys=map_keys,
    )
