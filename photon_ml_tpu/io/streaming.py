"""Streaming (>host-RAM) GLM input: chunked Avro decode into fixed-shape
device batches.

Reference: the reference streams Avro partitions lazily into RDD rows
(io/GLMSuite.scala:98-131) and relies on Spark's MEMORY_AND_DISK persist —
datasets larger than aggregate executor memory re-read from disk on every
pass. The one-host analog here: every optimizer evaluation streams the
input files through a FIXED-shape staging batch (one XLA compilation,
reused for every chunk of every evaluation), so peak host memory is
bounded by one decoded file + one staged chunk regardless of dataset
size. Multi-host runs split files per process with
``parallel.multihost.process_shard`` before constructing the stream.

Full-batch semantics are exact: chunk partials of (value, gradient) are
accumulated on device, so streaming L-BFGS walks the same iterate
sequence as the in-memory path (fp32 accumulation-order noise aside).
The cost model matches Spark's spilled-cache mode: one disk pass per
objective evaluation (including line-search trials).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.data.batch import SparseBatch
from photon_ml_tpu.utils.index_map import IndexMap


@dataclass(frozen=True)
class StreamStats:
    """One-pass scan results needed to fix the staging-batch shape."""

    num_rows: int
    max_nnz: int  # per-row nonzeros INCLUDING the intercept slot


def scan_stream(
    paths, fmt, *, index_map: Optional[IndexMap] = None
) -> Tuple[IndexMap, StreamStats]:
    """One bounded-memory pass collecting the vocabulary, the row count,
    and the max per-row nnz (incl. intercept) that fix the staging batch
    — dispatched to the input format's streaming protocol
    (``fmt.stream_scan``): Avro scans one decoded file at a time, LibSVM
    one text line at a time. With a prebuilt ``index_map`` (the
    FeatureIndexingJob store — required for multi-host streaming, where
    no single process sees the whole vocabulary) the key collection is
    skipped and only the shape stats are scanned."""
    return fmt.stream_scan(paths, index_map=index_map)


def scan_stream_with_summary(paths, fmt, *, index_map=None):
    """Fused scan: ONE pass collecting the vocabulary, the shape stats AND
    the colStats feature summary — formats without the fused hook (LibSVM)
    fall back to the classic two passes (scan, then streamed summary).
    Returns ``(index_map, StreamStats, summary)``; single-process only
    (the multi-host driver path shards files and all-reduces moments
    through :func:`streaming_summary` instead)."""
    fused = getattr(fmt, "stream_scan_with_summary", None)
    if fused is not None:
        return fused(paths, index_map=index_map)
    index_map, stats = scan_stream(paths, fmt, index_map=index_map)
    summary, _ = streaming_summary(paths, fmt, index_map, stats)
    return index_map, stats, summary


def _file_rows(fmt, path, index_map: IndexMap):
    """One file's decoded row stream behind the ``chunk_read`` seam: the
    whole-file decode is the retryable unit (re-decoding a file is
    idempotent). Formats with the split decode hook (Avro) retry the
    actual column decode; line-at-a-time formats (LibSVM) only cover
    stream construction — their per-line reads are not restartable
    mid-file, so a mid-stream error propagates (and the seam accounting
    still names the file)."""
    from photon_ml_tpu.reliability.retry import io_call

    decode = getattr(fmt, "decode_payload", None)
    rows_from = getattr(fmt, "stream_rows_from_payload", None)
    if decode is not None and rows_from is not None:
        payload = io_call("chunk_read", decode, path, detail=path)
        return rows_from(payload, path, index_map)
    return io_call(
        "chunk_read", fmt.stream_rows, path, index_map, detail=path
    )


def _pipelined_file_rows(files, fmt, index_map: IndexMap):
    """reader->decode stage of the populate pipeline: a worker thread
    decodes file i+1 (``fmt.decode_payload`` — the expensive whole-file
    native column decode) while the caller stages file i's rows. Bounded
    double-buffering: at most one decoded payload queued + one being
    staged + one in flight on the worker. Formats without the split
    decode hook (LibSVM is line-at-a-time) fall back to the serial
    ``stream_rows``. Decodes run behind the ``chunk_read`` seam on the
    worker thread — an injected/transient decode fault retries THERE,
    invisible to the consumer."""
    from photon_ml_tpu.reliability.retry import io_call

    decode = getattr(fmt, "decode_payload", None)
    rows_from = getattr(fmt, "stream_rows_from_payload", None)
    if decode is None or rows_from is None:
        for path in files:
            yield from _file_rows(fmt, path, index_map)
        return

    def decoded():
        for path in files:
            yield path, io_call("chunk_read", decode, path, detail=path)

    for path, payload in _prefetched(decoded(), depth=1):
        yield from rows_from(payload, path, index_map)


def iter_chunks(
    paths,
    fmt,
    index_map: IndexMap,
    *,
    rows_per_chunk: int,
    nnz_width: int,
    pipeline: Optional[bool] = None,
) -> Iterator[SparseBatch]:
    """Stream fixed-shape [rows_per_chunk, nnz_width] SparseBatch chunks
    (weight-0 padding rows in the final chunk). Every chunk has the SAME
    shape, so one jitted partial-objective serves the whole stream.

    ``pipeline``: decode-ahead the NEXT file on a worker thread while
    this thread stages the current one (reader->decode->stage overlap,
    parallel/overlap.py); None follows the global overlap setting AND
    requires a multi-core host — on one core the extra thread cannot
    overlap anything and its switching overhead measurably loses (A/B
    in PERF_NOTES round 6), while the existing chunk-level prefetch
    already recovers the recoverable idle. The serial path is
    row-for-row identical."""
    import os

    import jax.numpy as jnp

    if pipeline is None:
        from photon_ml_tpu.parallel.overlap import overlap_enabled

        pipeline = overlap_enabled() and (os.cpu_count() or 1) > 1
    # a multi-host process can own a ZERO-file shard (process_shard with
    # more processes than files) — it must yield no chunks and still join
    # every collective, not raise
    files = fmt.stream_files(paths) if paths else []
    R, W = rows_per_chunk, nnz_width
    ix_buf = np.zeros((R, W), np.int32)
    v_buf = np.zeros((R, W), np.float32)
    lab_buf = np.zeros((R,), np.float32)
    off_buf = np.zeros((R,), np.float32)
    wgt_buf = np.zeros((R,), np.float32)
    fill = 0

    def emit():
        # COPIES are load-bearing: jnp.asarray on the CPU backend can
        # alias numpy memory zero-copy and dispatch is async, so handing
        # out a view of the reused staging buffers would let the next
        # chunk's refill race the consumer's read of this one.
        return SparseBatch(
            indices=jnp.asarray(ix_buf.copy()),
            values=jnp.asarray(v_buf.copy()),
            labels=jnp.asarray(lab_buf.copy()),
            offsets=jnp.asarray(off_buf.copy()),
            weights=jnp.asarray(wgt_buf.copy()),
        )

    rows = (
        _pipelined_file_rows(files, fmt, index_map)
        if pipeline
        else (
            row
            for path in files
            for row in _file_rows(fmt, path, index_map)
        )
    )
    for ix, vs, lab, off, wgt in rows:
        if len(ix) > W:
            raise ValueError(
                f"row has {len(ix)} nonzeros > staging width {W}; "
                "re-scan the stream or raise nnz_width"
            )
        ix_buf[fill, : len(ix)] = ix
        ix_buf[fill, len(ix):] = 0
        v_buf[fill, : len(vs)] = vs
        v_buf[fill, len(vs):] = 0.0
        lab_buf[fill] = lab
        off_buf[fill] = off
        wgt_buf[fill] = wgt
        fill += 1
        if fill == R:
            yield emit()
            fill = 0
    if fill:
        ix_buf[fill:] = 0
        v_buf[fill:] = 0.0
        lab_buf[fill:] = 0.0
        off_buf[fill:] = 0.0
        wgt_buf[fill:] = 0.0  # weight-0 rows are inert in every objective
        yield emit()


def _prefetched(source: Iterator, depth: int = 2) -> Iterator:
    """Decode-ahead: a worker thread keeps up to ``depth`` staged chunks
    queued while the consumer's device compute runs — the IO/compute
    overlap Spark gets from its task pipeline. (On a single-core host the
    thread adds nothing; on real multi-core hosts decode hides behind the
    objective evaluation.)

    Abandoning the generator (consumer raises mid-pass) cancels the
    worker: its puts poll a stop flag, so no thread or open decode leaks
    across failed evaluations."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    sentinel = object()
    stop = threading.Event()
    errors: List[BaseException] = []

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            # decode_ahead seam: accounts the worker-thread handoff (and
            # gives chaos plans a handle on the thread itself). The
            # retryable IO underneath it is covered by the chunk_read /
            # spill_read seams the source generator crosses.
            from photon_ml_tpu.reliability.faults import inject

            inject("decode_ahead")
            for item in source:
                if not _put(item):
                    return
        except BaseException as e:  # re-raised on the consumer side
            errors.append(e)
        finally:
            _put(sentinel)

    threading.Thread(target=worker, daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is sentinel:
                if errors:
                    raise errors[0]
                return
            yield item
    finally:
        stop.set()


def shard_stream_files(paths, fmt):
    """Cross-process-consistent shard of the format's input files: the
    GLOBAL sort (inside ``fmt.stream_files``) before the round-robin
    split is load-bearing — every host must agree on the file order or
    the shards overlap. One definition shared by the streaming trainer,
    the driver's summary/validation passes, and tests."""
    from photon_ml_tpu.parallel.multihost import process_shard

    return process_shard(fmt.stream_files(paths))


def shard_avro_files(paths):
    """Back-compat alias: shard the default Avro format's files."""
    from photon_ml_tpu.io.input_format import AvroInputDataFormat

    return shard_stream_files(paths, AvroInputDataFormat())


_MOMENTS_JIT = None


def _sparse_moments_jit():
    """Module-level jitted sparse-moments wrapper (dim static): ONE
    compile cache shared across every streaming_summary call, instead of
    a fresh jit(lambda) — and a fresh XLA compilation — per scan."""
    global _MOMENTS_JIT
    if _MOMENTS_JIT is None:
        import jax

        from photon_ml_tpu.data.stats import sparse_moments

        _MOMENTS_JIT = jax.jit(sparse_moments, static_argnums=(1,))
    return _MOMENTS_JIT


def streaming_summary(
    paths,
    fmt,
    index_map: IndexMap,
    stats: StreamStats,
    *,
    rows_per_chunk: int = 65536,
    reservoir_rows: int = 0,
    seed: int = 0,
):
    """One bounded-memory pass computing the FEATURE SUMMARY over a >RAM
    stream (the colStats/summarization stage, BasicStatistics.scala:42 —
    every reference driver stage is a pass over an RDD; this is that pass
    over chunks), plus an optional uniform RESERVOIR SAMPLE of rows
    returned as an in-memory SparseBatch (algorithm R over the stream) —
    the bounded-memory stand-in for diagnostics stages that genuinely
    need row-level resampling (bootstrap).

    Returns ``(summary, sample_batch_or_None)``. Multi-host: moments
    reduce across processes; the reservoir stays process-local (used only
    by the coordinator's diagnostics) — i.e. it is drawn from the
    coordinator's 1/P round-robin file shard, not the full set. The
    round-robin split interleaves date/source-partitioned files, which
    keeps the sample roughly representative; exact global sampling would
    need a cross-host exchange that diagnostics do not warrant.
    """
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.stats import finalize_summary
    from photon_ml_tpu.parallel import overlap

    dim = index_map.size
    jitted_moments = _sparse_moments_jit()

    def moments_fn(b):
        return jitted_moments(b, dim)
    acc = None
    K = int(reservoir_rows)
    rng = np.random.default_rng(seed)
    W = stats.max_nnz
    res = (
        {
            "ix": np.zeros((K, W), np.int32),
            "v": np.zeros((K, W), np.float32),
            "lab": np.zeros(K, np.float32),
            "off": np.zeros(K, np.float32),
            "wgt": np.zeros(K, np.float32),
        }
        if K
        else None
    )
    seen = 0
    for chunk in iter_chunks(
        paths, fmt, index_map, rows_per_chunk=rows_per_chunk, nnz_width=W
    ):
        m = moments_fn(chunk)
        if acc is None:
            acc = list(m)
        else:
            for i in range(5):  # n, s1, s2, l1, nnz are sums
                acc[i] = acc[i] + m[i]
            acc[5] = jnp.maximum(acc[5], m[5])
            acc[6] = jnp.minimum(acc[6], m[6])
        if res is not None:
            wgt = np.asarray(chunk.weights)
            real = np.nonzero(wgt > 0)[0]
            m = len(real)
            if m:
                # vectorized algorithm R (exact): per-row independent
                # acceptance draws + random slots; numpy fancy assignment
                # applies duplicates in order, so the LAST accepted row
                # wins a contested slot — identical to the sequential
                # algorithm. One rng call per chunk, not per row.
                t = seen + 1 + np.arange(m)  # global 1-based row ranks
                fill_mask = t <= K
                slots = np.where(fill_mask, t - 1, 0)
                u = rng.random(m)
                accept = fill_mask | (u < K / t)
                rand_slots = rng.integers(0, K, size=m)
                slots = np.where(fill_mask, slots, rand_slots)
                sel = real[accept]
                dst = slots[accept]
                res["ix"][dst] = np.asarray(chunk.indices)[sel]
                res["v"][dst] = np.asarray(chunk.values)[sel]
                res["lab"][dst] = np.asarray(chunk.labels)[sel]
                res["off"][dst] = np.asarray(chunk.offsets)[sel]
                res["wgt"][dst] = wgt[sel]
                seen += m
    if acc is None:
        if jax.process_count() <= 1:
            raise ValueError(f"no rows found under {paths!r}")
        # a process can own ZERO file shards when processes outnumber
        # files — it still joins the cross-host reduction with inert
        # moments
        big = jnp.float32(jnp.inf)
        acc = [
            jnp.float32(0.0),
            jnp.zeros((dim,), jnp.float32),
            jnp.zeros((dim,), jnp.float32),
            jnp.zeros((dim,), jnp.float32),
            jnp.zeros((dim,), jnp.float32),
            jnp.full((dim,), -big),
            jnp.full((dim,), big),
        ]
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        for i in range(5):
            acc[i] = jnp.asarray(
                multihost_utils.process_allgather(acc[i]).sum(axis=0)
            )
        acc[5] = jnp.asarray(
            multihost_utils.process_allgather(acc[5]).max(axis=0)
        )
        acc[6] = jnp.asarray(
            multihost_utils.process_allgather(acc[6]).min(axis=0)
        )
        if int(overlap.device_get(acc[0])) == 0:
            # same contract as single-process: .avro files that exist but
            # hold zero rows must not produce a benign-looking summary
            # (mean 0 / variance 1) and train garbage normalization
            raise ValueError(f"no rows found under {paths!r} on any host")
    summary = finalize_summary(*acc)
    sample = None
    if res is not None:
        k_eff = min(seen, K)
        sample = SparseBatch(
            indices=jnp.asarray(res["ix"][:k_eff]),
            values=jnp.asarray(res["v"][:k_eff]),
            labels=jnp.asarray(res["lab"][:k_eff]),
            offsets=jnp.asarray(res["off"][:k_eff]),
            weights=jnp.asarray(res["wgt"][:k_eff]),
        )
    return summary, sample


# Live spill scratch directories, swept at interpreter exit. __del__ alone
# is not a cleanup contract: a driver exception that keeps the objective
# alive in a traceback, or an exit while generators still hold frames,
# skips finalizers and leaks multi-GB scratch. Every spill dir registers
# here at creation and unregisters on close(); the atexit sweep removes
# whatever is left. SIGTERM is covered when the process shuts down through
# the normal exit path (the preemption guard's iteration-boundary stop);
# a hard kill cannot run ANY handler — PHOTON_SPILL_DIR + an external
# scratch sweeper remain the belt-and-braces for that.
_LIVE_SPILL_DIRS: set = set()


def _sweep_spill_dirs() -> None:
    import shutil

    for d in list(_LIVE_SPILL_DIRS):
        _LIVE_SPILL_DIRS.discard(d)
        shutil.rmtree(d, ignore_errors=True)


def register_spill_dir(path: str) -> None:
    """Track a scratch directory for the atexit sweep (shared by every
    disk-spill store: GLM chunk cache, GAME chunk/score/bucket stores)."""
    import atexit

    if not _LIVE_SPILL_DIRS:
        atexit.register(_sweep_spill_dirs)
    _LIVE_SPILL_DIRS.add(path)


def unregister_spill_dir(path: str) -> None:
    _LIVE_SPILL_DIRS.discard(path)


def make_spill_dir(prefix: str, spill_dir: Optional[str] = None) -> str:
    """Create + register a scratch directory. On hosts with a tmpfs /tmp
    the default scratch is RAM-backed — point spill_dir (or
    PHOTON_SPILL_DIR) at real disk for genuinely >RAM datasets."""
    import os
    import tempfile

    base = spill_dir or os.environ.get("PHOTON_SPILL_DIR")
    path = tempfile.mkdtemp(prefix=prefix, dir=base)
    register_spill_dir(path)
    return path


def stream_budget_rows(
    budget_bytes: int, bytes_per_row: int, *, default_rows: int = 65536,
    min_rows: int = 8,
) -> int:
    """Rows-per-chunk under an explicit host-memory byte budget
    (--stream-memory-budget): the staging chunk is the unit every
    streaming stage holds resident, so its row count is budget // row
    bytes, floored at ``min_rows`` so degenerate budgets still make
    progress (the contract is then 'one minimal chunk'). budget <= 0
    keeps the historical default chunk sizing."""
    if budget_bytes is None or budget_bytes <= 0:
        return default_rows
    return max(min_rows, budget_bytes // max(1, bytes_per_row))


def sparse_row_bytes(nnz_width: int) -> int:
    """Staged bytes per row of one sparse chunk: int32 index + float32
    value per slot, plus label/offset/weight."""
    return max(1, nnz_width) * 8 + 12


def budgeted_rows(max_rows: int, budget_bytes: int, bytes_per_row: int) -> int:
    """Row count of a bounded in-memory sample (diagnostics reservoirs)
    under a byte budget: wide rows scale the count DOWN instead of
    allocating multiple GB on the host — the streaming paths' bounded-
    memory contract (ADVICE.md round 5). Shared by the GLM driver's
    reservoir (sparse_row_bytes rows) and the GAME driver's
    (game.streaming.game_row_bytes rows)."""
    return max(1, min(max_rows, budget_bytes // max(1, bytes_per_row)))


class _DiskChunkStore:
    """Fixed-shape staged chunks spilled to a local scratch directory —
    the disk half of Spark's persist(MEMORY_AND_DISK)
    (constants/StorageLevel.scala): evaluation 2..N re-reads the staged
    raw arrays (one sequential memmap pass) instead of re-decoding Avro."""

    _FIELDS = ("ix", "v", "lab", "off", "wgt")

    def __init__(
        self, rows_per_chunk: int, nnz_width: int,
        spill_dir: Optional[str] = None,
    ):
        import os

        self.R, self.W = rows_per_chunk, nnz_width
        self.dir = make_spill_dir("photon-stream-spill-", spill_dir)
        self.count = 0
        self._writers = {
            f: open(os.path.join(self.dir, f + ".bin"), "wb")
            for f in self._FIELDS
        }

    def append(self, batch: SparseBatch) -> None:
        from photon_ml_tpu.reliability.retry import io_call

        arrays = {
            "ix": np.asarray(batch.indices, np.int32),
            "v": np.asarray(batch.values, np.float32),
            "lab": np.asarray(batch.labels, np.float32),
            "off": np.asarray(batch.offsets, np.float32),
            "wgt": np.asarray(batch.weights, np.float32),
        }
        for f, a in arrays.items():
            data = a.tobytes()
            w = self._writers[f]
            # seek to the chunk's fixed offset per attempt: a retry after
            # a partial write overwrites in place instead of appending
            # garbage (every chunk field has a fixed record size)
            off = self.count * len(data)

            def _write(w=w, data=data, off=off):
                w.seek(off)
                w.write(data)

            io_call(
                "spill_write", _write,
                detail=f"{self.dir}/{f}.bin[{self.count}]",
            )
        self.count += 1

    def finalize(self) -> None:
        for f in self._writers.values():
            f.close()

    def chunks(self) -> Iterator[SparseBatch]:
        import os

        import jax.numpy as jnp

        R, W, n = self.R, self.W, self.count
        mm = {
            "ix": np.memmap(
                os.path.join(self.dir, "ix.bin"), np.int32, "r", shape=(n, R, W)
            ),
            "v": np.memmap(
                os.path.join(self.dir, "v.bin"), np.float32, "r", shape=(n, R, W)
            ),
            "lab": np.memmap(
                os.path.join(self.dir, "lab.bin"), np.float32, "r", shape=(n, R)
            ),
            "off": np.memmap(
                os.path.join(self.dir, "off.bin"), np.float32, "r", shape=(n, R)
            ),
            "wgt": np.memmap(
                os.path.join(self.dir, "wgt.bin"), np.float32, "r", shape=(n, R)
            ),
        }
        from photon_ml_tpu.reliability.retry import io_call

        for i in range(n):
            # spill_read seam: materializing one chunk from the memmaps
            # is idempotent, so transient read errors retry in place
            arrs = io_call(
                "spill_read",
                lambda i=i: {f: np.array(mm[f][i]) for f in self._FIELDS},
                detail=f"{self.dir}[{i}]",
            )
            yield SparseBatch(
                indices=jnp.asarray(arrs["ix"]),
                values=jnp.asarray(arrs["v"]),
                labels=jnp.asarray(arrs["lab"]),
                offsets=jnp.asarray(arrs["off"]),
                weights=jnp.asarray(arrs["wgt"]),
            )

    def close(self) -> None:
        import shutil

        self.finalize()
        unregister_spill_dir(self.dir)
        shutil.rmtree(self.dir, ignore_errors=True)

    def __del__(self):  # scratch must not outlive the objective
        try:
            self.close()
        except Exception:
            pass


# -- shared tiled-chunk fold programs ----------------------------------------
#
# The tiled cached path folds every chunk inside ONE jitted lax.scan over
# the chunk-stacked TiledSparseBatch. Module-level (objective passed as a
# pytree argument) so every StreamingGLMObjective instance with the same
# chunk structure shares one persistent compile cache — these replace the
# per-instance constructor jit(lambda)s of PERF_NOTES round 9.

_TILED_FOLDS = {}


def _tiled_fold_jit(which: str):
    global _TILED_FOLDS
    if which in _TILED_FOLDS:
        return _TILED_FOLDS[which]
    import jax
    import jax.numpy as jnp

    def _scan(stacked, fold):
        def body(carry, tb):
            return jax.tree.map(jnp.add, carry, fold(tb)), None

        init = jax.tree.map(
            jnp.zeros_like,
            jax.eval_shape(fold, jax.tree.map(lambda x: x[0], stacked)),
        )
        return jax.lax.scan(body, init, stacked)[0]

    if which == "vg":

        @jax.jit
        def fn(objective, w, stacked):
            return _scan(
                stacked, lambda tb: objective.value_and_gradient(w, tb, 0.0)
            )
    elif which == "hv":

        @jax.jit
        def fn(objective, w, d, stacked):
            return _scan(
                stacked, lambda tb: objective.hessian_vector(w, d, tb, 0.0)
            )
    else:

        @jax.jit
        def fn(objective, w, stacked):
            return _scan(
                stacked, lambda tb: objective.hessian_diagonal(w, tb, 0.0)
            )

    _TILED_FOLDS[which] = fn
    return fn


class StreamingGLMObjective:
    """GLMObjective facade whose (value, gradient) stream the input from
    disk per evaluation — full-batch semantics with bounded memory.

    The per-chunk partial (l2 = 0) is one fixed-shape jitted program;
    the L2 term is added once at the end. Feed this to the host-driven
    L-BFGS/OWL-QN (optim.host_lbfgs) — the in-jit while_loop optimizers
    cannot trace through disk IO.

    persist(MEMORY_AND_DISK) semantics (GLMSuite.scala:98-131 +
    StorageLevel.scala): the FIRST evaluation populates a cache of the
    staged fixed-shape chunks — device-resident up to ``cache_bytes``,
    the remainder spilled as raw arrays to local scratch — so evaluation
    2..N never re-decodes Avro. ``cache_bytes=0`` disables caching (one
    decode pass per evaluation, the round-3 behavior); ``prefetch``
    decode-aheads one chunk on a worker thread.

    FAST-KERNEL CACHED PATH (``kernel="auto"|"tiled"`` on TPU): staged
    chunks have FIXED structure after the populate pass — exactly what
    the tiled Pallas kernels' static schedules need — so once the cache
    exists, per-chunk tile schedules are built ONCE (padded to one common
    shape so a single compiled program serves every chunk) and evaluation
    2..N dispatches the gather/scatter-free bilinear kernels
    asynchronously chunk after chunk, accumulating on device. The
    reference pays no kernel penalty for persisted-on-disk data
    (GLMSuite.scala:98-131 + ValueAndGradientAggregator.scala:235-250);
    after this, neither do we. Tiled chunks are device-resident up to
    ``tiled_cache_bytes``; chunks past the budget stay on the scatter
    partial.
    """

    def __init__(
        self,
        paths,
        fmt,
        index_map: IndexMap,
        stats: StreamStats,
        task,
        *,
        rows_per_chunk: int = 65536,
        cache_bytes: int = 2 << 30,
        prefetch: bool = True,
        spill_dir: Optional[str] = None,
        kernel: str = "auto",
        tiled_cache_bytes: int = 4 << 30,
        tile_params=None,
        norm=None,
        tile_cache_dir: Optional[str] = None,
    ):
        from photon_ml_tpu.ops.losses import loss_for_task
        from photon_ml_tpu.ops.objective import GLMObjective

        self.paths = paths
        self.fmt = fmt
        self.index_map = index_map
        self.stats = stats
        self.rows_per_chunk = int(min(rows_per_chunk, max(stats.num_rows, 8)))
        self.nnz_width = stats.max_nnz
        self.dim = index_map.size
        self.cache_bytes = int(cache_bytes)
        self.prefetch = prefetch
        self.spill_dir = spill_dir
        self._mem_cache: List[SparseBatch] = []
        self._disk_cache: Optional[_DiskChunkStore] = None
        self._cached = False
        from photon_ml_tpu.ops.normalization import identity_context

        self._loss = loss_for_task(task)
        self.norm = norm if norm is not None else identity_context()
        # per-chunk partials run the SHARED module-level jits
        # (ops.objective.partial_value_and_gradient and friends): the
        # objective is a pytree argument, so every instance with the
        # same structure/chunk shape hits one persistent compile cache.
        self._objective = GLMObjective(self._loss, self.dim, self.norm)
        if kernel not in ("auto", "tiled", "scatter"):
            raise ValueError(f"unknown kernel {kernel!r}")
        from photon_ml_tpu.utils.backend import effective_platform

        self._use_tiled = kernel == "tiled" or (
            kernel == "auto" and effective_platform() == "tpu"
        )
        self.tiled_cache_bytes = int(tiled_cache_bytes)
        self.tile_params = tile_params
        # persistent schedule-cache dir for the per-chunk tiled builds
        # (ops/schedule_cache.py); None falls back to the process config /
        # PHOTON_TILE_CACHE_DIR. Staged chunks have fixed content after
        # the populate pass, so a rerun over the same files hits the
        # content-addressed artifacts chunk by chunk.
        self.tile_cache_dir = tile_cache_dir
        self._tiled_chunk_count: Optional[int] = None
        self._tiled_stacked = None  # chunk-stacked TiledSparseBatch pytree
        self._tiled_objective = None

    # -- tiled cached path --------------------------------------------------

    def _build_tiled_chunks(self) -> None:
        """Convert cached staged chunks to tiled batches, once.

        Every chunk shares the staging shape [R, W], so all schedules are
        padded to ONE static (steps, spill) shape — a single compiled
        tiled program then serves the whole stream with no per-chunk
        recompilation. Build cost is one pass of the native counting-sort
        builder per chunk (threaded; structure is fixed for the rest of
        training, the persisted-RDD analog)."""
        from concurrent.futures import ThreadPoolExecutor


        from photon_ml_tpu.ops import tiled_sparse as ts

        params0 = self.tile_params or ts.TileParams()
        win = params0.window
        R = self.rows_per_chunk
        r_pad = max(((R + win - 1) // win) * win, win)
        d_pad = max(((self.dim + win - 1) // win) * win, win)
        z_blocks, g_blocks = r_pad // win, d_pad // win

        # ONE chunk at a time — the COO staging of a chunk is dropped
        # before the next decodes, so host memory holds at most the KEPT
        # schedules (bounded by tiled_cache_bytes) + one in-flight chunk;
        # the >RAM streaming contract survives the fast-kernel upgrade.
        params = None
        built = []  # (z, g, lab, off, wgt) for kept chunks only
        budget = self.tiled_cache_bytes
        from photon_ml_tpu.ops.schedule_cache import cache_scope

        with cache_scope(self.tile_cache_dir), ThreadPoolExecutor(2) as pool:
            for batch in self.chunks():
                rows, feats, vals, _n = ts._sparse_coo(batch)
                if params is None:
                    # chunks share the staging shape; the first chunk's
                    # occupancy fixes the grid-step width for all
                    # (resolved() divides by the tile count itself)
                    params = params0.resolved(
                        len(vals), z_blocks * g_blocks
                    )
                fz = pool.submit(
                    ts._build_schedule_np, rows, feats, vals,
                    params=params, sort_by_feature_block=False,
                    num_out_blocks=z_blocks,
                )
                g = ts._build_schedule_np(
                    rows, feats, vals, params=params,
                    sort_by_feature_block=True, num_out_blocks=g_blocks,
                )
                z = fz.result()
                del rows, feats, vals
                nbytes = (
                    sum(a.nbytes for a in z) + 2 * sum(a.nbytes for a in g)
                )
                if nbytes > budget:
                    # remaining chunks stay on the scatter partial
                    break
                budget -= nbytes
                built.append((
                    z, g,
                    np.asarray(batch.labels),
                    np.asarray(batch.offsets),
                    np.asarray(batch.weights),
                ))
        if not built:
            self._tiled_chunk_count = 0
            return
        # pad every kept schedule to ONE static shape so a single
        # compiled program serves all chunks
        gz = max(b[0][0].shape[0] for b in built)
        gg = max(b[1][0].shape[0] for b in built)
        sz = max(b[0][8].shape[0] for b in built)
        sg = max(b[1][8].shape[0] for b in built)
        meta = ts._TiledMeta(
            params=params, num_rows=r_pad, dim=d_pad,
            num_real_rows=R, real_dim=self.dim,
        )
        import jax.numpy as jnp

        def pad_rows(a):
            out = np.zeros(r_pad, np.float32)
            out[: a.shape[0]] = a
            return out

        # ALL cached chunks evaluate in ONE dispatch: leaves stacked along
        # a leading chunk axis (stacked HOST-side — one device copy, no
        # per-chunk device duplicates) and folded by lax.scan — per-chunk
        # python dispatches cost ~10 ms each over a tunneled chip, which
        # at 16 chunks dwarfed the kernels themselves
        n_chunks = len(built)
        padded = [
            (
                ts._pad_schedule_np(z, gz, z_blocks, sz),
                ts._pad_schedule_np(g, gg, g_blocks, sg),
                lab, off, wgt,
            )
            for z, g, lab, off, wgt in built
        ]
        del built

        def lead(items):
            # ALWAYS stacked with a leading chunk axis (even at 1 chunk)
            # so the shared module-level scan programs below see one
            # uniform structure across instances
            return jnp.asarray(np.stack(list(items)))

        self._tiled_stacked = ts.TiledSparseBatch(
            meta=meta,
            z_sched=ts._Schedule(
                *(lead(p[0][i] for p in padded) for i in range(9))
            ),
            g_sched=ts._Schedule(
                *(lead(p[1][i] for p in padded) for i in range(9))
            ),
            g_vals_sq=lead(p[1][5] ** 2 for p in padded),
            labels=lead(pad_rows(p[2]) for p in padded),
            offsets=lead(pad_rows(p[3]) for p in padded),
            weights=lead(pad_rows(p[4]) for p in padded),
        )
        del padded
        from photon_ml_tpu.utils.backend import effective_platform

        self._tiled_objective = ts.TiledGLMObjective(
            self._loss, self.dim, self.norm,
            interpret=effective_platform() == "cpu",
        )
        self._tiled_chunk_count = n_chunks

    def _ensure_tiled(self) -> bool:
        if not (self._use_tiled and self._cached):
            return False
        if self._tiled_chunk_count is None:
            self._build_tiled_chunks()
        return self._tiled_chunk_count > 0

    def _overflow_chunks(self) -> Iterator[SparseBatch]:
        """Cached chunks past the tiled-cache budget (scatter fallback)."""
        import itertools

        yield from itertools.islice(
            self.chunks(), self._tiled_chunk_count, None
        )

    def _chunk_nbytes(self) -> int:
        return self.rows_per_chunk * (self.nnz_width * 8 + 12)

    def chunks(self) -> Iterator[SparseBatch]:
        if self._cached:
            yield from self._mem_cache
            if self._disk_cache is not None:
                # spill-tier reads get the same IO/compute overlap as the
                # populate pass
                spill = self._disk_cache.chunks()
                yield from (
                    _prefetched(spill) if self.prefetch else spill
                )
            return
        source = iter_chunks(
            self.paths, self.fmt, self.index_map,
            rows_per_chunk=self.rows_per_chunk, nnz_width=self.nnz_width,
        )
        if self.prefetch:
            source = _prefetched(source)
        if self.cache_bytes <= 0:
            yield from source
            return
        budget = max(1, self.cache_bytes // max(1, self._chunk_nbytes()))
        mem: List[SparseBatch] = []
        disk: Optional[_DiskChunkStore] = None
        for batch in source:
            if len(mem) < budget:
                mem.append(batch)
            else:
                if disk is None:
                    disk = _DiskChunkStore(
                        self.rows_per_chunk, self.nnz_width, self.spill_dir
                    )
                disk.append(batch)
            yield batch
        if disk is not None:
            disk.finalize()
        self._mem_cache = mem
        self._disk_cache = disk
        self._cached = True

    def _reduce_hosts(self, vec):
        """Cross-host sum of a streamed partial (the treeAggregate combine
        over DCN); no-op single-process."""
        import jax
        import jax.numpy as jnp

        if jax.process_count() <= 1:
            return vec
        from jax.experimental import multihost_utils

        return jnp.asarray(
            multihost_utils.process_allgather(vec).sum(axis=0), jnp.float32
        )

    def hessian_vector(self, w, direction, l2_weight=0.0):
        """Streamed H(w) @ d: one pass over the cached staged chunks —
        the reference's exact second-order pattern (one cluster aggregate
        per CG step, HessianVectorAggregator.scala:137-152). Rides the
        tiled chunk cache when built."""
        import jax.numpy as jnp

        from photon_ml_tpu.ops.objective import partial_hessian_vector

        hv = jnp.zeros((self.dim,), jnp.float32)
        if self._ensure_tiled():
            hv = hv + _tiled_fold_jit("hv")(
                self._tiled_objective, w, direction, self._tiled_stacked
            )
            chunks = self._overflow_chunks()
        else:
            chunks = self.chunks()
        for batch in chunks:
            hv = hv + partial_hessian_vector(
                self._objective, w, direction, batch
            )
        hv = self._reduce_hosts(hv)
        return hv + l2_weight * direction

    def hessian_diagonal(self, w, l2_weight=0.0):
        """Streamed Hessian diagonal (the variance pass,
        DistributedOptimizationProblem.scala:79-93): one pass over the
        cached staged chunks."""
        import jax.numpy as jnp

        from photon_ml_tpu.ops.objective import partial_hessian_diagonal

        diag = jnp.zeros((self.dim,), jnp.float32)
        if self._ensure_tiled():
            diag = diag + _tiled_fold_jit("hd")(
                self._tiled_objective, w, self._tiled_stacked
            )
            chunks = self._overflow_chunks()
        else:
            chunks = self.chunks()
        for batch in chunks:
            diag = diag + partial_hessian_diagonal(self._objective, w, batch)
        return self._reduce_hosts(diag) + l2_weight

    def value_and_gradient(self, w, l2_weight=0.0):
        import jax
        import jax.numpy as jnp

        from photon_ml_tpu.ops.objective import partial_value_and_gradient

        value = jnp.float32(0.0)
        grad = jnp.zeros((self.dim,), jnp.float32)
        if self._ensure_tiled():
            # cached fast path: EVERY tiled chunk folds inside one
            # jitted lax.scan dispatch (per-chunk dispatches cost ~10 ms
            # each over a tunneled chip)
            v, g = _tiled_fold_jit("vg")(
                self._tiled_objective, w, self._tiled_stacked
            )
            value = value + v
            grad = grad + g
            for batch in self._overflow_chunks():
                v, g = partial_value_and_gradient(self._objective, w, batch)
                value = value + v
                grad = grad + g
        else:
            for batch in self.chunks():
                v, g = partial_value_and_gradient(self._objective, w, batch)
                value = value + v
                grad = grad + g
        if jax.process_count() > 1:
            # cross-host reduction of the loss partials (the treeAggregate
            # combine step over DCN): each process streamed only ITS file
            # shard; the regularization term is added once, after
            from jax.experimental import multihost_utils

            packed = jnp.concatenate([value[None], grad])
            gathered = multihost_utils.process_allgather(packed)
            total = gathered.sum(axis=0)
            value = jnp.float32(total[0])
            grad = jnp.asarray(total[1:], jnp.float32)
        value = value + 0.5 * l2_weight * jnp.vdot(w, w)
        return value, grad + l2_weight * w


class FeatureShardedStreamingObjective:
    """Streaming x feature-sharded composition: the >host-RAM dataset AND
    the >single-chip-HBM coefficient vector at once — the north-star
    combination the round-5 verdict named as the open frontier.

    Rows stream through the SAME staged-chunk pipeline as
    :class:`StreamingGLMObjective` (decode once, fixed-shape chunks,
    mem/disk cache), but every staged chunk is RE-LAID-OUT per feature
    block on the (data, model) mesh (feature_shard_sparse_batch) — the
    per-chunk analog of the reference's hash-partitioned feature
    vocabulary. Each objective evaluation folds one sharded program per
    chunk (value replicated, gradient sharded over "model"); TRON runs
    one streamed Hv pass per CG step, exactly the host_tron driver's
    one-aggregate-per-CG-step pattern.

    Staged chunks have FIXED content after the populate pass, so each
    chunk's sharded layout is built ONCE and kept device-resident up to
    ``sharded_cache_bytes``; chunks past the budget re-shard from the
    staged arrays on every pass (the spilled-cache cost model). On a
    CPU backend "device-resident" is host RAM, so both budgets count
    against the host-memory contract.

    Scope (validated by the driver): single process, no normalization
    (the shift/factor extras are not threaded through the per-chunk
    entry points yet), sparse layout (the tiled per-chunk schedules ride
    the PR-1 cache through StreamingGLMObjective on the unsharded path).
    """

    def __init__(
        self,
        paths,
        fmt,
        index_map: IndexMap,
        stats: StreamStats,
        task,
        mesh,
        *,
        rows_per_chunk: int = 65536,
        cache_bytes: int = 2 << 30,
        sharded_cache_bytes: int = 2 << 30,
        prefetch: bool = True,
        spill_dir: Optional[str] = None,
    ):
        from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        if MODEL_AXIS not in mesh.axis_names or DATA_AXIS not in mesh.axis_names:
            raise ValueError(
                "streaming feature-sharded training needs a (data, model) "
                f"mesh, got axes {mesh.axis_names}"
            )
        self.mesh = mesh
        self.data_shards = int(mesh.shape[DATA_AXIS])
        self.model_shards = int(mesh.shape[MODEL_AXIS])
        self.dim = index_map.size
        self.block_dim = -(-self.dim // self.model_shards)
        self.d_pad = self.model_shards * self.block_dim
        self.sharded_cache_bytes = int(sharded_cache_bytes)
        # staging/cache tier only (kernel="scatter": the sharded programs
        # below do the math; the base's own partials are never dispatched)
        self._base = StreamingGLMObjective(
            paths, fmt, index_map, stats, task,
            rows_per_chunk=rows_per_chunk, cache_bytes=cache_bytes,
            prefetch=prefetch, spill_dir=spill_dir, kernel="scatter",
        )
        from photon_ml_tpu.ops.losses import loss_for_task
        from photon_ml_tpu.ops.objective import GLMObjective
        from photon_ml_tpu.parallel.distributed import (
            feature_sharded_hessian_diagonal,
            feature_sharded_sparse_hessian_vector,
            feature_sharded_sparse_value_and_grad,
        )

        self._objective = GLMObjective(loss_for_task(task), self.dim)
        self._vg = feature_sharded_sparse_value_and_grad(
            self._objective, mesh
        )
        self._hv = feature_sharded_sparse_hessian_vector(
            self._objective, mesh
        )
        self._hd = feature_sharded_hessian_diagonal(
            self._objective, mesh, None, layout="sparse"
        )
        # per-chunk sharded layouts: None until populated; entries are
        # either a FeatureShardedSparseBatch (cached) or None (over
        # budget -> re-shard per pass)
        self._sharded: Optional[List[Optional[object]]] = None

    def _shard_chunk(self, batch):
        from photon_ml_tpu.parallel import overlap
        from photon_ml_tpu.parallel.distributed import (
            feature_shard_sparse_batch,
        )

        # counted seam: the re-staging fetch happens once per chunk per
        # pass (cached under the budget) — route it through the counter
        host = overlap.device_get(batch)
        sharded, block_dim = feature_shard_sparse_batch(
            host, self.dim, self.model_shards,
            rows_multiple=self.data_shards,
        )
        assert block_dim == self.block_dim
        return sharded

    def _sharded_chunks(self):
        """Yield one FeatureShardedSparseBatch per staged chunk; builds
        (and budget-caches) the layouts on the first pass."""
        if self._sharded is None:
            built: List[Optional[object]] = []
            budget = self.sharded_cache_bytes
            for batch in self._base.chunks():
                sb = self._shard_chunk(batch)
                nbytes = sum(
                    np.dtype(a.dtype).itemsize * int(np.prod(a.shape))
                    for a in sb
                )
                if nbytes <= budget:
                    budget -= nbytes
                    built.append(sb)
                else:
                    built.append(None)
                yield sb
            self._sharded = built
            return
        source = None
        for i, sb in enumerate(self._sharded):
            if sb is not None:
                yield sb
                continue
            if source is None:
                # over-budget tail: re-shard from the staged chunk cache
                import itertools

                source = itertools.islice(self._base.chunks(), i, None)
            yield self._shard_chunk(next(source))

    def value_and_gradient(self, w, l2_weight=0.0):
        import jax.numpy as jnp

        value = jnp.float32(0.0)
        grad = jnp.zeros((self.d_pad,), jnp.float32)
        for sb in self._sharded_chunks():
            v, g = self._vg(w, sb, jnp.float32(0.0))
            value = value + v
            grad = grad + g
        value = value + 0.5 * l2_weight * jnp.vdot(w, w)
        return value, grad + l2_weight * w

    def hessian_vector(self, w, direction, l2_weight=0.0):
        import jax.numpy as jnp

        hv = jnp.zeros((self.d_pad,), jnp.float32)
        for sb in self._sharded_chunks():
            hv = hv + self._hv(w, direction, sb, jnp.float32(0.0))
        return hv + l2_weight * direction

    def hessian_diagonal(self, w, l2_weight=0.0):
        import jax.numpy as jnp

        diag = jnp.zeros((self.d_pad,), jnp.float32)
        for sb in self._sharded_chunks():
            diag = diag + self._hd(w, sb, jnp.float32(0.0))
        return diag + l2_weight
