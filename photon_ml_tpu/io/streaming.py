"""Streaming (>host-RAM) GLM input: chunked Avro decode into fixed-shape
device batches.

Reference: the reference streams Avro partitions lazily into RDD rows
(io/GLMSuite.scala:98-131) and relies on Spark's MEMORY_AND_DISK persist —
datasets larger than aggregate executor memory re-read from disk on every
pass. The one-host analog here: every optimizer evaluation streams the
input files through a FIXED-shape staging batch (one XLA compilation,
reused for every chunk of every evaluation), so peak host memory is
bounded by one decoded file + one staged chunk regardless of dataset
size. Multi-host runs split files per process with
``parallel.multihost.process_shard`` before constructing the stream.

Full-batch semantics are exact: chunk partials of (value, gradient) are
accumulated on device, so streaming L-BFGS walks the same iterate
sequence as the in-memory path (fp32 accumulation-order noise aside).
The cost model matches Spark's spilled-cache mode: one disk pass per
objective evaluation (including line-search trials).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.data.batch import SparseBatch
from photon_ml_tpu.utils.index_map import IndexMap, intercept_key


@dataclass(frozen=True)
class StreamStats:
    """One-pass scan results needed to fix the staging-batch shape."""

    num_rows: int
    max_nnz: int  # per-row nonzeros INCLUDING the intercept slot


def _iter_file_rows(path: str, fmt, index_map: IndexMap):
    """Yield (indices, values, label, offset, weight) per record of ONE
    file: native column decode when available (one file resident at a
    time), record-at-a-time Python codec otherwise. The remap semantics
    live in AvroInputDataFormat.iter_rows_from_{decoded,records} — one
    definition shared with the in-memory loader."""
    from photon_ml_tpu.io import native_avro
    from photon_ml_tpu.io.avro_codec import (
        read_avro_records,
        read_container_schema,
    )

    icept = (
        index_map.get_index(intercept_key()) if fmt.add_intercept else -1
    )
    icept = icept if icept >= 0 else None
    decoded = None
    if native_avro.available():
        try:
            schema = read_container_schema(path)
            names = {f["name"] for f in schema.get("fields", [])}
            if "features" in names and fmt.response_field in names:
                numeric = [
                    f
                    for f in (fmt.response_field, "offset", "weight")
                    if f in names
                ]
                plan = native_avro.Plan(schema).compile(
                    numeric_fields=numeric, bag_fields=["features"]
                )
                decoded = native_avro.decode_columns(path, plan)
        except (native_avro.PlanError, ValueError, OSError):
            decoded = None

    if decoded is not None:
        yield from fmt.iter_rows_from_decoded(decoded, index_map, icept)
    else:
        yield from fmt.iter_rows_from_records(
            read_avro_records([path]), index_map, icept
        )


def scan_stream(paths, fmt) -> Tuple[IndexMap, StreamStats]:
    """One streaming pass: build the feature IndexMap and the shape stats
    (row count, max per-row nnz incl. intercept) that fix the staging
    batch. RSS stays bounded by one file."""
    from photon_ml_tpu.io.paths import expand_input_paths

    files = sorted(expand_input_paths(paths, lambda fn: fn.endswith(".avro")))
    if not files:
        raise ValueError(f"no .avro inputs under {paths!r}")
    index_map = fmt.build_index_map(files)
    num_rows = 0
    max_nnz = 1
    for path in files:
        for ix, _vs, _l, _o, _w in _iter_file_rows(path, fmt, index_map):
            num_rows += 1
            max_nnz = max(max_nnz, len(ix))
    return index_map, StreamStats(num_rows=num_rows, max_nnz=max_nnz)


def iter_chunks(
    paths,
    fmt,
    index_map: IndexMap,
    *,
    rows_per_chunk: int,
    nnz_width: int,
) -> Iterator[SparseBatch]:
    """Stream fixed-shape [rows_per_chunk, nnz_width] SparseBatch chunks
    (weight-0 padding rows in the final chunk). Every chunk has the SAME
    shape, so one jitted partial-objective serves the whole stream."""
    import jax.numpy as jnp

    from photon_ml_tpu.io.paths import expand_input_paths

    files = sorted(expand_input_paths(paths, lambda fn: fn.endswith(".avro")))
    R, W = rows_per_chunk, nnz_width
    ix_buf = np.zeros((R, W), np.int32)
    v_buf = np.zeros((R, W), np.float32)
    lab_buf = np.zeros((R,), np.float32)
    off_buf = np.zeros((R,), np.float32)
    wgt_buf = np.zeros((R,), np.float32)
    fill = 0

    def emit():
        return SparseBatch(
            indices=jnp.asarray(ix_buf),
            values=jnp.asarray(v_buf),
            labels=jnp.asarray(lab_buf),
            offsets=jnp.asarray(off_buf),
            weights=jnp.asarray(wgt_buf),
        )

    for path in files:
        for ix, vs, lab, off, wgt in _iter_file_rows(path, fmt, index_map):
            if len(ix) > W:
                raise ValueError(
                    f"row has {len(ix)} nonzeros > staging width {W}; "
                    "re-scan the stream or raise nnz_width"
                )
            ix_buf[fill, : len(ix)] = ix
            ix_buf[fill, len(ix):] = 0
            v_buf[fill, : len(vs)] = vs
            v_buf[fill, len(vs):] = 0.0
            lab_buf[fill] = lab
            off_buf[fill] = off
            wgt_buf[fill] = wgt
            fill += 1
            if fill == R:
                yield emit()
                fill = 0
    if fill:
        ix_buf[fill:] = 0
        v_buf[fill:] = 0.0
        lab_buf[fill:] = 0.0
        off_buf[fill:] = 0.0
        wgt_buf[fill:] = 0.0  # weight-0 rows are inert in every objective
        yield emit()


class StreamingGLMObjective:
    """GLMObjective facade whose (value, gradient) stream the input from
    disk per evaluation — full-batch semantics with bounded memory.

    The per-chunk partial (l2 = 0) is one fixed-shape jitted program;
    the L2 term is added once at the end. Feed this to the host-driven
    L-BFGS (optim.host_lbfgs.minimize_lbfgs_host) — the in-jit while_loop
    optimizers cannot trace through disk IO.
    """

    def __init__(
        self,
        paths,
        fmt,
        index_map: IndexMap,
        stats: StreamStats,
        task,
        *,
        rows_per_chunk: int = 65536,
    ):
        import jax

        from photon_ml_tpu.ops.losses import loss_for_task
        from photon_ml_tpu.ops.objective import GLMObjective

        self.paths = paths
        self.fmt = fmt
        self.index_map = index_map
        self.stats = stats
        self.rows_per_chunk = int(min(rows_per_chunk, max(stats.num_rows, 8)))
        self.nnz_width = stats.max_nnz
        self.dim = index_map.size
        self._objective = GLMObjective(loss_for_task(task), self.dim)
        self._partial = jax.jit(
            lambda w, b: self._objective.value_and_gradient(w, b, 0.0)
        )

    def chunks(self) -> Iterator[SparseBatch]:
        return iter_chunks(
            self.paths, self.fmt, self.index_map,
            rows_per_chunk=self.rows_per_chunk, nnz_width=self.nnz_width,
        )

    def value_and_gradient(self, w, l2_weight=0.0):
        import jax.numpy as jnp

        value = jnp.float32(0.0)
        grad = jnp.zeros((self.dim,), jnp.float32)
        for batch in self.chunks():
            v, g = self._partial(w, batch)
            value = value + v
            grad = grad + g
        value = value + 0.5 * l2_weight * jnp.vdot(w, w)
        return value, grad + l2_weight * w
