"""Input data formats: Avro / LibSVM -> IndexMap + padded SparseBatch.

Reference: photon-ml .../io/GLMSuite.scala (Avro -> LabeledPoint with
name+TAB+term keys, intercept injection, selected-features filter, JSON
box-constraint parsing at :190-245, index map build/load at :98-187),
InputDataFormat.scala:26-51, AvroInputDataFormat.scala,
LibSVMInputDataFormat.scala:43-75, InputFormatFactory.scala.

The Spark RDD[LabeledPoint] becomes one padded SparseBatch (or a list of
equally-shaped shards for streaming); everything downstream is static-shape.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.data.batch import SparseBatch, make_sparse_batch
from photon_ml_tpu.io.avro_codec import read_avro_records
from photon_ml_tpu.io.libsvm import read_libsvm
from photon_ml_tpu.optim.common import BoxConstraints
from photon_ml_tpu.utils.index_map import (
    IndexMap,
    feature_key,
    intercept_key,
)

import jax.numpy as jnp


@dataclass
class LoadedData:
    """One loaded dataset: batch + vocabulary + optional constraints."""

    batch: SparseBatch
    index_map: IndexMap
    num_features: int
    intercept_index: Optional[int]
    constraints: Optional[BoxConstraints] = None


def parse_constraint_string(
    constraint_string: Optional[str],
    index_map: IndexMap,
    num_features: int,
    intercept_index: Optional[int],
) -> Optional[BoxConstraints]:
    """JSON array of {name, term, lowerBound, upperBound} -> box arrays.

    Wildcard "*" in name (with any term) applies the bound to every
    non-intercept feature; overlapping constraints are rejected
    (GLMSuite.createConstraintFeatureMap:190-245).
    """
    if not constraint_string:
        return None
    entries = json.loads(constraint_string)
    lower = np.full((num_features,), -np.inf, np.float32)
    upper = np.full((num_features,), np.inf, np.float32)
    seen: Dict[int, Tuple[float, float]] = {}
    wildcard: Optional[Tuple[float, float]] = None
    for entry in entries:
        if "name" not in entry or "term" not in entry:
            raise ValueError(
                f"constraint entry must contain name and term: {entry}"
            )
        name = entry["name"]
        term = entry["term"]
        lo = float(entry.get("lowerBound", -math.inf))
        hi = float(entry.get("upperBound", math.inf))
        if lo > hi:
            raise ValueError(f"lowerBound > upperBound in constraint {entry}")
        if name == "*":
            if wildcard is not None or seen:
                raise ValueError(
                    "conflicting constraints: wildcard plus other constraints"
                )
            wildcard = (lo, hi)
        else:
            if wildcard is not None:
                raise ValueError(
                    "conflicting constraints: wildcard plus other constraints"
                )
            idx = index_map.get_index(feature_key(name, term))
            if idx < 0:
                continue  # constraint on a feature absent from the data
            if idx in seen and seen[idx] != (lo, hi):
                raise ValueError(
                    f"conflicting constraints for feature ({name},{term})"
                )
            seen[idx] = (lo, hi)
            lower[idx], upper[idx] = lo, hi
    if wildcard is not None:
        lower[:], upper[:] = wildcard
        if intercept_index is not None:
            lower[intercept_index], upper[intercept_index] = -np.inf, np.inf
    elif not seen:
        return None
    return BoxConstraints(lower=jnp.asarray(lower), upper=jnp.asarray(upper))


def _rows_to_batch(
    rows: List[Tuple[List[int], List[float]]],
    labels: List[float],
    offsets: List[float],
    weights: List[float],
    *,
    pad_rows_to: int = 8,
    pad_nnz_to: int = 8,
) -> SparseBatch:
    return make_sparse_batch(
        rows,
        labels,
        offsets,
        weights,
        pad_rows_to=pad_rows_to,
        pad_nnz_to=pad_nnz_to,
    )


class AvroInputDataFormat:
    """TrainingExampleAvro reader (GLMSuite Avro path).

    ``selected_features``: optional set of feature keys to keep
    (GLMSuite.featureKeySet filtering); ``add_intercept`` appends the
    constant-1 intercept feature to every row (GLMSuite.addIntercept).
    ``field_names``: the Avro field-name convention
    (io/FieldNamesType.scala + avro/{TrainingExample,
    ResponsePrediction}FieldNames.scala) — the two differ only in the
    response field: TRAINING_EXAMPLE reads ``label``,
    RESPONSE_PREDICTION reads ``response``.
    """

    def __init__(
        self,
        *,
        add_intercept: bool = True,
        selected_features: Optional[Sequence[str]] = None,
        field_names: str = "TRAINING_EXAMPLE",
    ):
        self.add_intercept = add_intercept
        self.selected = set(selected_features) if selected_features else None
        fn = field_names.strip().upper()
        if fn in ("TRAINING_EXAMPLE", "NONE"):
            self.response_field = "label"
        elif fn == "RESPONSE_PREDICTION":
            self.response_field = "response"
        else:
            raise ValueError(f"unknown field names type {field_names!r}")

    def _record_pairs(self, record: dict) -> Iterable[Tuple[str, float]]:
        for f in record["features"]:
            key = feature_key(f["name"], f["term"])
            if self.selected is None or key in self.selected:
                yield key, float(f["value"])

    def decode_file(self, path: str):
        """Native column decode of ONE file; None -> caller uses the
        Python codec. The single definition of the native-decode fallback
        contract (schema shape check, recoverable errors), shared by the
        in-memory loader and the streaming path."""
        from photon_ml_tpu.io import native_avro
        from photon_ml_tpu.io.avro_codec import read_container_schema

        if not native_avro.available():
            return None
        try:
            schema = read_container_schema(path)
            names = {f["name"] for f in schema.get("fields", [])}
            if "features" not in names or self.response_field not in names:
                return None
            numeric = [
                f
                for f in (self.response_field, "offset", "weight")
                if f in names
            ]
            plan = native_avro.Plan(schema).compile(
                numeric_fields=numeric, bag_fields=["features"]
            )
            return native_avro.decode_columns(path, plan)
        except (native_avro.PlanError, ValueError, OSError):
            return None

    def _decode_native(self, paths):
        """Try the native column decoder for EVERY file; None -> caller
        falls back to the Python codec (all files or none, so one loader
        invocation never mixes decode semantics)."""
        from photon_ml_tpu.io.paths import expand_input_paths

        files = list(
            expand_input_paths(paths, lambda fn: fn.endswith(".avro"))
        )
        if not files:
            return None
        out = []
        for p in files:
            cols = self.decode_file(p)
            if cols is None:
                return None
            out.append(cols)
        return out

    def iter_rows_from_decoded(self, cols, index_map: IndexMap, intercept_index):
        """Yield (indices, values, label, offset, weight) per record of one
        file's DecodedColumns — the single definition of the native-decode
        remap semantics (intern-table remap, selected-features filter,
        null/NaN rules, intercept append) shared by the in-memory loader
        and the streaming (>RAM) path."""
        table = np.asarray(
            [
                index_map.get_index(s)
                if self.selected is None or s in self.selected
                else -1
                for s in cols.strings
            ],
            dtype=np.int64,
        )
        row_ptr, key_ids, values = cols.bag("features")
        gix = table[key_ids] if len(key_ids) else np.zeros(0, np.int64)
        lab = cols.f64(self.response_field)
        if np.isnan(lab).any():
            # the Python fallback would crash on float(None); a NaN label
            # must not silently poison the fit
            raise ValueError("null/NaN label in Avro input (native decode)")
        off = (
            cols.f64("offset")
            if "offset" in cols.plan.num_slots
            else np.zeros(len(lab))
        )
        wgt = (
            cols.f64("weight")
            if "weight" in cols.plan.num_slots
            else np.ones(len(lab))
        )
        # only the null sentinel is replaced — inf passes through,
        # matching the Python fallback
        off = np.where(np.isnan(off), 0.0, off)
        wgt = np.where(np.isnan(wgt), 1.0, wgt)
        for i in range(cols.num_records):
            lo, hi = int(row_ptr[i]), int(row_ptr[i + 1])
            g = gix[lo:hi]
            keep = g >= 0
            ix = g[keep].tolist()
            vs = values[lo:hi][keep].tolist()
            if intercept_index is not None:
                ix.append(intercept_index)
                vs.append(1.0)
            yield ix, vs, float(lab[i]), float(off[i]), float(wgt[i])

    def iter_rows_from_records(self, records, index_map: IndexMap, intercept_index):
        """Python-codec twin of iter_rows_from_decoded."""
        for record in records:
            ix: List[int] = []
            vs: List[float] = []
            for key, value in self._record_pairs(record):
                i = index_map.get_index(key)
                if i >= 0:
                    ix.append(i)
                    vs.append(value)
            if intercept_index is not None:
                ix.append(intercept_index)
                vs.append(1.0)
            off_v = record.get("offset")
            wgt_v = record.get("weight")
            yield (
                ix, vs, float(record[self.response_field]),
                0.0 if off_v is None else float(off_v),
                1.0 if wgt_v is None else float(wgt_v),
            )

    def _index_map_from_decoded(self, decoded) -> IndexMap:
        keys = (
            key
            for cols in decoded
            for key in cols.strings
            if self.selected is None or key in self.selected
        )
        return IndexMap.build(keys, add_intercept=self.add_intercept)

    def build_index_map(self, paths) -> IndexMap:
        decoded = self._decode_native(paths)
        if decoded is not None:
            return self._index_map_from_decoded(decoded)
        keys = (
            key
            for record in read_avro_records(paths)
            for key, _ in self._record_pairs(record)
        )
        return IndexMap.build(keys, add_intercept=self.add_intercept)

    def load(
        self,
        paths,
        index_map: Optional[IndexMap] = None,
        constraint_string: Optional[str] = None,
    ) -> LoadedData:
        decoded = self._decode_native(paths)
        if index_map is None:
            index_map = (
                self._index_map_from_decoded(decoded)
                if decoded is not None
                else self.build_index_map(paths)
            )
        dim = index_map.size
        icept = index_map.get_index(intercept_key()) if self.add_intercept else -1
        intercept_index = icept if icept >= 0 else None

        rows, labels, offsets, weights = [], [], [], []
        if decoded is not None:
            row_iter = (
                row
                for cols in decoded
                for row in self.iter_rows_from_decoded(
                    cols, index_map, intercept_index
                )
            )
        else:
            row_iter = self.iter_rows_from_records(
                read_avro_records(paths), index_map, intercept_index
            )
        for ix, vs, lab, off, wgt in row_iter:
            rows.append((ix, vs))
            labels.append(lab)
            offsets.append(off)
            weights.append(wgt)

        batch = _rows_to_batch(rows, labels, offsets, weights)
        constraints = parse_constraint_string(
            constraint_string, index_map, dim, intercept_index
        )
        return LoadedData(batch, index_map, dim, intercept_index, constraints)


class LibSVMInputDataFormat:
    """LibSVM text reader (LibSVMInputDataFormat.scala analog).

    ``selected_features``: optional feature-key filter, matching the Avro
    format's semantics (keys are ``str(index) + TAB``).
    """

    def __init__(
        self,
        *,
        add_intercept: bool = True,
        zero_based: bool = False,
        selected_features: Optional[Sequence[str]] = None,
        feature_dimension: Optional[int] = None,
    ):
        self.add_intercept = add_intercept
        self.zero_based = zero_based
        self.selected = set(selected_features) if selected_features else None
        self.feature_dimension = feature_dimension

    def build_index_map(self, paths) -> IndexMap:
        if self.feature_dimension is not None:
            # pre-declared dimension (the reference's --feature-dimension,
            # LibSVMInputDataFormat.scala:32-39): indices ARE the ids, no
            # vocabulary scan; intercept appended when enabled
            from photon_ml_tpu.utils.index_map import IdentityIndexMap

            return IdentityIndexMap(
                self.feature_dimension, add_intercept=self.add_intercept
            )
        keys = (
            key
            for _, pairs in read_libsvm(paths, zero_based=self.zero_based)
            for key in (feature_key(str(idx)) for idx, _ in pairs)
            if self.selected is None or key in self.selected
        )
        return IndexMap.build(keys, add_intercept=self.add_intercept)

    def load(
        self,
        paths,
        index_map: Optional[IndexMap] = None,
        constraint_string: Optional[str] = None,
    ) -> LoadedData:
        if index_map is None:
            index_map = self.build_index_map(paths)
        dim = index_map.size
        icept = index_map.get_index(intercept_key()) if self.add_intercept else -1
        intercept_index = icept if icept >= 0 else None

        rows, labels, offsets, weights = [], [], [], []
        for label, pairs in read_libsvm(paths, zero_based=self.zero_based):
            ix, vs = [], []
            for idx, value in pairs:
                key = feature_key(str(idx))
                # with a pre-declared feature_dimension the identity map
                # accepts every in-range id, so the selected-features
                # filter must be applied here
                if self.selected is not None and key not in self.selected:
                    continue
                i = index_map.get_index(key)
                if i >= 0:
                    ix.append(i)
                    vs.append(value)
            if intercept_index is not None:
                ix.append(intercept_index)
                vs.append(1.0)
            rows.append((ix, vs))
            labels.append(label)
            offsets.append(0.0)
            weights.append(1.0)

        batch = _rows_to_batch(rows, labels, offsets, weights)
        constraints = parse_constraint_string(
            constraint_string, index_map, dim, intercept_index
        )
        return LoadedData(batch, index_map, dim, intercept_index, constraints)


def create_input_format(kind: str, **kwargs):
    """InputFormatFactory analog: kind in {AVRO, LIBSVM}."""
    k = kind.strip().upper()
    if k == "AVRO":
        return AvroInputDataFormat(**kwargs)
    if k == "LIBSVM":
        return LibSVMInputDataFormat(**kwargs)
    raise ValueError(f"unknown input format: {kind}")
