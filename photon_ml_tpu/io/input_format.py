"""Input data formats: Avro / LibSVM -> IndexMap + padded SparseBatch.

Reference: photon-ml .../io/GLMSuite.scala (Avro -> LabeledPoint with
name+TAB+term keys, intercept injection, selected-features filter, JSON
box-constraint parsing at :190-245, index map build/load at :98-187),
InputDataFormat.scala:26-51, AvroInputDataFormat.scala,
LibSVMInputDataFormat.scala:43-75, InputFormatFactory.scala.

The Spark RDD[LabeledPoint] becomes one padded SparseBatch (or a list of
equally-shaped shards for streaming); everything downstream is static-shape.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.data.batch import SparseBatch, make_sparse_batch
from photon_ml_tpu.io.avro_codec import read_avro_records
from photon_ml_tpu.io.libsvm import read_libsvm
from photon_ml_tpu.optim.common import BoxConstraints
from photon_ml_tpu.utils.index_map import (
    IndexMap,
    feature_key,
    intercept_key,
)

import jax.numpy as jnp


@dataclass
class LoadedData:
    """One loaded dataset: batch + vocabulary + optional constraints."""

    batch: SparseBatch
    index_map: IndexMap
    num_features: int
    intercept_index: Optional[int]
    constraints: Optional[BoxConstraints] = None


def parse_constraint_string(
    constraint_string: Optional[str],
    index_map: IndexMap,
    num_features: int,
    intercept_index: Optional[int],
) -> Optional[BoxConstraints]:
    """JSON array of {name, term, lowerBound, upperBound} -> box arrays.

    Wildcard "*" in name (with any term) applies the bound to every
    non-intercept feature; overlapping constraints are rejected
    (GLMSuite.createConstraintFeatureMap:190-245).
    """
    if not constraint_string:
        return None
    entries = json.loads(constraint_string)
    lower = np.full((num_features,), -np.inf, np.float32)
    upper = np.full((num_features,), np.inf, np.float32)
    seen: Dict[int, Tuple[float, float]] = {}
    wildcard: Optional[Tuple[float, float]] = None
    for entry in entries:
        if "name" not in entry or "term" not in entry:
            raise ValueError(
                f"constraint entry must contain name and term: {entry}"
            )
        name = entry["name"]
        term = entry["term"]
        lo = float(entry.get("lowerBound", -math.inf))
        hi = float(entry.get("upperBound", math.inf))
        if lo > hi:
            raise ValueError(f"lowerBound > upperBound in constraint {entry}")
        if name == "*":
            if wildcard is not None or seen:
                raise ValueError(
                    "conflicting constraints: wildcard plus other constraints"
                )
            wildcard = (lo, hi)
        else:
            if wildcard is not None:
                raise ValueError(
                    "conflicting constraints: wildcard plus other constraints"
                )
            idx = index_map.get_index(feature_key(name, term))
            if idx < 0:
                continue  # constraint on a feature absent from the data
            if idx in seen and seen[idx] != (lo, hi):
                raise ValueError(
                    f"conflicting constraints for feature ({name},{term})"
                )
            seen[idx] = (lo, hi)
            lower[idx], upper[idx] = lo, hi
    if wildcard is not None:
        lower[:], upper[:] = wildcard
        if intercept_index is not None:
            lower[intercept_index], upper[intercept_index] = -np.inf, np.inf
    elif not seen:
        return None
    return BoxConstraints(lower=jnp.asarray(lower), upper=jnp.asarray(upper))


def _rows_to_batch(
    rows: List[Tuple[List[int], List[float]]],
    labels: List[float],
    offsets: List[float],
    weights: List[float],
    *,
    pad_rows_to: int = 8,
    pad_nnz_to: int = 8,
) -> SparseBatch:
    return make_sparse_batch(
        rows,
        labels,
        offsets,
        weights,
        pad_rows_to=pad_rows_to,
        pad_nnz_to=pad_nnz_to,
    )


class AvroInputDataFormat:
    """TrainingExampleAvro reader (GLMSuite Avro path).

    ``selected_features``: optional set of feature keys to keep
    (GLMSuite.featureKeySet filtering); ``add_intercept`` appends the
    constant-1 intercept feature to every row (GLMSuite.addIntercept).
    """

    def __init__(
        self,
        *,
        add_intercept: bool = True,
        selected_features: Optional[Sequence[str]] = None,
    ):
        self.add_intercept = add_intercept
        self.selected = set(selected_features) if selected_features else None

    def _record_pairs(self, record: dict) -> Iterable[Tuple[str, float]]:
        for f in record["features"]:
            key = feature_key(f["name"], f["term"])
            if self.selected is None or key in self.selected:
                yield key, float(f["value"])

    def build_index_map(self, paths) -> IndexMap:
        keys = (
            key
            for record in read_avro_records(paths)
            for key, _ in self._record_pairs(record)
        )
        return IndexMap.build(keys, add_intercept=self.add_intercept)

    def load(
        self,
        paths,
        index_map: Optional[IndexMap] = None,
        constraint_string: Optional[str] = None,
    ) -> LoadedData:
        if index_map is None:
            index_map = self.build_index_map(paths)
        dim = index_map.size
        icept = index_map.get_index(intercept_key()) if self.add_intercept else -1
        intercept_index = icept if icept >= 0 else None

        rows, labels, offsets, weights = [], [], [], []
        for record in read_avro_records(paths):
            ix: List[int] = []
            vs: List[float] = []
            for key, value in self._record_pairs(record):
                i = index_map.get_index(key)
                if i >= 0:
                    ix.append(i)
                    vs.append(value)
            if intercept_index is not None:
                ix.append(intercept_index)
                vs.append(1.0)
            rows.append((ix, vs))
            labels.append(float(record["label"]))
            offsets.append(float(record.get("offset") or 0.0))
            weights.append(float(record.get("weight") or 1.0))

        batch = _rows_to_batch(rows, labels, offsets, weights)
        constraints = parse_constraint_string(
            constraint_string, index_map, dim, intercept_index
        )
        return LoadedData(batch, index_map, dim, intercept_index, constraints)


class LibSVMInputDataFormat:
    """LibSVM text reader (LibSVMInputDataFormat.scala analog).

    ``selected_features``: optional feature-key filter, matching the Avro
    format's semantics (keys are ``str(index) + TAB``).
    """

    def __init__(
        self,
        *,
        add_intercept: bool = True,
        zero_based: bool = False,
        selected_features: Optional[Sequence[str]] = None,
    ):
        self.add_intercept = add_intercept
        self.zero_based = zero_based
        self.selected = set(selected_features) if selected_features else None

    def build_index_map(self, paths) -> IndexMap:
        keys = (
            key
            for _, pairs in read_libsvm(paths, zero_based=self.zero_based)
            for key in (feature_key(str(idx)) for idx, _ in pairs)
            if self.selected is None or key in self.selected
        )
        return IndexMap.build(keys, add_intercept=self.add_intercept)

    def load(
        self,
        paths,
        index_map: Optional[IndexMap] = None,
        constraint_string: Optional[str] = None,
    ) -> LoadedData:
        if index_map is None:
            index_map = self.build_index_map(paths)
        dim = index_map.size
        icept = index_map.get_index(intercept_key()) if self.add_intercept else -1
        intercept_index = icept if icept >= 0 else None

        rows, labels, offsets, weights = [], [], [], []
        for label, pairs in read_libsvm(paths, zero_based=self.zero_based):
            ix, vs = [], []
            for idx, value in pairs:
                i = index_map.get_index(feature_key(str(idx)))
                if i >= 0:
                    ix.append(i)
                    vs.append(value)
            if intercept_index is not None:
                ix.append(intercept_index)
                vs.append(1.0)
            rows.append((ix, vs))
            labels.append(label)
            offsets.append(0.0)
            weights.append(1.0)

        batch = _rows_to_batch(rows, labels, offsets, weights)
        constraints = parse_constraint_string(
            constraint_string, index_map, dim, intercept_index
        )
        return LoadedData(batch, index_map, dim, intercept_index, constraints)


def create_input_format(kind: str, **kwargs):
    """InputFormatFactory analog: kind in {AVRO, LIBSVM}."""
    k = kind.strip().upper()
    if k == "AVRO":
        return AvroInputDataFormat(**kwargs)
    if k == "LIBSVM":
        return LibSVMInputDataFormat(**kwargs)
    raise ValueError(f"unknown input format: {kind}")
