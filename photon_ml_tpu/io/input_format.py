"""Input data formats: Avro / LibSVM -> IndexMap + padded SparseBatch.

Reference: photon-ml .../io/GLMSuite.scala (Avro -> LabeledPoint with
name+TAB+term keys, intercept injection, selected-features filter, JSON
box-constraint parsing at :190-245, index map build/load at :98-187),
InputDataFormat.scala:26-51, AvroInputDataFormat.scala,
LibSVMInputDataFormat.scala:43-75, InputFormatFactory.scala.

The Spark RDD[LabeledPoint] becomes one padded SparseBatch (or a list of
equally-shaped shards for streaming); everything downstream is static-shape.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.data.batch import SparseBatch, make_sparse_batch
from photon_ml_tpu.io.avro_codec import read_avro_records
from photon_ml_tpu.io.libsvm import read_libsvm
from photon_ml_tpu.optim.common import BoxConstraints
from photon_ml_tpu.utils.index_map import (
    IndexMap,
    feature_key,
    intercept_key,
)

import jax.numpy as jnp


@dataclass
class LoadedData:
    """One loaded dataset: batch + vocabulary + optional constraints."""

    batch: SparseBatch
    index_map: IndexMap
    num_features: int
    intercept_index: Optional[int]
    constraints: Optional[BoxConstraints] = None


def parse_constraint_string(
    constraint_string: Optional[str],
    index_map: IndexMap,
    num_features: int,
    intercept_index: Optional[int],
) -> Optional[BoxConstraints]:
    """JSON array of {name, term, lowerBound, upperBound} -> box arrays.

    Wildcard "*" in name (with any term) applies the bound to every
    non-intercept feature; overlapping constraints are rejected
    (GLMSuite.createConstraintFeatureMap:190-245).
    """
    if not constraint_string:
        return None
    entries = json.loads(constraint_string)
    lower = np.full((num_features,), -np.inf, np.float32)
    upper = np.full((num_features,), np.inf, np.float32)
    seen: Dict[int, Tuple[float, float]] = {}
    wildcard: Optional[Tuple[float, float]] = None
    for entry in entries:
        if "name" not in entry or "term" not in entry:
            raise ValueError(
                f"constraint entry must contain name and term: {entry}"
            )
        name = entry["name"]
        term = entry["term"]
        lo = float(entry.get("lowerBound", -math.inf))
        hi = float(entry.get("upperBound", math.inf))
        if lo > hi:
            raise ValueError(f"lowerBound > upperBound in constraint {entry}")
        if name == "*":
            if wildcard is not None or seen:
                raise ValueError(
                    "conflicting constraints: wildcard plus other constraints"
                )
            wildcard = (lo, hi)
        else:
            if wildcard is not None:
                raise ValueError(
                    "conflicting constraints: wildcard plus other constraints"
                )
            idx = index_map.get_index(feature_key(name, term))
            if idx < 0:
                continue  # constraint on a feature absent from the data
            if idx in seen and seen[idx] != (lo, hi):
                raise ValueError(
                    f"conflicting constraints for feature ({name},{term})"
                )
            seen[idx] = (lo, hi)
            lower[idx], upper[idx] = lo, hi
    if wildcard is not None:
        lower[:], upper[:] = wildcard
        if intercept_index is not None:
            lower[intercept_index], upper[intercept_index] = -np.inf, np.inf
    elif not seen:
        return None
    return BoxConstraints(lower=jnp.asarray(lower), upper=jnp.asarray(upper))


def _rows_to_batch(
    rows: List[Tuple[List[int], List[float]]],
    labels: List[float],
    offsets: List[float],
    weights: List[float],
    *,
    pad_rows_to: int = 8,
    pad_nnz_to: int = 8,
) -> SparseBatch:
    return make_sparse_batch(
        rows,
        labels,
        offsets,
        weights,
        pad_rows_to=pad_rows_to,
        pad_nnz_to=pad_nnz_to,
    )


class AvroInputDataFormat:
    """TrainingExampleAvro reader (GLMSuite Avro path).

    ``selected_features``: optional set of feature keys to keep
    (GLMSuite.featureKeySet filtering); ``add_intercept`` appends the
    constant-1 intercept feature to every row (GLMSuite.addIntercept).
    ``field_names``: the Avro field-name convention
    (io/FieldNamesType.scala + avro/{TrainingExample,
    ResponsePrediction}FieldNames.scala) — the two differ only in the
    response field: TRAINING_EXAMPLE reads ``label``,
    RESPONSE_PREDICTION reads ``response``.
    """

    def __init__(
        self,
        *,
        add_intercept: bool = True,
        selected_features: Optional[Sequence[str]] = None,
        field_names: str = "TRAINING_EXAMPLE",
    ):
        self.add_intercept = add_intercept
        self.selected = set(selected_features) if selected_features else None
        fn = field_names.strip().upper()
        if fn in ("TRAINING_EXAMPLE", "NONE"):
            self.response_field = "label"
        elif fn == "RESPONSE_PREDICTION":
            self.response_field = "response"
        else:
            raise ValueError(f"unknown field names type {field_names!r}")

    def _record_pairs(self, record: dict) -> Iterable[Tuple[str, float]]:
        for f in record["features"]:
            key = feature_key(f["name"], f["term"])
            if self.selected is None or key in self.selected:
                yield key, float(f["value"])

    def decode_file(self, path: str):
        """Native column decode of ONE file; None -> caller uses the
        Python codec. The single definition of the native-decode fallback
        contract (schema shape check, recoverable errors), shared by the
        in-memory loader and the streaming path."""
        from photon_ml_tpu.io import native_avro
        from photon_ml_tpu.io.avro_codec import read_container_schema

        if not native_avro.available():
            return None
        try:
            schema = read_container_schema(path)
            names = {f["name"] for f in schema.get("fields", [])}
            if "features" not in names or self.response_field not in names:
                return None
            numeric = [
                f
                for f in (self.response_field, "offset", "weight")
                if f in names
            ]
            plan = native_avro.Plan(schema).compile(
                numeric_fields=numeric, bag_fields=["features"]
            )
            return native_avro.decode_columns(path, plan)
        except (native_avro.PlanError, ValueError, OSError):
            return None

    def _decode_native(self, paths):
        """Try the native column decoder for EVERY file; None -> caller
        falls back to the Python codec (all files or none, so one loader
        invocation never mixes decode semantics)."""
        from photon_ml_tpu.io.paths import expand_input_paths

        files = list(
            expand_input_paths(paths, lambda fn: fn.endswith(".avro"))
        )
        if not files:
            return None
        out = []
        for p in files:
            cols = self.decode_file(p)
            if cols is None:
                return None
            out.append(cols)
        return out

    def iter_rows_from_decoded(self, cols, index_map: IndexMap, intercept_index):
        """Yield (indices, values, label, offset, weight) per record of one
        file's DecodedColumns — the single definition of the native-decode
        remap semantics (intern-table remap, selected-features filter,
        null/NaN rules, intercept append) shared by the in-memory loader
        and the streaming (>RAM) path."""
        table = np.asarray(
            [
                index_map.get_index(s)
                if self.selected is None or s in self.selected
                else -1
                for s in cols.strings
            ],
            dtype=np.int64,
        )
        row_ptr, key_ids, values = cols.bag("features")
        gix = table[key_ids] if len(key_ids) else np.zeros(0, np.int64)
        lab = cols.f64(self.response_field)
        if np.isnan(lab).any():
            # the Python fallback would crash on float(None); a NaN label
            # must not silently poison the fit
            raise ValueError("null/NaN label in Avro input (native decode)")
        off = (
            cols.f64("offset")
            if "offset" in cols.plan.num_slots
            else np.zeros(len(lab))
        )
        wgt = (
            cols.f64("weight")
            if "weight" in cols.plan.num_slots
            else np.ones(len(lab))
        )
        # only the null sentinel is replaced — inf passes through,
        # matching the Python fallback
        off = np.where(np.isnan(off), 0.0, off)
        wgt = np.where(np.isnan(wgt), 1.0, wgt)
        for i in range(cols.num_records):
            lo, hi = int(row_ptr[i]), int(row_ptr[i + 1])
            g = gix[lo:hi]
            keep = g >= 0
            ix = g[keep].tolist()
            vs = values[lo:hi][keep].tolist()
            if intercept_index is not None:
                ix.append(intercept_index)
                vs.append(1.0)
            yield ix, vs, float(lab[i]), float(off[i]), float(wgt[i])

    def iter_rows_from_records(self, records, index_map: IndexMap, intercept_index):
        """Python-codec twin of iter_rows_from_decoded."""
        for record in records:
            ix: List[int] = []
            vs: List[float] = []
            for key, value in self._record_pairs(record):
                i = index_map.get_index(key)
                if i >= 0:
                    ix.append(i)
                    vs.append(value)
            if intercept_index is not None:
                ix.append(intercept_index)
                vs.append(1.0)
            off_v = record.get("offset")
            wgt_v = record.get("weight")
            yield (
                ix, vs, float(record[self.response_field]),
                0.0 if off_v is None else float(off_v),
                1.0 if wgt_v is None else float(wgt_v),
            )

    # -- streaming protocol (io/streaming.py drives these) -----------------

    def _stream_intercept(self, index_map: IndexMap) -> Optional[int]:
        icept = (
            index_map.get_index(intercept_key()) if self.add_intercept else -1
        )
        return icept if icept >= 0 else None

    def stream_files(self, paths) -> List[str]:
        """Sorted input files for the bounded-memory streaming path."""
        from photon_ml_tpu.io.paths import expand_input_paths

        files = sorted(
            expand_input_paths(paths, lambda fn: fn.endswith(".avro"))
        )
        if not files:
            raise ValueError(f"no .avro inputs under {paths!r}")
        return files

    def stream_rows(self, path: str, index_map: IndexMap):
        """Yield (indices, values, label, offset, weight) per record of
        ONE file, bounded memory: native column decode when available
        (one file resident at a time), record-at-a-time Python codec
        otherwise. The remap semantics live in iter_rows_from_{decoded,
        records} — one definition shared with the in-memory loader."""
        yield from self.stream_rows_from_payload(
            self.decode_payload(path), path, index_map
        )

    # The two pipeline stages of stream_rows, split so the streaming
    # layer can run them on DIFFERENT threads (reader/decode ahead of
    # staging, io/streaming._pipelined_file_rows): decode_payload is the
    # expensive whole-file native column decode; stream_rows_from_payload
    # is the cheap row remap/iteration over an already-decoded payload.

    def decode_payload(self, path: str):
        """Decode stage: ONE file's decoded columns (None -> the
        record-at-a-time Python-codec fallback in
        stream_rows_from_payload). Thread-safe; holds one file."""
        return self.decode_file(path)

    def stream_rows_from_payload(self, payload, path: str, index_map: IndexMap):
        """Staging stage: rows of one file from its decoded payload."""
        icept = self._stream_intercept(index_map)
        if payload is not None:
            yield from self.iter_rows_from_decoded(payload, index_map, icept)
        else:
            yield from self.iter_rows_from_records(
                read_avro_records([path]), index_map, icept
            )

    def stream_scan(self, paths, index_map: Optional[IndexMap] = None):
        """One streaming pass over the files — ONE AT A TIME — collecting
        the vocabulary, the row count, and the max per-row nnz (incl.
        intercept) that fix the staging batch. Never keeps more than one
        decoded file resident. With a prebuilt ``index_map`` (the
        FeatureIndexingJob store — required for multi-host streaming) the
        key collection is skipped and only shape stats are scanned."""
        from photon_ml_tpu.io.streaming import StreamStats

        files = self.stream_files(paths)
        keys = set()
        collect_keys = index_map is None
        num_rows = 0
        max_live = 0  # per-row live (nonzero, selected) feature count
        for path in files:
            decoded = self.decode_file(path)
            if decoded is not None:
                sel = np.asarray(
                    [
                        self.selected is None or s in self.selected
                        for s in decoded.strings
                    ]
                )
                if collect_keys:
                    keys.update(
                        s for s, ok in zip(decoded.strings, sel) if ok
                    )
                # per-row width = entries the row iterators will emit:
                # every entry whose key is selected (zero VALUES are kept
                # — they are in the map and emitted by
                # iter_rows_from_decoded)
                row_ptr, key_ids, _values = decoded.bag("features")
                live = (
                    sel[key_ids] if len(key_ids) else np.zeros(0, bool)
                )
                counts = np.add.reduceat(
                    np.concatenate([live.astype(np.int64), [0]]),
                    row_ptr[:-1],
                ) if decoded.num_records else np.zeros(0, np.int64)
                # reduceat quirk: empty rows (row_ptr[i] == row_ptr[i+1])
                # return the element at the index instead of 0
                widths = np.diff(row_ptr)
                counts = np.where(widths > 0, counts, 0)
                if len(counts):
                    max_live = max(max_live, int(counts.max()))
                num_rows += decoded.num_records
            else:
                for record in read_avro_records([path]):
                    live = 0
                    for key, _v in self._record_pairs(record):
                        if collect_keys:
                            keys.add(key)
                        live += 1
                    max_live = max(max_live, live)
                    num_rows += 1
        if collect_keys:
            index_map = IndexMap.build(
                iter(keys), add_intercept=self.add_intercept
            )
        max_nnz = max(max_live + (1 if self.add_intercept else 0), 1)
        return index_map, StreamStats(num_rows=num_rows, max_nnz=max_nnz)

    def stream_scan_with_summary(self, paths, index_map: Optional[IndexMap] = None):
        """ONE streaming pass collecting the vocabulary, the staging-shape
        stats AND the colStats feature summary — the fused form of
        ``stream_scan`` + ``io.streaming.streaming_summary``, which each
        re-read (and re-decode) the whole train directory back to back in
        the driver's preprocess stage. Moments accumulate host-side per
        feature KEY (the vocabulary is not fixed until the pass ends) and
        scatter into index order once the map exists; the final summary is
        numerically the compute_summary/streaming_summary result up to
        fp32-vs-fp64 accumulation order.

        Returns ``(index_map, StreamStats, BasicStatisticalSummary)``.
        Memory: one decoded file + O(vocabulary) moment arrays — the same
        class as the vocabulary scan itself."""
        import jax.numpy as jnp

        from photon_ml_tpu.data.stats import finalize_summary
        from photon_ml_tpu.io.streaming import StreamStats

        files = self.stream_files(paths)
        collect_keys = index_map is None

        # growing per-key moment table (amortized append; vocab-sized)
        slot_of: Dict[str, int] = {}
        cap = 1024
        s1 = np.zeros(cap); s2 = np.zeros(cap); l1 = np.zeros(cap)
        nnz = np.zeros(cap)
        mx = np.full(cap, -np.inf); mn = np.full(cap, np.inf)

        def _ensure(n):
            nonlocal cap, s1, s2, l1, nnz, mx, mn
            if n <= cap:
                return
            new_cap = max(n, cap * 2)
            s1 = np.concatenate([s1, np.zeros(new_cap - cap)])
            s2 = np.concatenate([s2, np.zeros(new_cap - cap)])
            l1 = np.concatenate([l1, np.zeros(new_cap - cap)])
            nnz = np.concatenate([nnz, np.zeros(new_cap - cap)])
            mx = np.concatenate([mx, np.full(new_cap - cap, -np.inf)])
            mn = np.concatenate([mn, np.full(new_cap - cap, np.inf)])
            cap = new_cap

        def _slot(key: str) -> int:
            s = slot_of.get(key, -1)
            if s < 0:
                if not collect_keys and index_map.get_index(key) < 0:
                    return -1  # prebuilt map drops this feature
                s = len(slot_of)
                slot_of[key] = s
                _ensure(s + 1)
            return s

        num_rows = 0
        real_rows = 0.0
        max_live = 0
        for path in files:
            decoded = self.decode_file(path)
            if decoded is not None:
                m = decoded.num_records
                sel = np.asarray([
                    self.selected is None or s in self.selected
                    for s in decoded.strings
                ]) if len(decoded.strings) else np.zeros(0, bool)
                slot_table = np.asarray([
                    _slot(s) if ok else -1
                    for s, ok in zip(decoded.strings, sel)
                ], np.int64) if len(decoded.strings) else np.zeros(0, np.int64)
                wgt = (
                    decoded.f64("weight")
                    if "weight" in decoded.plan.num_slots
                    else np.ones(m)
                )
                wgt = np.where(np.isnan(wgt), 1.0, wgt)
                real = wgt > 0
                real_rows += float(real.sum())
                row_ptr, key_ids, values = decoded.bag("features")
                live = sel[key_ids] if len(key_ids) else np.zeros(0, bool)
                counts = np.add.reduceat(
                    np.concatenate([live.astype(np.int64), [0]]),
                    row_ptr[:-1],
                ) if m else np.zeros(0, np.int64)
                widths = np.diff(row_ptr)
                counts = np.where(widths > 0, counts, 0)
                if len(counts):
                    max_live = max(max_live, int(counts.max()))
                num_rows += m
                if len(key_ids):
                    row_of = np.repeat(np.arange(m, dtype=np.int64), widths)
                    ks = slot_table[key_ids]
                    # value-0 entries are moment no-ops (s1 += 0, not
                    # counted in nnz, excluded from max/min) — drop them
                    keep = (ks >= 0) & real[row_of] & (values != 0)
                    sl = ks[keep]
                    v = values[keep].astype(np.float64)
                    np.add.at(s1, sl, v)
                    np.add.at(s2, sl, v * v)
                    np.add.at(l1, sl, np.abs(v))
                    np.add.at(nnz, sl, 1.0)
                    np.maximum.at(mx, sl, v)
                    np.minimum.at(mn, sl, v)
            else:
                for record in read_avro_records([path]):
                    wgt_v = record.get("weight")
                    w = 1.0 if wgt_v is None else float(wgt_v)
                    real = w > 0
                    real_rows += 1.0 if real else 0.0
                    live = 0
                    for key, value in self._record_pairs(record):
                        live += 1
                        s = _slot(key)
                        if s >= 0 and real and value != 0:
                            s1[s] += value
                            s2[s] += value * value
                            l1[s] += abs(value)
                            nnz[s] += 1.0
                            mx[s] = max(mx[s], value)
                            mn[s] = min(mn[s], value)
                    max_live = max(max_live, live)
                    num_rows += 1
        if collect_keys:
            index_map = IndexMap.build(
                iter(slot_of), add_intercept=self.add_intercept
            )
        dim = index_map.size
        f_s1 = np.zeros(dim); f_s2 = np.zeros(dim); f_l1 = np.zeros(dim)
        f_nnz = np.zeros(dim)
        f_mx = np.full(dim, -np.inf); f_mn = np.full(dim, np.inf)
        for key, s in slot_of.items():
            j = index_map.get_index(key)
            if j >= 0:
                f_s1[j], f_s2[j], f_l1[j] = s1[s], s2[s], l1[s]
                f_nnz[j], f_mx[j], f_mn[j] = nnz[s], mx[s], mn[s]
        icept = self._stream_intercept(index_map)
        if icept is not None and real_rows > 0:
            # every real row carries the constant-1 intercept entry
            f_s1[icept] = f_s2[icept] = f_l1[icept] = real_rows
            f_nnz[icept] = real_rows
            f_mx[icept] = f_mn[icept] = 1.0
        summary = finalize_summary(
            jnp.float32(real_rows),
            jnp.asarray(f_s1, jnp.float32),
            jnp.asarray(f_s2, jnp.float32),
            jnp.asarray(f_l1, jnp.float32),
            jnp.asarray(f_nnz, jnp.float32),
            jnp.asarray(f_mx, jnp.float32),
            jnp.asarray(f_mn, jnp.float32),
        )
        max_nnz = max(max_live + (1 if self.add_intercept else 0), 1)
        return (
            index_map,
            StreamStats(num_rows=num_rows, max_nnz=max_nnz),
            summary,
        )

    def _index_map_from_decoded(self, decoded) -> IndexMap:
        keys = (
            key
            for cols in decoded
            for key in cols.strings
            if self.selected is None or key in self.selected
        )
        return IndexMap.build(keys, add_intercept=self.add_intercept)

    def build_index_map(self, paths) -> IndexMap:
        decoded = self._decode_native(paths)
        if decoded is not None:
            return self._index_map_from_decoded(decoded)
        keys = (
            key
            for record in read_avro_records(paths)
            for key, _ in self._record_pairs(record)
        )
        return IndexMap.build(keys, add_intercept=self.add_intercept)

    def load(
        self,
        paths,
        index_map: Optional[IndexMap] = None,
        constraint_string: Optional[str] = None,
    ) -> LoadedData:
        decoded = self._decode_native(paths)
        if index_map is None:
            index_map = (
                self._index_map_from_decoded(decoded)
                if decoded is not None
                else self.build_index_map(paths)
            )
        dim = index_map.size
        icept = index_map.get_index(intercept_key()) if self.add_intercept else -1
        intercept_index = icept if icept >= 0 else None

        rows, labels, offsets, weights = [], [], [], []
        if decoded is not None:
            row_iter = (
                row
                for cols in decoded
                for row in self.iter_rows_from_decoded(
                    cols, index_map, intercept_index
                )
            )
        else:
            row_iter = self.iter_rows_from_records(
                read_avro_records(paths), index_map, intercept_index
            )
        for ix, vs, lab, off, wgt in row_iter:
            rows.append((ix, vs))
            labels.append(lab)
            offsets.append(off)
            weights.append(wgt)

        batch = _rows_to_batch(rows, labels, offsets, weights)
        constraints = parse_constraint_string(
            constraint_string, index_map, dim, intercept_index
        )
        return LoadedData(batch, index_map, dim, intercept_index, constraints)


class LibSVMInputDataFormat:
    """LibSVM text reader (LibSVMInputDataFormat.scala analog).

    ``selected_features``: optional feature-key filter, matching the Avro
    format's semantics (keys are ``str(index) + TAB``).
    """

    def __init__(
        self,
        *,
        add_intercept: bool = True,
        zero_based: bool = False,
        selected_features: Optional[Sequence[str]] = None,
        feature_dimension: Optional[int] = None,
    ):
        self.add_intercept = add_intercept
        self.zero_based = zero_based
        self.selected = set(selected_features) if selected_features else None
        self.feature_dimension = feature_dimension

    def build_index_map(self, paths) -> IndexMap:
        if self.feature_dimension is not None:
            # pre-declared dimension (the reference's --feature-dimension,
            # LibSVMInputDataFormat.scala:32-39): indices ARE the ids, no
            # vocabulary scan; intercept appended when enabled
            from photon_ml_tpu.utils.index_map import IdentityIndexMap

            return IdentityIndexMap(
                self.feature_dimension, add_intercept=self.add_intercept
            )
        keys = (
            key
            for _, pairs in read_libsvm(paths, zero_based=self.zero_based)
            for key in (feature_key(str(idx)) for idx, _ in pairs)
            if self.selected is None or key in self.selected
        )
        return IndexMap.build(keys, add_intercept=self.add_intercept)

    def load(
        self,
        paths,
        index_map: Optional[IndexMap] = None,
        constraint_string: Optional[str] = None,
    ) -> LoadedData:
        if index_map is None:
            index_map = self.build_index_map(paths)
        dim = index_map.size
        icept = index_map.get_index(intercept_key()) if self.add_intercept else -1
        intercept_index = icept if icept >= 0 else None

        # ONE remap definition: the in-memory loader iterates the same
        # stream_rows the streaming path uses (selected-features filter,
        # identity-map range check, intercept append), so the two paths
        # cannot diverge — the contract the Avro format keeps via
        # iter_rows_from_{decoded,records}
        rows, labels, offsets, weights = [], [], [], []
        for path in self.stream_files(paths):
            for ix, vs, lab, off, wgt in self.stream_rows(path, index_map):
                rows.append((ix, vs))
                labels.append(lab)
                offsets.append(off)
                weights.append(wgt)

        batch = _rows_to_batch(rows, labels, offsets, weights)
        constraints = parse_constraint_string(
            constraint_string, index_map, dim, intercept_index
        )
        return LoadedData(batch, index_map, dim, intercept_index, constraints)

    # -- streaming protocol (io/streaming.py drives these) -----------------
    # LibSVM is line-oriented text, so the bounded-memory contract is
    # trivial: one line resident at a time (the reference's GLMSuite
    # streams both formats identically through RDD rows,
    # LibSVMInputDataFormat.scala:43-75).

    def stream_files(self, paths) -> List[str]:
        from photon_ml_tpu.io.paths import expand_input_paths

        files = sorted(expand_input_paths(paths))
        if not files:
            raise ValueError(f"no inputs under {paths!r}")
        return files

    def stream_rows(self, path: str, index_map: IndexMap):
        """(indices, values, label, offset, weight) per line of ONE file,
        one line resident at a time; same remap semantics as load()."""
        from photon_ml_tpu.io.libsvm import parse_libsvm_line

        icept = (
            index_map.get_index(intercept_key()) if self.add_intercept else -1
        )
        icept = icept if icept >= 0 else None
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                parsed = parse_libsvm_line(line, zero_based=self.zero_based)
                if parsed is None:
                    continue
                label, pairs = parsed
                ix: List[int] = []
                vs: List[float] = []
                for idx, value in pairs:
                    key = feature_key(str(idx))
                    if self.selected is not None and key not in self.selected:
                        continue
                    i = index_map.get_index(key)
                    if i >= 0:
                        ix.append(i)
                        vs.append(value)
                if icept is not None:
                    ix.append(icept)
                    vs.append(1.0)
                yield ix, vs, label, 0.0, 1.0

    def stream_scan(self, paths, index_map: Optional[IndexMap] = None):
        """Line-at-a-time vocabulary + staging-shape scan. A pre-declared
        ``feature_dimension`` skips the vocabulary collection (identity
        map), exactly like build_index_map."""
        from photon_ml_tpu.io.libsvm import parse_libsvm_line
        from photon_ml_tpu.io.streaming import StreamStats

        files = self.stream_files(paths)
        collect_keys = index_map is None
        keys = set()
        num_rows = 0
        max_live = 0
        for path in files:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    parsed = parse_libsvm_line(
                        line, zero_based=self.zero_based
                    )
                    if parsed is None:
                        continue
                    _label, pairs = parsed
                    live = 0
                    for idx, _v in pairs:
                        key = feature_key(str(idx))
                        if (
                            self.selected is not None
                            and key not in self.selected
                        ):
                            continue
                        if collect_keys and self.feature_dimension is None:
                            keys.add(key)
                        live += 1
                    max_live = max(max_live, live)
                    num_rows += 1
        if collect_keys:
            if self.feature_dimension is not None:
                from photon_ml_tpu.utils.index_map import IdentityIndexMap

                index_map = IdentityIndexMap(
                    self.feature_dimension, add_intercept=self.add_intercept
                )
            else:
                index_map = IndexMap.build(
                    iter(keys), add_intercept=self.add_intercept
                )
        max_nnz = max(max_live + (1 if self.add_intercept else 0), 1)
        return index_map, StreamStats(num_rows=num_rows, max_nnz=max_nnz)


def create_input_format(kind: str, **kwargs):
    """InputFormatFactory analog: kind in {AVRO, LIBSVM}."""
    k = kind.strip().upper()
    if k == "AVRO":
        return AvroInputDataFormat(**kwargs)
    if k == "LIBSVM":
        return LibSVMInputDataFormat(**kwargs)
    raise ValueError(f"unknown input format: {kind}")
