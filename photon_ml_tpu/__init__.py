"""photon-ml-tpu: a TPU-native (JAX/XLA/pjit) framework with the capabilities
of LinkedIn's Photon ML (large-scale GLM + GAME/GLMix training).

The compute/communication layer is JAX on TPU instead of Spark RDDs:

- sparse example batches are statically-shaped, device-sharded arrays
  (``photon_ml_tpu.data.batch``),
- the map-reduce gradient/Hessian "aggregators" of the reference
  (reference: photon-ml .../function/ValueAndGradientAggregator.scala) are
  fused jit kernels reduced with ``jax.lax.psum`` over the mesh
  (``photon_ml_tpu.ops.objective``, ``photon_ml_tpu.parallel``),
- LBFGS/OWLQN/TRON are ``lax.while_loop`` programs, vmap-able for the
  per-entity random-effect solves (``photon_ml_tpu.optim``),
- GAME coordinate descent keeps residual scores device-resident
  (``photon_ml_tpu.game``).
"""

def _install_jax_compat() -> None:
    """Bridge older jax releases where ``shard_map`` still lives in
    ``jax.experimental`` under the pre-rename ``check_rep`` kwarg: the
    codebase imports ``from jax import shard_map`` and passes
    ``check_vma=...`` (the current API). No-op on current jax."""
    import jax

    if hasattr(jax, "shard_map"):
        return
    import functools
    import inspect

    from jax.experimental.shard_map import shard_map as _shard_map

    if "check_vma" in inspect.signature(_shard_map).parameters:
        jax.shard_map = _shard_map
        return

    @functools.wraps(_shard_map)
    def shard_map(f, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, **kwargs)

    jax.shard_map = shard_map


_install_jax_compat()

from photon_ml_tpu.task import TaskType

__version__ = "0.1.0"
__all__ = ["TaskType", "__version__"]
