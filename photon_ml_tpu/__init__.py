"""photon-ml-tpu: a TPU-native (JAX/XLA/pjit) framework with the capabilities
of LinkedIn's Photon ML (large-scale GLM + GAME/GLMix training).

The compute/communication layer is JAX on TPU instead of Spark RDDs:

- sparse example batches are statically-shaped, device-sharded arrays
  (``photon_ml_tpu.data.batch``),
- the map-reduce gradient/Hessian "aggregators" of the reference
  (reference: photon-ml .../function/ValueAndGradientAggregator.scala) are
  fused jit kernels reduced with ``jax.lax.psum`` over the mesh
  (``photon_ml_tpu.ops.objective``, ``photon_ml_tpu.parallel``),
- LBFGS/OWLQN/TRON are ``lax.while_loop`` programs, vmap-able for the
  per-entity random-effect solves (``photon_ml_tpu.optim``),
- GAME coordinate descent keeps residual scores device-resident
  (``photon_ml_tpu.game``).
"""

from photon_ml_tpu.task import TaskType

__version__ = "0.1.0"
__all__ = ["TaskType", "__version__"]
