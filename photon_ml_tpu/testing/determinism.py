"""Hash-seed twin-run reproducibility harness — the runtime twin of the
lint determinism pass (PL015-PL018).

Static analysis proves no unordered iteration or ambient entropy REACHES
an artifact writer; this harness proves the composition: it executes the
same artifact-producing target in two fresh subprocesses under different
``PYTHONHASHSEED`` values (plus a perturbed ``TZ`` — the classic second
channel for "works on my box" artifacts), then byte-diffs the produced
trees. A divergence names the first differing file and byte offset, so
the offending writer is attributable from the gate log alone.

Why subprocesses and not ``sys.flags``: hash randomization is fixed at
interpreter startup — the ONLY way to run the same code under two seeds
is two interpreters. The child entry is this module's ``__main__``
(``python -m photon_ml_tpu.testing.determinism --target <name> --out
<dir>``); targets live in :mod:`determinism_targets`, one per artifact
class the package ships.

``dev-scripts/determinism.sh`` runs the full matrix as a chaos-style
gate: every artifact class twin-run, nonzero exit on any divergence.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "TwinRunError",
    "TwinRunResult",
    "byte_diff_trees",
    "run_matrix",
    "run_target",
    "stable_seed",
    "twin_run",
]

# Repo root (the directory holding photon_ml_tpu/): children need the
# package importable regardless of the caller's cwd.
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# The two child environments. Different PYTHONHASHSEED is the point of
# the exercise; different TZ flushes out localtime-formatted timestamps
# that happen to agree when both runs share a zone. Kiritimati (UTC+14)
# maximizes the calendar distance from UTC — even the DATE differs for
# more than half of every day.
DEFAULT_SEEDS: Tuple[str, str] = ("0", "4242")
DEFAULT_TZS: Tuple[str, str] = ("UTC", "Pacific/Kiritimati")


def stable_seed(*parts: object) -> int:
    """A process-stable seed from the parts' text: crc32, NOT the
    builtin ``hash()`` (which is PYTHONHASHSEED-randomized — the exact
    defect class this harness exists to catch)."""
    text = ":".join(str(p) for p in parts)
    return zlib.crc32(text.encode("utf-8"))


class TwinRunError(RuntimeError):
    """A child run FAILED (nonzero exit) — distinct from a divergence,
    which is a successful run pair producing different bytes."""


@dataclass(frozen=True)
class TwinRunResult:
    target: str
    identical: bool
    divergence: Optional[str]  # None when identical
    seeds: Tuple[str, str]
    runtime_s: float

    def to_dict(self) -> Dict[str, object]:
        return {
            "target": self.target,
            "identical": self.identical,
            "divergence": self.divergence,
            "seeds": list(self.seeds),
            "runtime_s": round(self.runtime_s, 3),
        }


# -- tree comparison ----------------------------------------------------------


def _tree_files(root: str) -> Dict[str, str]:
    """relpath -> abspath for every file under root (sorted walk)."""
    out: Dict[str, str] = {}
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for name in sorted(filenames):
            path = os.path.join(dirpath, name)
            out[os.path.relpath(path, root)] = path
    return out


def byte_diff_trees(a: str, b: str) -> Optional[str]:
    """None when the two trees are bitwise identical; else a message
    naming the FIRST divergence (missing file or first differing byte
    offset) — the attribution a gate log needs."""
    fa, fb = _tree_files(a), _tree_files(b)
    only_a = sorted(set(fa) - set(fb))
    only_b = sorted(set(fb) - set(fa))
    if only_a:
        return f"{only_a[0]}: present only in the first run"
    if only_b:
        return f"{only_b[0]}: present only in the second run"
    for rel in sorted(fa):
        with open(fa[rel], "rb") as fh:
            ba = fh.read()
        with open(fb[rel], "rb") as fh:
            bb = fh.read()
        if ba == bb:
            continue
        off = next(
            (i for i, (x, y) in enumerate(zip(ba, bb)) if x != y),
            min(len(ba), len(bb)),
        )
        return (
            f"{rel}: first byte divergence at offset {off} "
            f"({len(ba)} vs {len(bb)} bytes)"
        )
    return None


# -- the twin run -------------------------------------------------------------


def _child_env(seed: str, tz: str) -> Dict[str, str]:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = seed
    env["TZ"] = tz
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = (
        _REPO_ROOT + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH")
        else _REPO_ROOT
    )
    return env


def twin_run(
    target: str,
    *,
    base_dir: str,
    seeds: Sequence[str] = DEFAULT_SEEDS,
    tzs: Sequence[str] = DEFAULT_TZS,
    timeout_s: float = 300.0,
) -> TwinRunResult:
    """Run ``target`` in two subprocesses under ``seeds[i]``/``tzs[i]``
    and byte-diff the output trees. Raises :class:`TwinRunError` when a
    child FAILS; a divergence is a normal (identical=False) result."""
    if len(seeds) != 2 or len(tzs) != 2:
        raise ValueError("twin_run needs exactly two seeds and two TZs")
    t0 = time.perf_counter()
    out_dirs: List[str] = []
    for i, (seed, tz) in enumerate(zip(seeds, tzs)):
        out = os.path.join(base_dir, f"{target}.run{i}")
        os.makedirs(out, exist_ok=True)
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "photon_ml_tpu.testing.determinism",
                "--target",
                target,
                "--out",
                out,
            ],
            env=_child_env(seed, tz),
            cwd=_REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        if proc.returncode != 0:
            tail = (proc.stderr or proc.stdout or "").strip().splitlines()
            raise TwinRunError(
                f"{target} child (PYTHONHASHSEED={seed}, TZ={tz}) exited "
                f"{proc.returncode}: {' | '.join(tail[-3:])}"
            )
        out_dirs.append(out)
    divergence = byte_diff_trees(out_dirs[0], out_dirs[1])
    return TwinRunResult(
        target=target,
        identical=divergence is None,
        divergence=divergence,
        seeds=(str(seeds[0]), str(seeds[1])),
        runtime_s=time.perf_counter() - t0,
    )


def run_target(name: str, out_dir: str) -> None:
    """In-process dispatch to one artifact target (the child entry and
    the unit tests both route through here)."""
    from photon_ml_tpu.testing import determinism_targets as dt

    fn = dt.ALL_TARGETS.get(name)
    if fn is None:
        known = ", ".join(sorted(dt.ALL_TARGETS))
        raise KeyError(f"unknown determinism target {name!r} (known: {known})")
    os.makedirs(out_dir, exist_ok=True)
    fn(out_dir)


# -- the gate matrix ----------------------------------------------------------


def run_matrix(
    base_dir: str,
    *,
    targets: Optional[Sequence[str]] = None,
    report_path: Optional[str] = None,
) -> Dict[str, object]:
    """Twin-run every artifact class; returns (and optionally writes)
    the gate report: per-class identical/divergence/runtime plus the
    overall verdict. The shell gate exits nonzero on ``ok == False``."""
    from photon_ml_tpu.testing import determinism_targets as dt

    names = list(targets) if targets is not None else sorted(dt.TARGETS)
    t0 = time.perf_counter()
    classes: Dict[str, object] = {}
    ok = True
    for name in names:
        result = twin_run(name, base_dir=base_dir)
        classes[name] = result.to_dict()
        ok = ok and result.identical
    report: Dict[str, object] = {
        "ok": ok,
        "classes": classes,
        "seeds": list(DEFAULT_SEEDS),
        "tzs": list(DEFAULT_TZS),
        "runtime_s": round(time.perf_counter() - t0, 3),
    }
    if report_path is not None:
        from photon_ml_tpu.reliability import atomic_write_json

        atomic_write_json(report_path, report)
    return report


def _main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.testing.determinism",
        description=(
            "Twin-run determinism harness: --target/--out runs ONE "
            "artifact target in-process (the child mode twin_run "
            "spawns); --matrix twin-runs every artifact class and "
            "exits nonzero on any byte divergence."
        ),
    )
    ap.add_argument("--target", help="artifact target name (child mode)")
    ap.add_argument("--out", help="output directory")
    ap.add_argument(
        "--matrix",
        action="store_true",
        help="run the full twin-run matrix over every artifact class",
    )
    ap.add_argument(
        "--report",
        help="with --matrix: write the gate report JSON here",
    )
    args = ap.parse_args(argv)
    if args.matrix:
        if not args.out:
            ap.error("--matrix requires --out")
        report = run_matrix(args.out, report_path=args.report)
        for name in sorted(report["classes"]):
            entry = report["classes"][name]
            verdict = (
                "byte-identical"
                if entry["identical"]
                else f"DIVERGED: {entry['divergence']}"
            )
            print(
                f"determinism[{name}]: {verdict} "
                f"({entry['runtime_s']:.2f}s)"
            )
        print(
            "determinism matrix: "
            + ("OK" if report["ok"] else "DIVERGENCE")
            + f" ({report['runtime_s']:.2f}s, {len(report['classes'])} "
            f"classes, seeds {'/'.join(report['seeds'])})"
        )
        return 0 if report["ok"] else 1
    if not args.target or not args.out:
        ap.error("child mode requires --target and --out")
    run_target(args.target, args.out)
    return 0


if __name__ == "__main__":
    sys.exit(_main())
