"""Artifact targets for the twin-run determinism harness.

One callable per artifact CLASS the package ships, each driving the
real production writer (never a test-only reimplementation) with fixed
inputs that deliberately flow through hash-ordered containers — so a
writer that forgets to sort diverges under the harness's twin
``PYTHONHASHSEED`` runs. The classes:

- ``metrics_json``     — the run-summary/metrics JSON family
  (``reliability.atomic_write_json``)
- ``wire_frames``      — one frame of every photon-wire message family
  (MSG_JSON, score request/response, partial response, trace response)
- ``registry_publish`` — a full registry publish: staged model copy,
  manifest, content signature, COMMIT marker
- ``avro_container``   — an Avro object container (deterministic sync
  marker contract from ``io.avro_codec``)
- ``sharding_md``      — the SPMD contract inventory renderer over a
  fixed synthetic source tree
- ``fleet_trace``      — the merged fleet timeline
  (``obs.fleet.export_fleet_trace``) over fixed stitched spans

``CONTROL_TARGETS`` holds the harness's positive control: a writer that
is hash-order dependent ON PURPOSE. It must DIVERGE under the twin run
— a harness that passes it is broken. It is excluded from the gate
matrix (``TARGETS``) for exactly that reason.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict

__all__ = ["ALL_TARGETS", "CONTROL_TARGETS", "TARGETS"]


def target_metrics_json(out: str) -> None:
    from photon_ml_tpu.reliability import atomic_write_json
    from photon_ml_tpu.reliability.retry import reliability_metrics

    # seam names enter as a SET: the writer below only produces stable
    # bytes because the payload sorts them — exactly the discipline
    # PL015 enforces on the production metrics writers
    seams = {"chunk_read", "spill_write", "ckpt_save", "serving.dispatch"}
    payload = {
        "round": {"name": "determinism-harness", "artifact": "metrics"},
        "seams": sorted(seams),
        "reliability": reliability_metrics(),
    }
    atomic_write_json(os.path.join(out, "metrics.json"), payload)


class _FixedPartial:
    """The two-method surface ``wire.append_response`` needs from a
    PartialScore carrier, with fixed values."""

    fe = 0.5

    def term_vector(self):
        import numpy as np

        return ["geo:us", "item:42"], np.asarray(
            [0.25, -0.75], dtype="<f4"
        )


def target_wire_frames(out: str) -> None:
    from photon_ml_tpu.serving import wire

    buf = bytearray()
    # MSG_JSON: shard names enter as a set, sorted at the seam
    wire.append_json(
        buf,
        {"op": "status", "shards": sorted({"shard-1", "shard-0"})},
    )
    # MSG_SCORE_REQUEST: a columnar bag + scalar fields
    wire.append_score_request(
        buf,
        {
            "uid": 7,
            "features": [
                {"name": "f0", "term": "", "value": 1.5},
                {"name": "f1", "term": "t", "value": -2.25},
            ],
        },
    )
    # MSG_SCORE_RESPONSE
    wire.append_response(
        buf, {"status": "ok", "uid": 7, "score": 0.125}
    )
    # MSG_PARTIAL_RESPONSE
    wire.append_response(
        buf, {"status": "ok", "uid": 8, "_wire_partial": _FixedPartial()}
    )
    # MSG_TRACE_RESPONSE, one finished + one unfinished span
    wire.append_response(
        buf,
        {
            "op": "trace",
            "status": "ok",
            "spans": [
                {
                    "name": "serving.score",
                    "trace_id": "t1",
                    "span_id": "s1",
                    "parent_id": None,
                    "t0": 1.0,
                    "t1": 1.5,
                    "tid": 3,
                    "seq": 1,
                    "attrs": {"generation": 4},
                },
                {
                    "name": "serving.dispatch",
                    "trace_id": "t1",
                    "span_id": "s2",
                    "parent_id": "s1",
                    "t0": 1.1,
                    "t1": None,
                    "tid": 3,
                    "seq": 2,
                    "attrs": {},
                },
            ],
            "cursor": 2,
            "dropped": 0,
            "epoch": [0.0, 0.0],
        },
    )
    from photon_ml_tpu.reliability import atomic_write_bytes

    atomic_write_bytes(os.path.join(out, "frames.bin"), bytes(buf))


def target_registry_publish(out: str) -> None:
    from photon_ml_tpu.registry.registry import ModelRegistry

    from photon_ml_tpu.reliability import atomic_write_json

    src = os.path.join(out, "candidate")
    os.makedirs(src, exist_ok=True)
    atomic_write_json(
        os.path.join(src, "model.json"),
        {"coefficients": [0.1, -0.2, 0.3], "intercept": 0.05},
    )
    reg = ModelRegistry(os.path.join(out, "registry"))
    reg.publish(
        src,
        data_ranges={"train": "2026-01"},
        gate_report={"verdict": "PASS", "checks": ["auc"]},
    )


def target_avro_container(out: str) -> None:
    from photon_ml_tpu.io.avro_codec import write_container

    schema = {
        "type": "record",
        "name": "Pair",
        "fields": [
            {"name": "name", "type": "string"},
            {"name": "value", "type": "double"},
        ],
    }
    records = [{"name": f"f{i}", "value": i * 0.5} for i in range(16)]
    write_container(os.path.join(out, "pairs.avro"), schema, records)


_SHARDING_SRC = '''\
"""Synthetic mesh entry point for the determinism harness."""
import jax


# photon: sharding(axes=[data], in=[data, None], out=[data])
def scatter_scores(mesh, batch, bank):
    with mesh:
        return jax.jit(lambda b: b * 2.0)(batch)
'''


def target_sharding_md(out: str) -> None:
    from photon_ml_tpu.lint.core import FileContext, PackageContext
    from photon_ml_tpu.lint.sharding_contracts import write_sharding_md

    # relative ctx paths: the rendered inventory must not embed the
    # (run-unique) output directory, or the twin diff is trivially noise
    ctx = FileContext("harness_mod.py", _SHARDING_SRC)
    write_sharding_md(
        os.path.join(out, "SHARDING.md"), PackageContext([ctx])
    )


def target_fleet_trace(out: str) -> None:
    from photon_ml_tpu.obs.fleet import export_fleet_trace

    stitched = [
        {
            "name": "serving.score",
            "trace_id": "t9",
            "span_id": "shard-0.s1",
            "parent_id": None,
            "t0": 10.0,
            "t1": 10.5,
            "tid": 1,
            "seq": 1,
            "member": "shard-0",
            "attrs": {"generation": 2},
        },
        {
            "name": "serving.dispatch",
            "trace_id": "t9",
            "span_id": "shard-1.s1",
            "parent_id": "shard-0.s1",
            "t0": 10.1,
            "t1": 10.4,
            "tid": 2,
            "seq": 1,
            "member": "shard-1",
            "attrs": {},
        },
    ]
    member_status = {
        "shard-0": {"polls": 3, "offset_s": 0.0},
        "shard-1": {"polls": 3, "offset_s": 0.001},
    }
    export_fleet_trace(
        os.path.join(out, "fleet_trace.json"),
        stitched,
        member_status=member_status,
        extra={"round": "determinism-harness"},
    )


def control_hash_order(out: str) -> None:
    """POSITIVE CONTROL — intentionally hash-order dependent: string
    set iteration order follows PYTHONHASHSEED, and nothing here sorts
    it. The harness MUST report this one as diverged; see
    test_determinism_harness.py."""
    keys = {f"key-{i}" for i in range(64)}
    path = os.path.join(out, "control.txt")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        for k in keys:
            fh.write(k + "\n")
    os.replace(tmp, path)


TARGETS: Dict[str, Callable[[str], None]] = {
    "metrics_json": target_metrics_json,
    "wire_frames": target_wire_frames,
    "registry_publish": target_registry_publish,
    "avro_container": target_avro_container,
    "sharding_md": target_sharding_md,
    "fleet_trace": target_fleet_trace,
}

CONTROL_TARGETS: Dict[str, Callable[[str], None]] = {
    "control_hash_order": control_hash_order,
}

ALL_TARGETS: Dict[str, Callable[[str], None]] = {
    **TARGETS,
    **CONTROL_TARGETS,
}
