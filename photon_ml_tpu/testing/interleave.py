"""Deterministic interleaving harness: a seeded cooperative scheduler
for the serving/registry thread plane.

The runtime twin of the PL008-PL010 static rules. The chaos arms can
only sample schedules the OS happens to produce; this harness OWNS the
schedule: it wraps the ``threading`` primitives (Lock, RLock,
Condition, Event, Thread) with cooperative versions that hand control
to a scheduler at every acquisition, wait, notify and spawn — the
deterministic preemption points — and the scheduler picks the next
runnable thread with a seeded RNG. Same seed, same schedule, every
run: a race found once is a regression test forever, and ``explore``
sweeps a seed set so tests can demand "zero invariant violations over
N schedules of submit/close/swap/rollback".

Time is VIRTUAL (discrete-event): a timed wait registers a deadline on
the logical clock, and the clock only advances when every live thread
is blocked — jumping straight to the earliest deadline. Patching
``time.monotonic``/``time.perf_counter`` onto the logical clock makes
production deadline math (submit budgets, heartbeat beats, queue
polls) deterministic too. A schedule where every thread is blocked
with no deadline is reported as :class:`DeadlockError` — the dynamic
complement of PL009's static cycle detection.

Usage::

    sched = InterleaveScheduler(seed=7)
    with sched.patched():          # threading.* / time.* -> cooperative
        batcher = MicroBatcher(...)   # constructed INSIDE the window
        sched.spawn(lambda: batcher.submit(req), name="client")
        sched.spawn(batcher.close, name="closer")
    sched.run()                    # drives to completion, one schedule

Only code that parks on the managed primitives is schedulable; a
managed thread blocking on a REAL socket/file would stall the harness,
so tests drive fakes (``tests/test_interleave.py``).
"""

from __future__ import annotations

import queue as _queue
import random
import threading as _threading
import time as _time
from collections import deque
from contextlib import contextmanager
from typing import Callable, List, Optional, Sequence

__all__ = [
    "DeadlockError",
    "StepBudgetExceeded",
    "InterleaveScheduler",
    "explore",
]

# the raw C-level thread API: the harness's own machinery must not run
# through ``threading.Thread``/``threading.Event``, whose constructors
# resolve the (patched) module globals at call time
import _thread as _raw_thread  # noqa: E402


class _RawGate:
    """Binary handshake gate built directly on the C lock primitive —
    ``threading.Event`` internally calls ``threading.Condition`` at
    CONSTRUCTION time, which would recurse into the patched
    cooperative primitives; the raw lock cannot be patched. ``set``
    releases, ``wait`` acquires (auto-consuming), which is exactly the
    alternating scheduler<->thread lockstep."""

    def __init__(self):
        self._lock = _raw_thread.allocate_lock()
        self._lock.acquire()  # starts "unset"

    def set(self) -> None:
        try:
            self._lock.release()
        except RuntimeError:
            pass  # already set

    def wait(self) -> None:
        self._lock.acquire()


class DeadlockError(AssertionError):
    """Every live thread is blocked and no deadline can unblock one."""


class StepBudgetExceeded(AssertionError):
    """The schedule exceeded max_steps — a livelock or runaway loop."""


class _Task:
    """One managed thread: a real OS thread in lockstep with the
    scheduler (at most one unparked at any instant)."""

    def __init__(self, sched: "InterleaveScheduler", fn: Callable,
                 name: str):
        self.sched = sched
        self.fn = fn
        self.name = name
        self.go = _RawGate()
        self.parked = _RawGate()
        self.started = False
        # single-writer atomic publishes: only the task's own OS
        # thread writes them (plain assignments in _run), the
        # scheduler reads them — the same discipline PL008 enforces on
        # the serving plane, declared the same way
        self.finished = False  # photon: guarded-by(atomic)
        self.error: Optional[BaseException] = None  # photon: guarded-by(atomic)
        # block state, read by the scheduler to compute runnability
        self.block_pred: Optional[Callable[[], bool]] = None
        self.deadline: Optional[float] = None

    def start_os_thread(self) -> None:
        # raw spawn: threading.Thread would build its _started Event
        # through the patched module globals
        _raw_thread.start_new_thread(self._run, ())

    def _run(self) -> None:
        self.go.wait()
        try:
            self.fn()
        except BaseException as e:  # surfaced by run()
            self.error = e
        finally:
            self.finished = True
            self.parked.set()

    def runnable(self, now: float) -> bool:
        if self.finished:
            return False
        if self.block_pred is None:
            return True
        if self.block_pred():
            return True
        return self.deadline is not None and now >= self.deadline


class _CoopLock:
    """Cooperative Lock/RLock. State is plain Python — safe because the
    scheduler never lets two managed threads run at once."""

    def __init__(self, sched: "InterleaveScheduler",
                 reentrant: bool = False):
        self._sched = sched
        self._reentrant = reentrant
        self._owner: Optional[_Task] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sched = self._sched
        t = sched._current()
        sched._preempt()  # schedules may interleave JUST before entry
        if self._owner is t and self._reentrant:
            self._count += 1
            return True
        if self._owner is t and not self._reentrant:
            if not blocking:
                return False  # real Lock semantics: try-acquire fails
            raise RuntimeError(
                f"non-reentrant lock re-acquired by {t.name} — "
                "a guaranteed self-deadlock (PL009's dynamic twin)"
            )
        if self._owner is None:
            self._owner = t
            self._count = 1
            return True
        if not blocking:
            return False
        deadline = (
            None if timeout is None or timeout < 0
            else sched.time() + timeout
        )
        ok = sched._block(lambda: self._owner is None, deadline)
        if not ok:
            return False
        self._owner = t
        self._count = 1
        return True

    def release(self) -> None:
        t = self._sched._current()
        if self._owner is not t:
            raise RuntimeError(f"release of un-owned lock by {t.name}")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            self._sched._preempt()

    def locked(self) -> bool:
        return self._owner is not None

    # release EVERYTHING (Condition.wait on an RLock) and restore
    def _release_save(self):
        owner, count = self._owner, self._count
        self._owner, self._count = None, 0
        return owner, count

    def _acquire_restore(self, state) -> None:
        owner, count = state
        self._sched._block(lambda: self._owner is None, None)
        self._owner, self._count = owner, count

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _CoopCondition:
    def __init__(self, sched: "InterleaveScheduler", lock=None):
        self._sched = sched
        self._lock = lock if lock is not None else _CoopLock(sched)
        self._notified: set = set()
        self._waiters: List[_Task] = []

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        return self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched = self._sched
        t = sched._current()
        if self._lock._owner is not t:
            raise RuntimeError("cannot wait on un-acquired condition")
        deadline = (
            None if timeout is None else sched.time() + float(timeout)
        )
        self._waiters.append(t)
        state = self._lock._release_save()
        sched._block(lambda: t in self._notified, deadline)
        notified = t in self._notified
        self._notified.discard(t)
        if t in self._waiters:
            self._waiters.remove(t)
        self._lock._acquire_restore(state)
        return notified

    def wait_for(self, predicate, timeout: Optional[float] = None):
        sched = self._sched
        endtime = (
            None if timeout is None else sched.time() + float(timeout)
        )
        result = predicate()
        while not result:
            if endtime is not None:
                waittime = endtime - sched.time()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait(None)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        if self._lock._owner is not self._sched._current():
            raise RuntimeError("cannot notify on un-acquired condition")
        for t in self._waiters[:n]:
            self._notified.add(t)
        self._sched._preempt()

    def notify_all(self) -> None:
        self.notify(len(self._waiters))


class _CoopEvent:
    def __init__(self, sched: "InterleaveScheduler"):
        self._sched = sched
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True
        self._sched._preempt()

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched = self._sched
        if self._flag:
            sched._preempt()
            return True
        deadline = (
            None if timeout is None else sched.time() + float(timeout)
        )
        sched._block(lambda: self._flag, deadline)
        return self._flag


class _CoopQueue:
    """queue.Queue stand-in on the virtual clock (the stdlib Queue
    binds ``time.monotonic`` at import, so its timeouts would burn real
    time under the scheduler). Raises the REAL queue.Full/queue.Empty
    so production except-clauses keep working."""

    def __init__(self, sched: "InterleaveScheduler", maxsize: int = 0):
        self._sched = sched
        self.maxsize = int(maxsize)
        self._items: deque = deque()

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return 0 < self.maxsize <= len(self._items)

    def put_nowait(self, item) -> None:
        if self.full():
            raise _queue.Full
        self._items.append(item)
        self._sched._preempt()

    def get_nowait(self):
        if not self._items:
            raise _queue.Empty
        item = self._items.popleft()
        self._sched._preempt()
        return item

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            return self.put_nowait(item)
        deadline = (
            None if timeout is None
            else self._sched.time() + float(timeout)
        )
        ok = self._sched._block(lambda: not self.full(), deadline)
        if not ok:
            raise _queue.Full
        self._items.append(item)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        if not block:
            return self.get_nowait()
        deadline = (
            None if timeout is None
            else self._sched.time() + float(timeout)
        )
        ok = self._sched._block(lambda: bool(self._items), deadline)
        if not ok:
            raise _queue.Empty
        return self._items.popleft()


class _CoopThread:
    """threading.Thread stand-in registering with the scheduler."""

    _counter = 0

    def __init__(self, group=None, target=None, name=None, args=(),
                 kwargs=None, *, daemon=None, sched=None):
        _CoopThread._counter += 1
        self._sched = sched
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        self.name = name or f"coop-{_CoopThread._counter}"
        self.daemon = bool(daemon) if daemon is not None else True
        self._task: Optional[_Task] = None

    def start(self) -> None:
        if self._task is not None:
            raise RuntimeError("threads can only be started once")

        def body():
            if self._target is not None:
                self._target(*self._args, **self._kwargs)

        self._task = self._sched.spawn(body, name=self.name)

    def join(self, timeout: Optional[float] = None) -> None:
        task = self._task
        if task is None:
            raise RuntimeError("cannot join un-started thread")
        deadline = (
            None
            if timeout is None
            else self._sched.time() + float(timeout)
        )
        self._sched._block(lambda: task.finished, deadline)

    def is_alive(self) -> bool:
        return self._task is not None and not self._task.finished


class InterleaveScheduler:
    """The seeded cooperative scheduler. One instance = one replayable
    schedule universe; ``seed`` fully determines every pick."""

    def __init__(self, seed: int = 0, max_steps: int = 200_000,
                 tick_quantum: float = 0.05):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.max_steps = int(max_steps)
        # bound on how far ONE scheduled <tick> may advance the clock:
        # timeouts race runnable threads (that is the point of the
        # tick), but an unbounded jump to some far-future deadline
        # would warp past every intermediate moment a runnable thread
        # was about to create (its next sleep/wait deadline), gutting
        # the scenario's relative timing
        self.tick_quantum = float(tick_quantum)
        self.steps = 0
        self._now = 1000.0  # virtual; arbitrary epoch
        self._tasks: List[_Task] = []
        self._running: Optional[_Task] = None
        self._started = False
        self.trace: List[str] = []  # thread names, in schedule order
        # pseudo-task identity for UNMANAGED callers (construction-time
        # code on the test's own thread, e.g. Future.set_result inside
        # the patch window): they may own cooperative locks but never
        # park — their blocking resolves immediately against current
        # state (construction is single-threaded by contract)
        self._main = _Task(self, lambda: None, "<main>")

    # -- public surface ------------------------------------------------------

    def time(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        deadline = self._now + max(float(seconds), 0.0)
        self._block(lambda: False, deadline)

    def spawn(self, fn: Callable, name: Optional[str] = None) -> _Task:
        task = _Task(self, fn, name or f"task-{len(self._tasks)}")
        self._tasks.append(task)
        task.start_os_thread()
        task.started = True
        return task

    def Lock(self) -> _CoopLock:
        return _CoopLock(self)

    def RLock(self) -> _CoopLock:
        return _CoopLock(self, reentrant=True)

    def Condition(self, lock=None) -> _CoopCondition:
        return _CoopCondition(self, lock)

    def Event(self) -> _CoopEvent:
        return _CoopEvent(self)

    def Thread(self, *a, **kw) -> _CoopThread:
        return _CoopThread(*a, sched=self, **kw)

    def Queue(self, maxsize: int = 0) -> _CoopQueue:
        return _CoopQueue(self, maxsize)

    @contextmanager
    def patched(self):
        """Swap ``threading``/``time`` module attributes for the
        cooperative versions, so production classes CONSTRUCTED inside
        the window (and the stdlib ``queue`` built on them) run on this
        scheduler. Construction only registers state — drive the
        schedule with :meth:`run` after the window closes (or inside;
        both work, patches are restored either way)."""
        saved = {
            "Lock": _threading.Lock,
            "RLock": _threading.RLock,
            "Condition": _threading.Condition,
            "Event": _threading.Event,
            "Thread": _threading.Thread,
        }
        saved_time = {
            "monotonic": _time.monotonic,
            "perf_counter": _time.perf_counter,
            "sleep": _time.sleep,
        }
        saved_queue = _queue.Queue
        _threading.Lock = self.Lock
        _threading.RLock = self.RLock
        _threading.Condition = self.Condition
        _threading.Event = self.Event
        _threading.Thread = self.Thread
        _time.monotonic = self.time
        _time.perf_counter = self.time
        _time.sleep = self.sleep
        _queue.Queue = self.Queue
        try:
            yield self
        finally:
            for k, v in saved.items():
                setattr(_threading, k, v)
            for k, v in saved_time.items():
                setattr(_time, k, v)
            _queue.Queue = saved_queue

    def run(self, until: Optional[Callable[[], bool]] = None) -> None:
        """Drive the schedule until every task finishes (or ``until``
        returns True). Raises the first task exception, DeadlockError
        when no task can ever run again, StepBudgetExceeded past the
        step budget."""
        self._started = True
        while True:
            live = [t for t in self._tasks if not t.finished]
            if not live:
                break
            if until is not None and until():
                break
            runnable = [t for t in live if t.runnable(self._now)]
            # deadlines of threads that are NOT yet runnable: firing a
            # timeout is itself a schedulable event — real timeouts
            # race running threads, so the virtual clock may jump even
            # while work is runnable (this is what makes e.g. a poll
            # loop's drain check interleave into another thread's
            # two-step update)
            pending_deadlines = [
                t.deadline for t in live
                if t.deadline is not None and not t.runnable(self._now)
            ]
            if not runnable:
                if not pending_deadlines:
                    blocked = ", ".join(t.name for t in live)
                    raise DeadlockError(
                        f"seed {self.seed}: all threads blocked with no "
                        f"deadline — deadlock among [{blocked}] after "
                        f"{self.steps} step(s); trace tail: "
                        f"{self.trace[-12:]}"
                    )
                self._now = min(pending_deadlines)
                continue
            self.steps += 1
            if self.steps > self.max_steps:
                raise StepBudgetExceeded(
                    f"seed {self.seed}: {self.steps} scheduler steps "
                    "without completion — livelock or runaway loop; "
                    f"trace tail: {self.trace[-12:]}"
                )
            choices: List = sorted(runnable, key=lambda t: t.name)
            if pending_deadlines:
                choices.append(None)  # None = fire the next timeout
            task = self.rng.choice(choices)
            if task is None:
                # advance toward (at most quantum; exactly onto when
                # imminent) the earliest pending deadline
                self._now = min(
                    self._now + self.tick_quantum,
                    min(pending_deadlines),
                )
                self.trace.append("<tick>")
                continue
            self.trace.append(task.name)
            self._resume(task)
        for t in self._tasks:
            if t.error is not None:
                raise t.error

    # -- scheduler internals -------------------------------------------------

    def _current(self) -> _Task:
        cur = self._running
        if cur is None:
            return self._main  # unmanaged (construction-time) caller
        return cur

    def _resume(self, task: _Task) -> None:
        task.block_pred = None
        task.deadline = None
        self._running = task
        task.go.set()
        task.parked.wait()  # auto-consumes: gate is reset by the wait
        self._running = None

    def _park(self, task: _Task) -> None:
        """Called ON the task's thread: hand control back, wait to be
        rescheduled."""
        task.parked.set()
        task.go.wait()  # auto-consumes

    def _preempt(self) -> None:
        """A deterministic preemption point: the running thread offers
        the scheduler a chance to run someone else."""
        task = self._running
        if task is None:
            return  # outside a managed thread (construction time)
        self._park(task)

    def _block(self, predicate: Callable[[], bool],
               deadline: Optional[float]) -> bool:
        """Park until ``predicate()`` or the virtual deadline. Returns
        the predicate's final verdict (False = timed out)."""
        task = self._running
        if task is None:
            # construction-time call (e.g. Event.wait before run());
            # resolve immediately against current state
            return bool(predicate())
        while True:
            if predicate():
                return True
            if deadline is not None and self._now >= deadline:
                return False
            task.block_pred = predicate
            task.deadline = deadline
            self._park(task)
            task.block_pred = None
            task.deadline = None


def explore(
    scenario: Callable[[InterleaveScheduler], Optional[Callable]],
    seeds: Sequence[int] = range(20),
    max_steps: int = 200_000,
) -> List[int]:
    """Run ``scenario`` once per seed. The scenario receives a fresh
    scheduler, builds its world (typically inside ``sched.patched()``),
    spawns threads, and may return a verifier callable that runs after
    the schedule completes. Returns the list of seeds driven; raises
    AssertionError naming every failing seed (each independently
    replayable)."""
    failures: List[str] = []
    for seed in seeds:
        sched = InterleaveScheduler(seed=seed, max_steps=max_steps)
        try:
            verify = scenario(sched)
            sched.run()
            if verify is not None:
                verify()
        except BaseException as e:
            failures.append(f"seed {seed}: {type(e).__name__}: {e}")
    if failures:
        raise AssertionError(
            f"{len(failures)}/{len(list(seeds))} schedule(s) violated "
            "invariants:\n" + "\n".join(failures[:10])
        )
    return list(seeds)
