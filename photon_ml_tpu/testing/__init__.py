"""Test-support runtime: the deterministic interleaving harness and the
hash-seed twin-run reproducibility harness.

Importable from production-adjacent test code and dev-scripts; never
imported by the serving/registry modules themselves.
"""

from photon_ml_tpu.testing.determinism import (
    TwinRunError,
    TwinRunResult,
    byte_diff_trees,
    run_matrix,
    run_target,
    stable_seed,
    twin_run,
)
from photon_ml_tpu.testing.interleave import (
    DeadlockError,
    InterleaveScheduler,
    StepBudgetExceeded,
    explore,
)

__all__ = [
    "DeadlockError",
    "InterleaveScheduler",
    "StepBudgetExceeded",
    "TwinRunError",
    "TwinRunResult",
    "byte_diff_trees",
    "explore",
    "run_matrix",
    "run_target",
    "stable_seed",
    "twin_run",
]
