"""Test-support runtime: the deterministic interleaving harness.

Importable from production-adjacent test code and dev-scripts; never
imported by the serving/registry modules themselves.
"""

from photon_ml_tpu.testing.interleave import (
    DeadlockError,
    InterleaveScheduler,
    StepBudgetExceeded,
    explore,
)

__all__ = [
    "DeadlockError",
    "InterleaveScheduler",
    "StepBudgetExceeded",
    "explore",
]
