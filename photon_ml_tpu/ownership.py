"""The ONE entity-ownership rule, shared by every plane that places
entities on shards.

Photon's entity sharding is everywhere: the pod trainer places bank
rows (``game/pod.py``), the in-jit shuffle routes rows to owners
(``parallel/shuffle.py``), the residual router builds its slot tables
(``game/residual_routing.py``), the serving loader keeps one shard of
a model (``serving/model_bank.py``), and the scatter/gather routing
tier (``serving/routing.py``) decides which shard-server answers for a
request's entities. All of them MUST agree, or a trained coefficient
silently serves from the wrong host — so the rule lives here, once:

- **owner**:     entity code ``e`` lives on shard ``e % num_shards``
  (the LongHashPartitioner analog — stable, stateless, balanced for
  hashed ids, and new entities never re-home old ones);
- **local row**: within its shard, ``e`` sits at local row
  ``e // num_shards``;
- **id lists**:  for a SORTED entity-id list (the model artifact
  layout), an id's code is its position, so shard ``s`` keeps exactly
  the ids at positions ``s, s + n, s + 2n, …``.

Everything is plain arithmetic so the same functions serve Python
ints, numpy arrays and traced jax values alike (the shuffle/pod call
sites run inside ``jit``/``shard_map``).

``tests/test_ownership.py`` pins the agreement property: for random
entity codes, the pod placement, the shuffle owner computation and the
serving shard split select identical shards.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

__all__ = [
    "owner_of",
    "local_row_of",
    "rows_per_shard",
    "sharded_row_of",
    "validate_entity_shard",
    "owned_positions",
    "shard_entity_ids",
    "OWNERSHIP_RULE",
]

# the human/wire description, published by shard-server topology blocks
# so operators (and the router) can verify the deployed rule
OWNERSHIP_RULE = "entity_code % num_shards"


def owner_of(codes, num_shards: int):
    """Entity code -> owning shard (``e % n``). ``codes`` may be a
    Python int, a numpy array or a traced jax value — plain modulo, no
    dtype coercion, so in-jit call sites stay traceable."""
    return codes % num_shards


def local_row_of(codes, num_shards: int):
    """Entity code -> local bank row on its owning shard (``e // n``)."""
    return codes // num_shards


def rows_per_shard(num_entities: int, num_shards: int) -> int:
    """Local bank rows per shard (ceil division, >= 1 so empty banks
    stay valid device shapes)."""
    return -(-max(int(num_entities), 1) // int(num_shards))


def sharded_row_of(codes, num_shards: int, rows_per_shard: int):
    """Entity code -> row in the concatenated ``[n * E_loc, d]`` pod
    bank layout: shard-major, local-row-minor."""
    return owner_of(codes, num_shards) * rows_per_shard + local_row_of(
        codes, num_shards
    )


def validate_entity_shard(
    entity_shard: Optional[Tuple[int, int]]
) -> Optional[Tuple[int, int]]:
    """Normalize/validate an ``(shard_index, num_shards)`` pair (None
    passes through: "all entities")."""
    if entity_shard is None:
        return None
    s, n = entity_shard
    if not (isinstance(n, int) and n >= 1 and isinstance(s, int)
            and 0 <= s < n):
        raise ValueError(
            f"entity_shard must be (shard, num_shards) with "
            f"0 <= shard < num_shards, got {entity_shard!r}"
        )
    return (int(s), int(n))


def owned_positions(num_ids: int, shard: int, num_shards: int) -> range:
    """Positions of shard ``shard``'s entities in a sorted id list of
    length ``num_ids`` (position == entity code for artifact layouts)."""
    return range(int(shard), int(num_ids), int(num_shards))


def shard_entity_ids(
    ids: Sequence[str], entity_shard: Optional[Tuple[int, int]]
) -> List[str]:
    """One entity SHARD of a sorted entity-id list: an id's code is its
    position in the model's sorted order, and its owner is
    ``code % num_shards`` — identical to the training-side pod bank
    placement, so a server loading shard ``s`` of a pod-trained model
    holds exactly the rows device ``s`` trained. ``entity_shard`` is
    ``(shard_index, num_shards)`` or None (keep all)."""
    shard = validate_entity_shard(entity_shard)
    if shard is None:
        return list(ids)
    s, n = shard
    ids = list(ids)
    return [ids[i] for i in owned_positions(len(ids), s, n)]
