"""GAME model classes.

Reference: photon-ml .../model/GAMEModel.scala:93-95 (Map[coordinateName ->
DatumScoringModel], score = sum of submodel scores), FixedEffectModel.scala
:29-104 (Broadcast[GLM] + featureShardId), RandomEffectModel.scala:126-168
(RDD[(entityId, GLM)] scored via join), RandomEffectModelInProjectedSpace
.scala, MatrixFactorizationModel.scala:141-178 (double-cogroup latent
scoring), DatumScoringModel.scala.

TPU-native: every model scores a GameDataset into a row-aligned [n] array;
the RDD-of-models becomes a dense [E, D] coefficient bank; the MF cogroup
becomes two row gathers + a dot.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import jax.numpy as jnp

from photon_ml_tpu.game.data import GameDataset
from photon_ml_tpu.game.random_effect import score_random_effect
from photon_ml_tpu.game.random_effect_data import RandomEffectDataset
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.task import TaskType

Array = jnp.ndarray


class DatumScoringModel:
    """score(dataset) -> row-aligned [n] raw scores (no offsets)."""

    def score(self, dataset: GameDataset) -> Array:  # pragma: no cover
        raise NotImplementedError


@dataclass
class FixedEffectModel(DatumScoringModel):
    """Global GLM over one feature shard (FixedEffectModel.scala)."""

    model: GeneralizedLinearModel
    feature_shard_id: str

    def score(self, dataset: GameDataset) -> Array:
        batch = dataset.batch_for_shard(self.feature_shard_id)
        return self.model.score(batch)


@dataclass
class RandomEffectModel(DatumScoringModel):
    """Per-entity coefficient bank [E, D] over a local projection
    (RandomEffectModel + RandomEffectModelInProjectedSpace)."""

    bank: Array  # [E, D]
    re_dataset: RandomEffectDataset
    random_effect_type: str
    feature_shard_id: str
    # per-entity coefficient variances [E, D], populated when the problem
    # runs with compute_variances (isComputingVariance analog)
    variances: Optional[Array] = None

    def score(self, dataset: GameDataset) -> Array:
        # The bank's projection is tied to re_dataset; scoring another
        # dataset requires a re-projected view built by the data layer.
        return score_random_effect(self.bank, self.re_dataset)

    def score_rows(self, re_view: RandomEffectDataset) -> Array:
        return score_random_effect(self.bank, re_view)


@dataclass
class MatrixFactorizationModel(DatumScoringModel):
    """score_i = rowLatent[rowId_i] . colLatent[colId_i]
    (MatrixFactorizationModel.scala:141-178)."""

    row_effect_type: str
    col_effect_type: str
    row_latent: Array  # [R, K]
    col_latent: Array  # [C, K]

    @property
    def num_latent_factors(self) -> int:
        return self.row_latent.shape[1]

    def score(self, dataset: GameDataset) -> Array:
        rows = dataset.entity_codes[self.row_effect_type]
        cols = dataset.entity_codes[self.col_effect_type]
        valid = jnp.asarray((rows >= 0) & (cols >= 0))
        r = jnp.take(self.row_latent, jnp.maximum(jnp.asarray(rows), 0), axis=0)
        c = jnp.take(self.col_latent, jnp.maximum(jnp.asarray(cols), 0), axis=0)
        return jnp.where(valid, jnp.sum(r * c, axis=-1), 0.0)


@dataclass
class GameModel:
    """Ordered coordinate name -> submodel; total score = sum
    (GAMEModel.scala:93-95)."""

    models: Dict[str, DatumScoringModel] = field(default_factory=dict)
    task: TaskType = TaskType.LOGISTIC_REGRESSION

    def get_model(self, name: str) -> Optional[DatumScoringModel]:
        return self.models.get(name)

    def update_model(self, name: str, model: DatumScoringModel) -> "GameModel":
        new = dict(self.models)
        new[name] = model
        return GameModel(new, self.task)

    def score(self, dataset: GameDataset) -> Array:
        total = jnp.zeros((dataset.num_rows,), jnp.float32)
        for m in self.models.values():
            total = total + m.score(dataset)
        return total
