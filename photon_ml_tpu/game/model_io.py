"""GAME model persistence with reference directory-layout parity.

Reference: photon-ml .../avro/model/ModelProcessingUtils.scala:44-189 and
avro/Constants.scala:22-25 —

    <dir>/fixed-effect/<coordinate>/id-info            (feature shard id)
    <dir>/fixed-effect/<coordinate>/coefficients/part-00000.avro
    <dir>/random-effect/<coordinate>/id-info           (reType, shardId)
    <dir>/random-effect/<coordinate>/coefficients/part-00000.avro
    <dir>/matrix-factorization/<coordinate>/{row,col}-latent/part-00000.avro
    <dir>/model-spec                                   (human-readable)

Fixed-effect coefficients: ONE BayesianLinearModelAvro (modelId
"fixed-effect"); random-effect: one record PER ENTITY (modelId = raw
entity id); MF latent factors as LatentFactorAvro. Files written by the
reference load here and vice versa (same schemas + layout).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.game.data import GameDataset
from photon_ml_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    MatrixFactorizationModel,
    RandomEffectModel,
)
from photon_ml_tpu.game.coordinate import FactoredRandomEffectModel
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import read_avro_records, write_container
from photon_ml_tpu.io.model_io import model_to_bayesian_avro
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import create_model
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.utils.index_map import split_feature_key

FIXED_EFFECT = "fixed-effect"
RANDOM_EFFECT = "random-effect"
MATRIX_FACTORIZATION = "matrix-factorization"
ID_INFO = "id-info"
COEFFICIENTS = "coefficients"


def _write_lines(path: str, lines: List[str]) -> None:
    from photon_ml_tpu.reliability.artifacts import atomic_writer

    os.makedirs(os.path.dirname(path), exist_ok=True)
    with atomic_writer(path) as f:
        f.write("\n".join(lines) + "\n")


def _write_parts(base: str, schema, records, num_files: int) -> None:
    """Split records across ``num_files`` container part files
    (numberOfOutputFilesForRandomEffectModel,
    avro/model/ModelProcessingUtils.scala save path; <=0 means one
    file). The loader reads the whole directory, so the split is
    transparent on read."""
    n = max(1, num_files)
    chunks = [records[i::n] for i in range(n)] if n > 1 else [records]
    for i, chunk in enumerate(chunks):
        write_container(
            os.path.join(base, f"part-{i:05d}.avro"), schema, chunk
        )


def save_loaded_game_model(loaded: "LoadedGameModel", out_dir: str) -> str:
    """Write a host-side :class:`LoadedGameModel` back out in the
    reference directory layout — the dataset-free publication path
    (synthetic fleets, republication of a loaded artifact, bench/chaos
    fixtures). Round-trips bitwise through :func:`load_game_model`:
    coefficients are plain named floats both ways."""
    os.makedirs(out_dir, exist_ok=True)

    def _means_record(model_id, means: Dict[str, float]) -> Dict:
        out = []
        for key, v in means.items():
            nm, term = split_feature_key(key)
            out.append({"name": nm, "term": term, "value": float(v)})
        return {
            "modelId": model_id,
            "modelClass": None,
            "means": out,
            "variances": None,
            "lossFunction": None,
        }

    for name, (shard_id, means) in loaded.fixed_effects.items():
        base = os.path.join(out_dir, FIXED_EFFECT, name)
        _write_lines(os.path.join(base, ID_INFO), [shard_id])
        write_container(
            os.path.join(base, COEFFICIENTS, "part-00000.avro"),
            schemas.BAYESIAN_LINEAR_MODEL_AVRO,
            [_means_record(name, means)],
        )
    for name, (re_type, shard_id, per_entity) in (
        loaded.random_effects.items()
    ):
        base = os.path.join(out_dir, RANDOM_EFFECT, name)
        _write_lines(os.path.join(base, ID_INFO), [re_type, shard_id])
        _write_parts(
            os.path.join(base, COEFFICIENTS),
            schemas.BAYESIAN_LINEAR_MODEL_AVRO,
            [
                _means_record(eid, means)
                for eid, means in sorted(per_entity.items())
            ],
            1,
        )
    for name, (row_t, col_t, rows, cols) in (
        loaded.matrix_factorizations.items()
    ):
        base = os.path.join(out_dir, MATRIX_FACTORIZATION, name)
        _write_lines(os.path.join(base, ID_INFO), [row_t, col_t])
        for sub, latent in (("row-latent", rows), ("col-latent", cols)):
            write_container(
                os.path.join(base, sub, "part-00000.avro"),
                schemas.LATENT_FACTOR_AVRO,
                [
                    {
                        "effectId": eid,
                        "latentFactor": [float(x) for x in vec],
                    }
                    for eid, vec in sorted(latent.items())
                ],
            )
    return out_dir


def save_game_model(
    model: GameModel,
    dataset: GameDataset,
    out_dir: str,
    *,
    model_spec: Optional[str] = None,
    num_re_output_files: int = 1,
) -> None:
    os.makedirs(out_dir, exist_ok=True)
    if model_spec:
        from photon_ml_tpu.reliability.artifacts import atomic_writer

        with atomic_writer(os.path.join(out_dir, "model-spec")) as f:
            f.write(model_spec)
    for name, sub in model.models.items():
        if isinstance(sub, FixedEffectModel):
            base = os.path.join(out_dir, FIXED_EFFECT, name)
            _write_lines(os.path.join(base, ID_INFO), [sub.feature_shard_id])
            imap = dataset.shards[sub.feature_shard_id].index_map
            rec = model_to_bayesian_avro(sub.model, FIXED_EFFECT, imap)
            write_container(
                os.path.join(base, COEFFICIENTS, "part-00000.avro"),
                schemas.BAYESIAN_LINEAR_MODEL_AVRO,
                [rec],
            )
        elif isinstance(sub, RandomEffectModel):
            base = os.path.join(out_dir, RANDOM_EFFECT, name)
            _write_lines(
                os.path.join(base, ID_INFO),
                [sub.random_effect_type, sub.feature_shard_id],
            )
            imap = dataset.shards[sub.feature_shard_id].index_map
            eindex = dataset.entity_indexes[sub.random_effect_type]
            bank = np.asarray(sub.bank)
            bank_vars = (
                np.asarray(sub.variances) if sub.variances is not None else None
            )
            projection = sub.re_dataset.projection
            records = []
            for e in range(sub.re_dataset.num_entities):
                means = []
                variances = [] if bank_vars is not None else None
                for local, g in enumerate(projection[e]):
                    if g < 0:
                        continue
                    v = float(bank[e, local])
                    if v == 0.0:
                        continue
                    key = imap.get_feature_name(int(g))
                    if key is None:
                        continue
                    nm, term = split_feature_key(key)
                    means.append({"name": nm, "term": term, "value": v})
                    if variances is not None:
                        variances.append({
                            "name": nm,
                            "term": term,
                            "value": float(bank_vars[e, local]),
                        })
                records.append({
                    "modelId": eindex.ids[e],
                    "modelClass": None,
                    "means": means,
                    "variances": variances,
                    "lossFunction": None,
                })
            _write_parts(
                os.path.join(base, COEFFICIENTS),
                schemas.BAYESIAN_LINEAR_MODEL_AVRO,
                records,
                num_re_output_files,
            )
        elif isinstance(sub, MatrixFactorizationModel):
            base = os.path.join(out_dir, MATRIX_FACTORIZATION, name)
            _write_lines(
                os.path.join(base, ID_INFO),
                [sub.row_effect_type, sub.col_effect_type],
            )
            for side, latent, id_type in (
                ("row-latent", sub.row_latent, sub.row_effect_type),
                ("col-latent", sub.col_latent, sub.col_effect_type),
            ):
                eindex = dataset.entity_indexes[id_type]
                arr = np.asarray(latent)
                records = [
                    {
                        "effectId": eindex.ids[e],
                        "latentFactor": [float(x) for x in arr[e]],
                    }
                    for e in range(arr.shape[0])
                ]
                write_container(
                    os.path.join(base, side, "part-00000.avro"),
                    schemas.LATENT_FACTOR_AVRO,
                    records,
                )
        elif isinstance(sub, FactoredRandomEffectModel):
            # Persist as a plain random-effect model in the ORIGINAL space:
            # bank_global = bank_latent @ projection^T per entity.
            base = os.path.join(out_dir, RANDOM_EFFECT, name)
            _write_lines(
                os.path.join(base, ID_INFO),
                [sub.random_effect_type, sub.feature_shard_id],
            )
            imap = dataset.shards[sub.feature_shard_id].index_map
            eindex = dataset.entity_indexes[sub.random_effect_type]
            bank_g = np.asarray(sub.bank @ sub.projection.T)  # [E, d_local]
            projection = sub.re_dataset.projection
            records = []
            for e in range(bank_g.shape[0]):
                means = []
                for local, g in enumerate(projection[e]):
                    if g < 0 or local >= bank_g.shape[1]:
                        continue
                    v = float(bank_g[e, local])
                    if v == 0.0:
                        continue
                    key = imap.get_feature_name(int(g))
                    if key is None:
                        continue
                    nm, term = split_feature_key(key)
                    means.append({"name": nm, "term": term, "value": v})
                records.append({
                    "modelId": eindex.ids[e], "modelClass": None,
                    "means": means, "variances": None, "lossFunction": None,
                })
            write_container(
                os.path.join(base, COEFFICIENTS, "part-00000.avro"),
                schemas.BAYESIAN_LINEAR_MODEL_AVRO,
                records,
            )
        else:
            raise ValueError(f"cannot save model type {type(sub)} for {name}")


class LoadedGameModel:
    """Host-side loaded GAME model, scorable against any GameDataset built
    with compatible shard index maps (loadGameModelFromHDFS analog)."""

    def __init__(self):
        self.fixed_effects: Dict[str, Tuple[str, "np.ndarray"]] = {}
        self.random_effects: Dict[str, Tuple[str, str, Dict[str, Dict[str, float]]]] = {}
        self.matrix_factorizations: Dict[str, Tuple[str, str, Dict[str, np.ndarray], Dict[str, np.ndarray]]] = {}
        # {coordinate: {entity id: {feature key: variance}}} for models
        # saved with per-entity variances (scoring ignores them; they load
        # for inspection/round-trip parity)
        self.random_effect_variances: Dict[str, Dict[str, Dict[str, float]]] = {}

    def coordinate_names(self) -> List[str]:
        return (
            list(self.fixed_effects)
            + list(self.random_effects)
            + list(self.matrix_factorizations)
        )

    def score(self, dataset: GameDataset, task: TaskType) -> jnp.ndarray:
        total = jnp.zeros((dataset.num_rows,), jnp.float32)
        fe_cache = self.__dict__.setdefault("_fe_weight_cache", {})
        for name, (shard_id, means) in self.fixed_effects.items():
            imap = dataset.shards[shard_id].index_map
            # the fixed-effect weight vector depends only on (model,
            # index map): chunked scoring calls score() once per chunk
            # with the SAME prebuilt maps — don't rebuild the whole
            # coefficient dict each time
            hit = fe_cache.get(name)
            if hit is None or hit[0] is not imap:
                w = np.zeros((imap.size,), np.float32)
                for key, v in means.items():
                    i = imap.get_index(key)
                    if i >= 0:
                        w[i] = v
                hit = (imap, jnp.asarray(w))
                fe_cache[name] = hit
            glm = create_model(task, Coefficients(hit[1]))
            total = total + glm.score(dataset.batch_for_shard(shard_id))
        re_cache = self.__dict__.setdefault("_re_bank_cache", {})
        for name, (re_type, shard_id, per_entity) in self.random_effects.items():
            imap = dataset.shards[shard_id].index_map
            eindex = dataset.entity_indexes[re_type]
            # chunks sliced from one file share eindex/imap: build the
            # bank once per (entity index, index map), like the FE cache
            hit = re_cache.get(name)
            if hit is None or hit[0] is not eindex or hit[1] is not imap:
                bank = np.zeros((eindex.num_entities, imap.size), np.float32)
                # iterate the DATASET's entities (small per scoring
                # chunk) and look up the model dict — not the model's
                # full entity set per call
                for code, raw_id in enumerate(eindex.ids):
                    means = per_entity.get(raw_id)
                    if not means:
                        continue  # entity has no model (scores 0)
                    for key, v in means.items():
                        i = imap.get_index(key)
                        if i >= 0:
                            bank[code, i] = v
                hit = (eindex, imap, jnp.asarray(bank))
                re_cache[name] = hit
            bank = hit[2]
            codes = dataset.entity_codes[re_type]
            valid = jnp.asarray(codes >= 0)
            w_rows = jnp.take(
                jnp.asarray(bank), jnp.maximum(jnp.asarray(codes), 0), axis=0
            )
            sd = dataset.shards[shard_id]
            score = jnp.sum(
                jnp.asarray(sd.values)
                * jnp.take_along_axis(w_rows, jnp.asarray(sd.indices), axis=1),
                axis=-1,
            )
            total = total + jnp.where(valid, score, 0.0)
        mf_cache = self.__dict__.setdefault("_mf_latent_cache", {})
        for name, (row_t, col_t, rows, cols) in self.matrix_factorizations.items():
            r_index = dataset.entity_indexes[row_t]
            c_index = dataset.entity_indexes[col_t]
            hit = mf_cache.get(name)
            if hit is None or hit[0] is not r_index or hit[1] is not c_index:
                K = len(next(iter(rows.values())))
                R = np.zeros((r_index.num_entities, K), np.float32)
                C = np.zeros((c_index.num_entities, K), np.float32)
                for code, rid in enumerate(r_index.ids):
                    vec = rows.get(rid)
                    if vec is not None:
                        R[code] = vec
                for code, cid in enumerate(c_index.ids):
                    vec = cols.get(cid)
                    if vec is not None:
                        C[code] = vec
                hit = (r_index, c_index, jnp.asarray(R), jnp.asarray(C))
                mf_cache[name] = hit
            mf = MatrixFactorizationModel(row_t, col_t, hit[2], hit[3])
            total = total + mf.score(dataset)
        return total


def load_game_model(model_dir: str) -> LoadedGameModel:
    out = LoadedGameModel()
    fe_dir = os.path.join(model_dir, FIXED_EFFECT)
    if os.path.isdir(fe_dir):
        for name in sorted(os.listdir(fe_dir)):
            base = os.path.join(fe_dir, name)
            with open(os.path.join(base, ID_INFO)) as f:
                shard_id = f.read().split()[0]
            recs = list(read_avro_records(os.path.join(base, COEFFICIENTS)))
            means = {
                f"{m['name']}\t{m['term']}": m["value"]
                for m in recs[0]["means"]
            }
            out.fixed_effects[name] = (shard_id, means)
    re_dir = os.path.join(model_dir, RANDOM_EFFECT)
    if os.path.isdir(re_dir):
        for name in sorted(os.listdir(re_dir)):
            base = os.path.join(re_dir, name)
            with open(os.path.join(base, ID_INFO)) as f:
                parts = f.read().split()
            re_type, shard_id = parts[0], parts[1] if len(parts) > 1 else parts[0]
            per_entity: Dict[str, Dict[str, float]] = {}
            coef_dir = os.path.join(base, COEFFICIENTS)
            # A random-effect coordinate with no part files loads as an
            # empty per-entity map (every entity scores 0 through this
            # coordinate) — the reference's own GameIntegTest/gameModel
            # fixture ships exactly this shape (id-info only).
            recs = read_avro_records(coef_dir) if os.path.isdir(coef_dir) else ()
            per_entity_vars: Dict[str, Dict[str, float]] = {}
            for rec in recs:
                per_entity[rec["modelId"]] = {
                    f"{m['name']}\t{m['term']}": m["value"]
                    for m in rec["means"]
                }
                if rec.get("variances"):
                    per_entity_vars[rec["modelId"]] = {
                        f"{m['name']}\t{m['term']}": m["value"]
                        for m in rec["variances"]
                    }
            out.random_effects[name] = (re_type, shard_id, per_entity)
            if per_entity_vars:
                out.random_effect_variances[name] = per_entity_vars
    mf_dir = os.path.join(model_dir, MATRIX_FACTORIZATION)
    if os.path.isdir(mf_dir):
        for name in sorted(os.listdir(mf_dir)):
            base = os.path.join(mf_dir, name)
            with open(os.path.join(base, ID_INFO)) as f:
                row_t, col_t = f.read().split()[:2]
            rows = {
                r["effectId"]: np.asarray(r["latentFactor"], np.float32)
                for r in read_avro_records(os.path.join(base, "row-latent"))
            }
            cols = {
                r["effectId"]: np.asarray(r["latentFactor"], np.float32)
                for r in read_avro_records(os.path.join(base, "col-latent"))
            }
            out.matrix_factorizations[name] = (row_t, col_t, rows, cols)
    return out
