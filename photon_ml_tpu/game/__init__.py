"""GAME (Generalized Additive Mixed Effects): multi-shard data, per-entity
random effects, block coordinate descent. See module docstrings for
reference citations."""

from photon_ml_tpu.game.config import (
    FactoredRandomEffectConfiguration,
    FeatureShardConfiguration,
    FixedEffectDataConfiguration,
    MFOptimizationConfiguration,
    ProjectorType,
    RandomEffectDataConfiguration,
)
from photon_ml_tpu.game.coordinate import (
    Coordinate,
    FactoredRandomEffectCoordinate,
    FixedEffectCoordinate,
    MatrixFactorizationCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.coordinate_descent import (
    CoordinateDescent,
    CoordinateDescentResult,
)
from photon_ml_tpu.game.data import (
    EntityIndex,
    GameDataset,
    build_game_dataset,
    build_game_dataset_from_files,
)
from photon_ml_tpu.game.coordinate import PodRandomEffectCoordinate
from photon_ml_tpu.game.model import (
    DatumScoringModel,
    FixedEffectModel,
    GameModel,
    MatrixFactorizationModel,
    RandomEffectModel,
)
from photon_ml_tpu.game.pod import (
    EntityShardSpec,
    PodRandomEffectModel,
    PodRandomEffectProblem,
    ShardedREBank,
)
from photon_ml_tpu.game.random_effect import (
    RandomEffectOptimizationProblem,
    RandomEffectTracker,
    score_random_effect,
)
from photon_ml_tpu.game.random_effect_data import (
    RandomEffectBucket,
    RandomEffectDataset,
    build_random_effect_dataset,
)

__all__ = [
    "FactoredRandomEffectConfiguration",
    "FeatureShardConfiguration",
    "FixedEffectDataConfiguration",
    "MFOptimizationConfiguration",
    "ProjectorType",
    "RandomEffectDataConfiguration",
    "Coordinate",
    "FactoredRandomEffectCoordinate",
    "FixedEffectCoordinate",
    "MatrixFactorizationCoordinate",
    "RandomEffectCoordinate",
    "PodRandomEffectCoordinate",
    "EntityShardSpec",
    "PodRandomEffectModel",
    "PodRandomEffectProblem",
    "ShardedREBank",
    "CoordinateDescent",
    "CoordinateDescentResult",
    "EntityIndex",
    "GameDataset",
    "build_game_dataset",
    "build_game_dataset_from_files",
    "DatumScoringModel",
    "FixedEffectModel",
    "GameModel",
    "MatrixFactorizationModel",
    "RandomEffectModel",
    "RandomEffectOptimizationProblem",
    "RandomEffectTracker",
    "score_random_effect",
    "RandomEffectBucket",
    "RandomEffectDataset",
    "build_random_effect_dataset",
]
