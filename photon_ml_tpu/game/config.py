"""GAME configuration: feature shards, coordinate data configs, projectors.

Reference: photon-ml .../data/FixedEffectDataConfiguration.scala:50,
RandomEffectDataConfiguration.scala:64-127 (string DSL
``reType,shardId,numPartitions,activeCap,passiveLowerBound,featureRatio,
projector``), projector/ProjectorType.scala:30, and the GAME driver's
feature shard maps (cli/game/training/Params.scala:44-161,
``featureShardIdToFeatureSectionKeysMap``).
"""

from __future__ import annotations

import enum
import math
from dataclasses import dataclass
from typing import Optional, Sequence


class ProjectorType(enum.Enum):
    INDEX_MAP = "INDEX_MAP"
    RANDOM = "RANDOM"
    IDENTITY = "IDENTITY"

    @classmethod
    def parse(cls, s: str) -> "ProjectorType":
        base = s.strip().upper().split("=")[0]
        return cls(base)


@dataclass(frozen=True)
class FeatureShardConfiguration:
    """One named feature space: the union of one or more Avro feature bags
    (e.g. shard "userShard" = ["userFeatures"]). ``add_intercept`` appends
    the constant-1 feature (featureShardIdToInterceptMap analog)."""

    shard_id: str
    feature_bags: Sequence[str]
    add_intercept: bool = True


@dataclass(frozen=True)
class FixedEffectDataConfiguration:
    feature_shard_id: str = "global"

    @classmethod
    def parse(cls, s: str) -> "FixedEffectDataConfiguration":
        # reference format: "shardId,numPartitions" — partitions meaningless
        # on a mesh; accepted and ignored for CLI compat.
        parts = [p.strip() for p in s.split(",")]
        return cls(feature_shard_id=parts[0])


@dataclass(frozen=True)
class RandomEffectDataConfiguration:
    """Per-coordinate random effect data settings
    (RandomEffectDataConfiguration.scala:64-127)."""

    random_effect_type: str  # id column, e.g. "userId"
    feature_shard_id: str
    active_data_upper_bound: Optional[int] = None  # reservoir cap / entity
    passive_data_lower_bound: Optional[int] = None
    features_to_samples_ratio: Optional[float] = None  # Pearson filter bound
    projector_type: ProjectorType = ProjectorType.INDEX_MAP
    random_projection_dim: Optional[int] = None

    @classmethod
    def parse(cls, s: str) -> "RandomEffectDataConfiguration":
        parts = [p.strip() for p in s.split(",")]
        if len(parts) != 7:
            raise ValueError(
                "expected 'reType,shardId,numPartitions,activeCap,"
                f"passiveLowerBound,featureRatio,projector', got {s!r}"
            )
        def opt_int(x):
            return None if x.lower() in ("none", "") else int(float(x))
        def opt_float(x):
            v = None if x.lower() in ("none", "") else float(x)
            return None if v is not None and math.isinf(v) else v
        proj = parts[6]
        ptype = ProjectorType.parse(proj)
        pdim = None
        if "=" in proj:
            pdim = int(proj.split("=")[1])
        if ptype == ProjectorType.RANDOM and pdim is None:
            raise ValueError(f"RANDOM projector requires a dimension: {s!r}")
        return cls(
            random_effect_type=parts[0],
            feature_shard_id=parts[1],
            active_data_upper_bound=opt_int(parts[3]),
            passive_data_lower_bound=opt_int(parts[4]),
            features_to_samples_ratio=opt_float(parts[5]),
            projector_type=ptype,
            random_projection_dim=pdim,
        )


@dataclass(frozen=True)
class MFOptimizationConfiguration:
    """Matrix factorization settings (MFOptimizationConfiguration.scala:50):
    ``maxNumberIterations,numFactors``."""

    max_iterations: int = 20
    num_latent_factors: int = 8

    @classmethod
    def parse(cls, s: str) -> "MFOptimizationConfiguration":
        parts = [p.strip() for p in s.split(",")]
        return cls(max_iterations=int(parts[0]), num_latent_factors=int(parts[1]))


@dataclass(frozen=True)
class FactoredRandomEffectConfiguration:
    """Factored random effect: RE solves in a learned latent projection
    alternating with a distributed projection-matrix fit
    (FactoredRandomEffectOptimizationProblem.scala:42-162)."""

    latent_space_dimension: int = 8
    num_inner_iterations: int = 2
