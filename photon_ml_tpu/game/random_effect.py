"""Random-effect solver: per-entity GLM solves as vmapped while_loop banks.

Reference: photon-ml .../algorithm/RandomEffectCoordinate.scala:104-128 —
``activeData.join(optimizationProblems).join(modelsRDD).mapValues { local
optimizer.optimize }`` i.e. millions of independent single-node solves —
and optimization/game/RandomEffectOptimizationProblem.scala:41-130 (one
problem per entity, co-partitioned) with tracker aggregation
(RandomEffectOptimizationTracker.scala).

TPU-native: each bucket of equal-capacity entities is ONE
``jax.vmap(minimize_lbfgs)`` program over the entity axis — zero
cross-entity communication, matching the reference's key scalability
property, but with the per-entity JVM loop replaced by a single fused XLA
while_loop over [E_b, ...] blocks. Shard the entity axis over the mesh
("data" axis) for multi-chip (expert-parallel analog).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.random_effect_data import RandomEffectDataset
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim.common import (
    CONVERGENCE_REASON_NAMES,
    FUNCTION_VALUES_WITHIN_TOLERANCE,
    GRADIENT_WITHIN_TOLERANCE,
    LINE_SEARCH_STALLED,
    NOT_CONVERGED,
    check_convergence,
)
from photon_ml_tpu.optim.config import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
)
from photon_ml_tpu.optim.lbfgs import minimize_lbfgs, minimize_owlqn
from photon_ml_tpu.optim.tron import minimize_tron

Array = jnp.ndarray


@dataclass
class RandomEffectTracker:
    """Aggregated per-entity convergence stats
    (RandomEffectOptimizationTracker analog)."""

    num_entities: int
    iterations_mean: float
    iterations_max: int
    reason_counts: Dict[str, int]


class LazyRandomEffectTracker:
    """RandomEffectTracker facade whose aggregated stats stay
    DEVICE-RESIDENT until first use (the overlap deferred-readback path):
    ``update_bank(..., defer_tracker=True)`` returns one of these instead
    of forcing a device->host round trip per bank update. The coordinate
    descent loop batch-fetches every coordinate's ``.deferred`` with ONE
    ``device_get`` per iteration (parallel/overlap.fetch_all); any other
    consumer that touches an attribute forces its own (counted) fetch, so
    behavior is identical to the eager tracker — only the transfer
    schedule changes."""

    __slots__ = ("deferred",)

    def __init__(self, deferred):
        self.deferred = deferred

    def _tracker(self) -> RandomEffectTracker:
        return self.deferred.result()

    @property
    def num_entities(self) -> int:
        return self._tracker().num_entities

    @property
    def iterations_mean(self) -> float:
        return self._tracker().iterations_mean

    @property
    def iterations_max(self) -> int:
        return self._tracker().iterations_max

    @property
    def reason_counts(self) -> Dict[str, int]:
        return self._tracker().reason_counts

    def __repr__(self) -> str:  # force: repr is a host-side consumer
        return repr(self._tracker())


# Solver namespaces shared across problem instances with equal
# (loss, config, regularization): a GAME combo grid builds a fresh
# RandomEffectOptimizationProblem per combo, and without sharing each
# re-jits (and, over a relay, re-COMPILES) every bucket program — the
# reg weights are traced arguments, so combos differing only in lambda
# are the same programs. The namespace also carries the shared AOT
# executable cache. FIFO-bounded; unhashable configs fall through to a
# fresh build.
_SOLVER_CACHE: dict = {}
_SOLVER_CACHE_MAX = 16


def _cached_bucket_solver(
    loss: PointwiseLoss,
    config: OptimizerConfig,
    regularization: RegularizationContext,
):
    from photon_ml_tpu.utils.memo import get_or_build

    return get_or_build(
        _SOLVER_CACHE, _SOLVER_CACHE_MAX,
        (loss, config, regularization),
        lambda: _bucket_solver(loss, config, regularization),
    )


def _bucket_solver(
    loss: PointwiseLoss,
    config: OptimizerConfig,
    regularization: RegularizationContext,
):
    """Build jit(solve)(bank_slice, bucket arrays, offsets, l1, l2)."""

    def entity_objective(ix, v, lab, off, w):
        def vg(coef):
            z = jnp.sum(v * jnp.take(coef, ix, axis=0), axis=-1) + off
            lv = loss.value(z, lab)
            ld = loss.d1(z, lab)
            c = w * ld
            val = jnp.sum(w * lv)
            grad = jnp.zeros_like(coef).at[ix.reshape(-1)].add(
                (v * c[:, None]).reshape(-1)
            )
            return val, grad

        def hvp(coef, direction):
            z = jnp.sum(v * jnp.take(coef, ix, axis=0), axis=-1) + off
            zd = jnp.sum(v * jnp.take(direction, ix, axis=0), axis=-1)
            c = w * loss.d2(z, lab) * zd
            return jnp.zeros_like(coef).at[ix.reshape(-1)].add(
                (v * c[:, None]).reshape(-1)
            )

        return vg, hvp

    use_tron = config.optimizer_type == OptimizerType.TRON
    use_owlqn = regularization.has_l1

    def _minimize(vg, hvp, coef0, l1):
        if use_tron:
            return minimize_tron(
                vg, hvp, coef0,
                max_iter=config.max_iter, tol=config.tolerance,
                max_cg=config.tron_max_cg,
            )
        if use_owlqn:
            return minimize_owlqn(
                vg, coef0, l1,
                max_iter=config.max_iter, tol=config.tolerance,
                history=config.lbfgs_history,
            )
        return minimize_lbfgs(
            vg, coef0,
            max_iter=config.max_iter, tol=config.tolerance,
            history=config.lbfgs_history,
        )

    @jax.jit
    def solve(bank, ix, v, lab, off, w, l1, l2):
        def one(coef0, ix_e, v_e, lab_e, off_e, w_e):
            vg_raw, hvp_raw = entity_objective(ix_e, v_e, lab_e, off_e, w_e)

            def vg(c):
                val, g = vg_raw(c)
                return val + 0.5 * l2 * jnp.vdot(c, c), g + l2 * c

            def hvp(c, d):
                return hvp_raw(c, d) + l2 * d

            return _minimize(vg, hvp, coef0, l1)

        res = jax.vmap(one)(bank, ix, v, lab, off, w)
        return res.coefficients, res.iterations, res.reason

    def _densify(ix, v, d_local):
        """Batched densification of each entity's [S, k] sparse rows into a
        dense X [E, S, D] block — as a fused compare-and-reduce over the
        nnz axis rather than a scatter: TPU scatters serialize per element
        (measured 132 ms at E=20k, S=16, k=32, D=1000) while the VPU eats
        the k-reduction whole (33 ms, exact same result). XLA fuses the
        [E, S, k, D] broadcast; it is never materialized."""
        d = jnp.arange(d_local, dtype=ix.dtype)
        return jnp.sum(
            v[..., :, None] * (ix[..., :, None] == d[None, None, None, :]),
            axis=2,
        )

    def _make_dense(identity):
        """DENSE per-entity layout: one compare-and-reduce densification
        of each entity's rows into X [E, S, D] up front (see _densify),
        then every objective evaluation is a pair of batched matmuls
        riding the MXU instead of the serialized per-element gathers/
        scatters of the sparse path — a ~40x gradient-path win whenever
        S*D is small enough to afford the dense block. ``identity``:
        the bucket's indices are the tiled arange (k == D, the MF latent
        view) and X IS values — no densify broadcast at all."""

        @jax.jit
        def solve_dense(bank, ix, v, lab, off, w, l1, l2):
            X = v if identity else _densify(ix, v, bank.shape[1])

            def one(coef0, X_e, lab_e, off_e, w_e):
                def vg(c):
                    z = X_e @ c + off_e
                    lv = loss.value(z, lab_e)
                    ld = loss.d1(z, lab_e)
                    val = jnp.sum(w_e * lv) + 0.5 * l2 * jnp.vdot(c, c)
                    grad = X_e.T @ (w_e * ld) + l2 * c
                    return val, grad

                def hvp(c, d):
                    z = X_e @ c + off_e
                    zd = X_e @ d
                    return X_e.T @ (w_e * loss.d2(z, lab_e) * zd) + l2 * d

                return _minimize(vg, hvp, coef0, l1)

            res = jax.vmap(one)(bank, X, lab, off, w)
            return res.coefficients, res.iterations, res.reason

        return solve_dense

    def _make_newton(identity):
        @jax.jit
        def solve_dense_newton(bank, ix, v, lab, off, w, l1, l2):
            """Damped Newton in the DUAL (sample) space — the TPU-first
            redesign of the per-entity solve.

            The reference runs L-BFGS per entity (RandomEffectCoordinate.
            scala:104-128); quasi-Newton line searches cost many objective
            evaluations, and under vmap the whole bucket pays the slowest
            lane's trials every iteration. But the reservoir cap
            (RandomEffectDataSet.scala:254-317) bounds each entity's active
            samples S by construction, so the exact Newton step is cheap in
            the sample space: H = X^T D X + l2 I has rank <= S + ridge, and
            by Woodbury

                H^-1 g = (1/l2) * (g - X^T (l2 I + D G)^-1 D X g),

            with G = X X^T ([S, S], built once). Each iteration is two X
            passes + one batched S x S solve; quadratic convergence replaces
            ~O(10) line-search evaluations per L-BFGS iteration with ~1
            halving check per Newton iteration. Requires l2 > 0 and a twice-
            differentiable loss — update_bank selects it host-side.
            """
            del l1  # smooth path only (OWL-QN handles l1)
            _, s_b, _ = ix.shape
            X = v if identity else _densify(ix, v, bank.shape[1])
            max_iter = config.max_iter
            tol = config.tolerance

            def one(coef0, X_e, lab_e, off_e, w_e):
                G = X_e @ X_e.T  # [S, S] sample Gram, one-time

                def value(c, z):
                    return jnp.sum(w_e * loss.value(z, lab_e)) + 0.5 * l2 * jnp.vdot(c, c)

                def grad_vec(z, c):
                    # Exact g = X^T cd + l2 c, materialized in coefficient
                    # space: the all-dual norm expansion (cd G cd + 2 l2 cd.Xc
                    # + l2^2 ||c||^2) cancels catastrophically in float32 once
                    # ||g|| is small relative to the individual terms,
                    # mis-reporting convergence — so spend one [D, S] matvec
                    # per iteration on the true gradient. The vector rides the
                    # loop carry: the NEXT iteration's Cauchy fallback needs
                    # exactly this gradient, so it costs no extra X pass.
                    cd = w_e * loss.d1(z, lab_e)
                    return X_e.T @ cd + l2 * c

                z0 = X_e @ coef0 + off_e
                f0 = value(coef0, z0)
                g0_vec = grad_vec(z0, coef0)
                g0_norm = jnp.linalg.norm(g0_vec)

                # state: (c, z, f, g_vec, iter, reason). z is carried
                # incrementally (z_t = z + alpha * z_step, z_step computed in
                # dual space) — the only X touches per iteration are the X^T
                # applies that materialize the step and the exact gradient.
                def cond(st):
                    return st[5] == NOT_CONVERGED

                def body(st):
                    c, z, f, g_vec, it, _ = st
                    cd = w_e * loss.d1(z, lab_e)  # dual gradient weights [S]
                    d2 = w_e * loss.d2(z, lab_e)  # [S] >= 0 (convex)
                    zp = z - off_e  # = X c
                    u = G @ cd + l2 * zp  # = X g, no X pass
                    # t = (l2 I + D G)^-1 D u via the symmetrized SPD system
                    # B = l2 I + Dh G Dh (Dh = sqrt(D)): t = Dh B^-1 Dh u.
                    # CG with S iterations is exact up to roundoff and runs
                    # ~6x faster than batched LU on TPU (no pivoting loops,
                    # matvecs ride the MXU); the safeguarded line search
                    # absorbs any residual inexactness.
                    dh = jnp.sqrt(d2)

                    def b_mv(x):
                        return l2 * x + dh * (G @ (dh * x))

                    rhs = dh * u

                    def cg_body(i, st):
                        x_c, r_c, p_c, rs = st
                        ap = b_mv(p_c)
                        alpha = rs / (jnp.vdot(p_c, ap) + 1e-30)
                        x_c = x_c + alpha * p_c
                        r_c = r_c - alpha * ap
                        rs2 = jnp.vdot(r_c, r_c)
                        p_c = r_c + (rs2 / (rs + 1e-30)) * p_c
                        return x_c, r_c, p_c, rs2

                    y0 = jnp.zeros_like(rhs)
                    y, _, _, _ = jax.lax.fori_loop(
                        0, s_b, cg_body,
                        (y0, rhs, rhs, jnp.vdot(rhs, rhs)),
                    )
                    t = dh * y
                    r = cd - t
                    step = -(X_e.T @ r) / l2 - c  # = -H^-1 g, ONE X pass
                    z_step = -(G @ r) / l2 - zp  # = X step, dual space

                    # Line search over 16 halving trials: 0-7 along the Newton
                    # step, 8-15 along the exact Cauchy (steepest-descent)
                    # step — the fallback for the rare entity whose float32 CG
                    # left the Newton step non-descent (ill-conditioned B at
                    # tiny l2). Every trial is pure z-space: the loss term
                    # moves along the precomputed dual step and the l2 term is
                    # a scalar quadratic in alpha, so no [D]-sized work or X
                    # pass happens per trial.
                    cc = jnp.vdot(c, c)
                    cs_n = jnp.vdot(c, step)
                    ss_n = jnp.vdot(step, step)
                    cg_dot = jnp.vdot(c, g_vec)
                    g_sq = jnp.vdot(g_vec, g_vec)  # exact, from the carry
                    g_hg = jnp.vdot(u, d2 * u) + l2 * g_sq
                    cauchy = g_sq / (g_hg + 1e-30)
                    cs_c = -cauchy * cg_dot
                    ss_c = cauchy * cauchy * g_sq
                    z_step_c = -cauchy * u

                    def trial(k):
                        newton = k < 8
                        a = jnp.exp2(-jnp.where(newton, k, k - 8).astype(z.dtype))
                        z_t = z + a * jnp.where(newton, z_step, z_step_c)
                        cs = jnp.where(newton, cs_n, cs_c)
                        ss = jnp.where(newton, ss_n, ss_c)
                        loss_t = jnp.sum(w_e * loss.value(z_t, lab_e))
                        return a, loss_t + 0.5 * l2 * (
                            cc + 2.0 * a * cs + a * a * ss
                        )

                    def ls_cond(carry):
                        k, _, f_t, _ = carry
                        bad = (f_t > f) | ~jnp.isfinite(f_t)
                        return bad & (k < 16)

                    def ls_body(carry):
                        k, _, _, f_min = carry
                        k = k + 1
                        a, f_t = trial(k)
                        f_t = jnp.where(k < 16, f_t, jnp.inf)
                        return k, a, f_t, jnp.minimum(f_min, f_t)

                    a0, f0_t = trial(jnp.int32(0))
                    k, alpha, f_t, f_min = jax.lax.while_loop(
                        ls_cond, ls_body, (jnp.int32(0), a0, f0_t, f0_t)
                    )
                    # Strict decrease moves the iterate (monotone invariant);
                    # when NO trial decreases but the best trial was a float32
                    # near-tie, the entity is sitting on its optimum's noise
                    # plateau — report convergence WITHOUT moving instead of a
                    # bogus MaxIterations (and instead of accepting an uphill
                    # step, which could random-walk past the convergence test).
                    moved = (f_t <= f) & jnp.isfinite(f_t)
                    plateau = ~moved & (f_min <= f + 1e-6 * (1.0 + jnp.abs(f)))
                    newton_used = k < 8
                    # the carried g_vec IS the gradient at (c, z) — the
                    # fallback direction costs no extra X pass
                    used_step = jnp.where(newton_used, step, -cauchy * g_vec)
                    used_zstep = jnp.where(newton_used, z_step, z_step_c)
                    c2 = jnp.where(moved, c + alpha * used_step, c)
                    z2 = jnp.where(moved, z + alpha * used_zstep, z)
                    f2 = jnp.where(moved, f_t, f)
                    it2 = it + 1
                    g2_vec = grad_vec(z2, c2)
                    g_norm = jnp.linalg.norm(g2_vec)
                    reason = jnp.where(
                        moved,
                        check_convergence(
                            it2, f, f2, g_norm, f0, g0_norm,
                            max_iter=max_iter, tol=tol,
                        ),
                        jnp.where(
                            plateau,
                            FUNCTION_VALUES_WITHIN_TOLERANCE,
                            LINE_SEARCH_STALLED,  # no decreasing step exists
                        ),
                    ).astype(jnp.int32)
                    return (c2, z2, f2, g2_vec, it2, reason)

                init = (
                    coef0, z0, f0, g0_vec, jnp.zeros((), jnp.int32),
                    jnp.where(
                        g0_norm == 0.0, GRADIENT_WITHIN_TOLERANCE, NOT_CONVERGED
                    ).astype(jnp.int32),
                )
                c, _, _, _, it, reason = jax.lax.while_loop(cond, body, init)
                return c, it, reason

            coefs, iters, reasons = jax.vmap(one)(bank, X, lab, off, w)
            return coefs, iters, reasons

        return solve_dense_newton

    n_reasons = max(CONVERGENCE_REASON_NAMES) + 1

    def _fused(core):
        """Single-dispatch bucket update: bank-row gather, solve, bank
        scatter, and the tracker reductions all inside ONE jit program —
        per-bucket host overhead (separate gather/scatter dispatches plus
        two [E]-sized device->host tracker transfers) otherwise dwarfs the
        ~ms solve itself on a tunneled chip.

        The bank operand is DONATED (where the backend supports donation):
        the scatter updates it in place instead of copying the full
        [E_total, D] bank per bucket — at the 1B-coefficient scale that
        copy would double peak bank memory and add a ~4 GB HBM pass per
        bucket. update_bank defensively copies the caller's bank ONCE
        before the bucket chain so outside references stay valid."""
        from photon_ml_tpu.utils.backend import effective_platform

        donate = (0,) if effective_platform() != "cpu" else ()

        # photon: sharding(axes=[], donates=[0])
        @partial(jax.jit, donate_argnums=donate)
        def fused(bank_full, codes, ix, v, lab, off, w, l1, l2):
            sl = jnp.take(bank_full, codes, axis=0)
            new_sl, iters, reasons = core(sl, ix, v, lab, off, w, l1, l2)
            bank_full = bank_full.at[codes].set(new_sl)
            return (
                bank_full,
                jnp.sum(iters),
                jnp.max(iters),
                jnp.bincount(reasons, length=n_reasons),
            )

        return fused

    def _fused_scan(core):
        """The fused bucket update folded over a STACK of same-shape
        buckets by lax.scan — one dispatch for the whole group. Profiled
        at the config-4 user-bank shape (PERF_NOTES round 5): the four
        sequential per-bucket dispatches left ~125 ms of host gaps
        between ~76 ms device programs; scanning removes the gaps. The
        bank threads through the scan carry (donated, in-place
        scatters)."""
        from photon_ml_tpu.utils.backend import effective_platform

        donate = (0,) if effective_platform() != "cpu" else ()

        # photon: sharding(axes=[], donates=[0])
        @partial(jax.jit, donate_argnums=donate)
        def fused_scan(bank_full, codes_s, ix_s, v_s, lab_s, off_s, w_s,
                       l1, l2):
            def body(bank, args):
                codes, ix, v, lab, off, w = args
                sl = jnp.take(bank, codes, axis=0)
                new_sl, iters, reasons = core(sl, ix, v, lab, off, w, l1, l2)
                bank = bank.at[codes].set(new_sl)
                return bank, (
                    jnp.sum(iters),
                    jnp.max(iters),
                    jnp.bincount(reasons, length=n_reasons),
                )

            bank_full, (it_sums, it_maxs, counts) = jax.lax.scan(
                body, bank_full, (codes_s, ix_s, v_s, lab_s, off_s, w_s)
            )
            return (
                bank_full,
                jnp.sum(it_sums),
                jnp.max(it_maxs),
                jnp.sum(counts, axis=0),
            )

        return fused_scan

    @jax.jit
    def hdiag(sl, ix, v, lab, off, w, l2):
        """Per-entity Hessian diagonals at the given bank rows:
        Hdiag_e[j] = sum_s w_s l''(z_s) x_{s,j}^2 + l2 — the
        computeVariances input (RandomEffectOptimizationProblem.
        scala:106-127 -> GeneralizedLinearOptimizationProblem
        computeVariances). One pass, not a solve: padded samples carry
        w = 0 and contribute nothing."""

        def one(c_e, ix_e, v_e, lab_e, off_e, w_e):
            z = jnp.sum(v_e * jnp.take(c_e, ix_e, axis=0), axis=-1) + off_e
            cd = w_e * loss.d2(z, lab_e)
            return jnp.zeros_like(c_e).at[ix_e.reshape(-1)].add(
                ((v_e * v_e) * cd[:, None]).reshape(-1)
            )

        return jax.vmap(one)(sl, ix, v, lab, off, w) + l2

    from types import SimpleNamespace

    solve_dense = _make_dense(False)
    solve_dense_id = _make_dense(True)
    solve_newton = _make_newton(False)
    solve_newton_id = _make_newton(True)
    return SimpleNamespace(
        sparse=solve,
        dense=solve_dense,
        dense_id=solve_dense_id,
        newton=solve_newton,
        newton_id=solve_newton_id,
        fused_sparse=_fused(solve),
        fused_dense=_fused(solve_dense),
        fused_dense_id=_fused(solve_dense_id),
        fused_newton=_fused(solve_newton),
        fused_newton_id=_fused(solve_newton_id),
        fused_scan_sparse=_fused_scan(solve),
        fused_scan_dense=_fused_scan(solve_dense),
        fused_scan_dense_id=_fused_scan(solve_dense_id),
        fused_scan_newton=_fused_scan(solve_newton),
        fused_scan_newton_id=_fused_scan(solve_newton_id),
        hdiag=hdiag,
    )


@dataclass
class RandomEffectOptimizationProblem:
    """One solver config shared by all entities (the reference materializes
    an RDD of identical per-entity problems; here the per-entity state is
    just the bank row).

    ``mesh``: when set, every bucket's entity axis is sharded over the
    mesh's first axis — the expert-parallel analog of the reference's
    entity co-partitioning (RandomEffectDataSetPartitioner.scala:62-95).
    Load balance is by construction: a bucket's entities share one padded
    capacity, so equal-count splits are equal-cost (the reference needs a
    greedy partitioner because its per-entity costs vary).
    """

    loss: PointwiseLoss
    config: OptimizerConfig
    regularization: RegularizationContext
    reg_weight: float = 0.0
    mesh: Optional[object] = None
    # Per-entity data layout for the solves: "auto" densifies a bucket's
    # [E, S, k] sparse rows into [E, S, D] blocks when that fits the
    # budget below (matmul gradients instead of serialized TPU scatters
    # per line-search trial); "sparse"/"dense" force a layout.
    layout: str = "auto"
    dense_bytes_budget: int = 2 << 30
    # isComputingVariance (RandomEffectOptimizationProblem.scala:106-127):
    # the coordinate attaches bank_variances() to the model after each
    # bank update so saved per-entity models carry them
    compute_variances: bool = False

    def __post_init__(self):
        if self.layout not in ("auto", "sparse", "dense"):
            raise ValueError(f"unknown layout {self.layout!r}")
        self._solvers = _cached_bucket_solver(
            self.loss, self.config, self.regularization
        )
        # AOT-compiled bucket programs from the threaded warm pass,
        # keyed by (kind, bank shape, bucket indices shape). Lives ON the
        # (shared) solver namespace so equal-config problems — a combo
        # grid's fresh problem per combo — reuse compiled executables.
        if not hasattr(self._solvers, "aot_cache"):
            self._solvers.aot_cache = {}
        self._aot_cache: Dict[tuple, object] = self._solvers.aot_cache
        # Device-resident copies of each bucket's static arrays (indices/
        # values/labels/weights), keyed by id(bucket). Coordinate descent
        # calls update_bank once per iteration with identical bucket data —
        # only the bank rows and residual offsets change — and host->device
        # re-transfer of the big [E, S, k] blocks would otherwise dominate
        # the whole update (measured: ~6s transfer vs ~1ms solve at
        # E=20k, S=16, k=32 over the tunneled chip). Entries hold only a
        # weakref to the bucket: callers that rebuild buckets every call
        # (factored-RE latent views, MF ALS half-steps) get their device
        # copies freed with the bucket instead of accumulating until OOM,
        # and a recycled id cannot alias because the dead entry removes
        # itself first.
        self._device_cache: Dict[int, Tuple[object, List[Array]]] = {}
        # per-dataset residual routers for the mesh path (static routing
        # tables + jitted all_to_all scatter; weakref like _device_cache)
        self._router_cache: Dict[int, Tuple[object, object]] = {}

    def _router_for(self, dataset):  # photon: entropy(id-keyed router memo; weakref-pinned, never serialized)
        import weakref

        key = id(dataset)
        hit = self._router_cache.get(key)
        if hit is not None and hit[0]() is dataset:
            return hit[1]
        from photon_ml_tpu.game.residual_routing import ResidualRouter

        router = ResidualRouter(self.mesh, dataset)
        cache = self._router_cache
        ref = weakref.ref(dataset, lambda _, k=key, c=cache: c.pop(k, None))
        cache[key] = (ref, router)
        return router

    def _bucket_kind(self, bucket, d_local: int) -> str:
        """Which solver program this bucket runs (host-side selection)."""
        use_dense = self._use_dense(bucket, d_local)
        kind = (
            ("newton" if self._newton_eligible() else "dense")
            if use_dense
            else "sparse"
        )
        if use_dense and bucket.identity_indices:
            # indices are the tiled arange (k == local_dim, the MF
            # latent view): X IS values — skip the [E, S, k, D]
            # densify broadcast
            kind += "_id"
        return kind

    def _newton_eligible(self) -> bool:
        """The dual-space Newton solver needs l2 > 0 (Woodbury ridge), a
        twice-differentiable loss, and no l1/TRON machinery."""
        l1, l2 = self.regularization.split(self.reg_weight)
        return (
            l2 > 0.0
            and not l1
            and self.loss.has_hessian
            and self.config.optimizer_type != OptimizerType.TRON
        )

    def _use_dense(self, bucket, d_local: int) -> bool:
        if self.layout != "auto":
            return self.layout == "dense"
        e_b, s_b, _ = bucket.indices.shape
        itemsize = np.dtype(bucket.values.dtype).itemsize
        # X [E, S, D], plus the Newton path's Gram G [E, S, S] when that
        # solver would actually run (the CG solve is matrix-free — no
        # second S x S block) — when S > D the Grams, not X, dominate the
        # footprint, but charging them to a bucket that can only take the
        # plain dense solver would wrongly force the slow sparse path.
        # Identity-indices buckets pay no X at all (X IS values).
        floats = 0 if bucket.identity_indices else e_b * s_b * d_local
        if self._newton_eligible():
            floats += e_b * s_b * s_b
        return floats * itemsize <= self.dense_bytes_budget

    def _bucket_device_args(self, bucket, with_values=True) -> List[Array]:  # photon: entropy(id-keyed device-array memo; weakref-pinned, never serialized)
        """Device-resident (mesh-sharded if configured) static arrays for a
        bucket, transferred once and reused across update_bank calls. The
        cache holds a weakref: device copies die with the bucket.
        ``with_values=False`` (the values_override path) skips uploading
        the bucket's stored values — a caller that always overrides them
        must not pin a dead [E, S, k] copy in HBM."""
        import weakref

        key = (id(bucket), with_values)
        hit = self._device_cache.get(key)
        if hit is not None and hit[0]() is bucket:
            return hit[1]
        arrs = [
            jnp.asarray(bucket.indices),
            jnp.asarray(bucket.values) if with_values else None,
            jnp.asarray(bucket.labels),
            jnp.asarray(bucket.weights),
            jnp.asarray(bucket.offsets),
            jnp.asarray(bucket.row_index),
        ]
        if self.mesh is not None:
            present = [a for a in arrs if a is not None]
            present, _ = self._shard_entity_axis(present)
            it = iter(present)
            arrs = [next(it) if a is not None else None for a in arrs]
        # entity codes stay unsharded: they index the full bank host-side
        arrs = arrs + [jnp.asarray(bucket.entity_codes)]
        cache = self._device_cache
        ref = weakref.ref(bucket, lambda _, k=key, c=cache: c.pop(k, None))
        self._device_cache[key] = (ref, arrs)
        return arrs

    def _shard_entity_axis(self, arrays):
        """Pad arrays' leading (entity) dim to the mesh axis size and place
        them entity-sharded; returns (padded arrays, real length)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        axis = mesh.axis_names[0]
        n_dev = int(mesh.shape[axis])
        sharding = NamedSharding(mesh, P(axis))
        e = arrays[0].shape[0]
        e_pad = ((e + n_dev - 1) // n_dev) * n_dev
        out = []
        for a in arrays:
            if e_pad != e:
                pad = jnp.zeros((e_pad - e,) + a.shape[1:], a.dtype)
                a = jnp.concatenate([a, pad])
            out.append(jax.device_put(a, sharding))
        return out, e

    def _route_residuals(self, dataset, residual_offsets):
        """Pre-loop residual-offset routing shared by update_bank and
        bank_variances: -> (offsets_f32, routed_buffers, router)."""
        routed = None
        router = None
        if residual_offsets is not None:
            residual_offsets = jnp.asarray(residual_offsets, jnp.float32)
            if self.mesh is not None and dataset.buckets:
                # ICI re-key: ONE all_to_all routes each row's offset to
                # its entity's owner device (the addScoresToOffsets
                # shuffle analog) instead of replicating the whole [n]
                # vector to every device.
                router = self._router_for(dataset)
                routed = router.route(residual_offsets)
        return residual_offsets, routed, router

    def _stacked_group_args(self, dataset, members, *, with_residuals):
        """Device-stacked [B, ...] args for a same-shape bucket group,
        built from the HOST arrays in one transfer per field and cached
        on the dataset. Only the offset source the configuration needs is
        stacked: stored offsets when ``with_residuals`` is False, row
        indices (for the on-device residual gather) when True — never
        both (a dead [B, E, S] buffer would otherwise pin HBM for the
        dataset's lifetime).

        Accepted trade-off: a dataset that ALSO runs the per-bucket path
        (bank_variances / with_variances) holds its buckets in both this
        cache and the per-bucket device cache; the two paths do not
        co-occur within one update, and problems are variance-typed for
        their lifetime, so the overlap is rare in practice."""
        cache = dataset.__dict__.setdefault("_stacked_device_cache", {})
        key = (tuple(members), bool(with_residuals))
        hit = cache.get(key)
        if hit is not None:
            return hit
        bs = [dataset.buckets[bi] for bi in members]
        out = (
            jnp.asarray(np.stack([b.entity_codes for b in bs])),
            jnp.asarray(np.stack([b.indices for b in bs])),
            jnp.asarray(np.stack([b.values for b in bs])),
            jnp.asarray(np.stack([b.labels for b in bs])),
            None
            if with_residuals
            else jnp.asarray(np.stack([b.offsets for b in bs])),
            jnp.asarray(np.stack([b.weights for b in bs])),
            jnp.asarray(np.stack([b.row_index for b in bs]))
            if with_residuals
            else None,
        )
        cache[key] = out
        return out

    def _bucket_offsets(
        self, bi, bucket, rows_d, residual_offsets, routed, router
    ):
        """One bucket's per-sample offsets from the routed residuals."""
        if routed is not None:
            # mesh path: slice this bucket's slab out of the routed
            # per-device buffers — already entity-sharded
            return router.bucket_slab(routed, bi, bucket.capacity)
        # single device: per-row gather stays on device — the
        # KeyValueScore residual currency never leaves it
        # (SURVEY §7.9; round 2 gathered on host per bucket)
        return jnp.where(
            rows_d >= 0, residual_offsets[jnp.maximum(rows_d, 0)], 0.0
        )

    def _bucket_plans(
        self,
        bank: Array,
        dataset: RandomEffectDataset,
        *,
        has_values_override: bool,
        has_residual_offsets: bool,
        l1_d,
        l2_d,
        groups=None,
    ):
        """(sig, thunk) plans for every DISTINCT bucket program of one
        dataset; ``thunk()`` lowers the bucket's exact solver call and
        returns the compiled executable. With ``groups`` (the update_bank
        fold grouping) multi-member groups plan the SCAN program from
        avals instead of per-bucket programs."""
        plans = []
        if groups is not None:
            singles = []
            seen_scan_sigs = set()
            for sig, members in groups:
                if len(members) == 1:
                    singles.append(members[0])
                    continue
                kind = sig[0]
                bucket = dataset.buckets[members[0]]
                E, S = bucket.labels.shape
                ixk = bucket.indices.shape
                B = len(members)
                scan_sig = (
                    "scan", kind, bank.shape, (B,) + ixk
                )
                if scan_sig in seen_scan_sigs:
                    continue  # identical program; one compile suffices
                seen_scan_sigs.add(scan_sig)

                def thunk(kind=kind, B=B, E=E, S=S, ixk=ixk, bank=bank):
                    sds = jax.ShapeDtypeStruct
                    f32, i32 = jnp.float32, jnp.int32
                    fused_scan = getattr(
                        self._solvers, f"fused_scan_{kind}"
                    )
                    return fused_scan.lower(
                        bank,
                        sds((B, E), i32),
                        sds((B,) + ixk, i32),
                        sds((B,) + ixk, f32),
                        sds((B, E, S), f32),
                        sds((B, E, S), f32),
                        sds((B, E, S), f32),
                        l1_d, l2_d,
                    ).compile()

                plans.append((scan_sig, thunk))
            buckets_iter = [(bi, dataset.buckets[bi]) for bi in singles]
        else:
            buckets_iter = list(enumerate(dataset.buckets))
        seen_sigs = set()
        for bi, bucket in buckets_iter:
            kind = self._bucket_kind(bucket, bank.shape[1])
            sig = (kind, bank.shape, bucket.indices.shape)
            if sig in seen_sigs:
                continue
            seen_sigs.add(sig)

            def thunk(bi=bi, bucket=bucket, kind=kind, bank=bank):
                (
                    ix_d, v_d, lab_d, w_d, off_d, rows_d, codes_d,
                ) = self._bucket_device_args(
                    bucket, with_values=not has_values_override
                )
                # COMPUTED operands (override gathers, residual
                # offsets) lower from avals only — materializing them
                # here would run every bucket's partner gather
                # concurrently and break the one-bucket HBM cap the
                # deferred values_override exists for
                if has_values_override:
                    k_dim = bucket.indices.shape[-1]
                    v_d = jax.ShapeDtypeStruct(
                        bucket.indices.shape[:2] + (k_dim,), jnp.float32
                    )
                if has_residual_offsets:
                    off_d = jax.ShapeDtypeStruct(
                        bucket.offsets.shape, jnp.float32
                    )
                fused = getattr(self._solvers, f"fused_{kind}")
                # lowering never executes; the loop calls the result
                return fused.lower(
                    bank, codes_d, ix_d, v_d, lab_d, off_d, w_d,
                    l1_d, l2_d,
                ).compile()

            plans.append((sig, thunk))
        return plans

    def _bucket_groups(self, d_local, dataset, *, fold_eligible):
        """Consecutive same-signature bucket runs -> [(sig, members)]
        (the lax.scan fold grouping); singletons when folding is off."""
        groups: List = []
        if fold_eligible:
            for bi, bucket in enumerate(dataset.buckets):
                kind = self._bucket_kind(bucket, d_local)
                sig = (kind, bucket.indices.shape)
                if groups and groups[-1][0] == sig:
                    groups[-1][1].append(bi)
                else:
                    groups.append((sig, [bi]))
        else:
            groups = [(None, [bi]) for bi in range(len(dataset.buckets))]
        return groups

    def prepare(
        self, bank: Array, dataset: RandomEffectDataset,
        *, has_residual_offsets: bool = True,
    ) -> None:
        """Host-side staging for a FUTURE update_bank over ``dataset``:
        device transfer of every bucket's static arrays (stacked group
        args on the fold path), residual routing tables on the mesh path,
        and AOT compiles of the bucket programs. Idempotent — everything
        lands in the same caches update_bank reads — and safe to run on a
        background thread while ANOTHER coordinate's solves occupy the
        device (the overlap prefetched-dispatch lever: coordinate k+1's
        host prep runs under coordinate k's device work instead of as a
        serial gap between their dispatches)."""
        if not dataset.buckets:
            return
        # mirror update_bank's fold eligibility (variance-typed problems
        # run the per-bucket path, so stage per-bucket device args — a
        # stacked copy would pin HBM the update never reads)
        fold_eligible = (
            self.mesh is None
            and not self.compute_variances
            and len(dataset.buckets) > 1
        )
        groups = self._bucket_groups(
            bank.shape[1], dataset, fold_eligible=fold_eligible
        )
        for _sig, members in groups:
            if len(members) > 1:
                self._stacked_group_args(
                    dataset, members, with_residuals=has_residual_offsets
                )
            else:
                self._bucket_device_args(dataset.buckets[members[0]])
        if self.mesh is None:
            l1, l2 = self.regularization.split(self.reg_weight)
            self._warm_solvers(self._bucket_plans(
                bank, dataset,
                has_values_override=False,
                has_residual_offsets=has_residual_offsets,
                l1_d=jnp.float32(l1), l2_d=jnp.float32(l2),
                groups=groups if fold_eligible else None,
            ))
        elif has_residual_offsets:
            self._router_for(dataset)  # static routing tables, host-built

    def prewarm(self, specs) -> None:
        """AOT-compile the bucket programs of SEVERAL (bank, dataset,
        has_values_override, has_residual_offsets) quadruples in ONE
        threaded pool. The MF coordinate calls this before its first ALS
        half-step so BOTH sides' programs — including single-bucket sides
        that per-side warming used to skip — compile concurrently over
        the relay instead of serializing across half-steps."""
        if self.mesh is not None:
            return
        l1, l2 = self.regularization.split(self.reg_weight)
        l1_d, l2_d = jnp.float32(l1), jnp.float32(l2)
        plans = []
        for bank, dataset, has_override, has_resid in specs:
            plans += self._bucket_plans(
                bank, dataset,
                has_values_override=has_override,
                has_residual_offsets=has_resid,
                l1_d=l1_d, l2_d=l2_d,
            )
        self._warm_solvers(plans)

    def _warm_solvers(self, plans) -> None:
        """AOT-compile each distinct bucket program from its own thread so
        the relay compiles them CONCURRENTLY. The async jit-call path
        serializes compiles (per-function compilation lock + server-side
        queueing: measured 50 s for 4 MF programs) while threaded
        ``lower().compile()`` overlaps them (measured ~8 s for the same
        four); the persistent XLA cache never sees relay compiles, so
        this is the only cold-start lever. Compiled executables land in
        ``_aot_cache`` and the bucket loop calls them instead of the jit
        wrapper. Single fresh programs AOT-compile too (round-5: the
        jit-call path's compile is slower over the relay even alone, and
        single-bucket MF sides used to skip the pool entirely)."""
        from concurrent.futures import ThreadPoolExecutor

        fresh = [
            (sig, thunk) for sig, thunk in plans if sig not in self._aot_cache
        ]
        if not fresh:
            return
        with ThreadPoolExecutor(min(8, len(fresh))) as pool:
            compiled = list(pool.map(lambda item: item[1](), fresh))
        for (sig, _), exe in zip(fresh, compiled):
            # FIFO-bounded: the cache lives on the SHARED solver
            # namespace (process lifetime via _SOLVER_CACHE), so a
            # long-lived driver sweeping many bank/bucket shapes must
            # not accumulate executables forever
            while len(self._aot_cache) >= 64:
                self._aot_cache.pop(next(iter(self._aot_cache)))
            self._aot_cache[sig] = exe

    def update_bank(
        self,
        bank: Array,  # [E, D]
        dataset: RandomEffectDataset,
        residual_offsets: Optional[Array] = None,  # [n] replaces offsets
        values_override: Optional[Sequence[Array]] = None,
        with_variances: bool = False,
        defer_tracker: bool = False,
    ):
        """Solve every entity against its active data; returns the new bank
        and an aggregated tracker — plus the per-entity variance bank when
        ``with_variances`` (the Hdiag pass runs inside the bucket loop with
        the already-routed offsets in hand, so the mesh path pays no second
        residual all_to_all).

        ``values_override``: device-resident per-bucket feature values
        (aligned with ``dataset.buckets``) replacing each bucket's stored
        values — the MF ALS path recomputes latent feature views on
        device every half-step while the bucket STRUCTURE stays cached.

        ``defer_tracker``: return a LazyRandomEffectTracker whose stats
        stay on device — the GAME CD loop folds every coordinate's
        tracker into ONE batched readback per iteration instead of one
        round trip per bank update (~100 ms each over a relay).
        """
        l1, l2 = self.regularization.split(self.reg_weight)
        l1_d, l2_d = jnp.float32(l1), jnp.float32(l2)
        # Per-bucket stat vectors [iter_sum, iter_max, *reason_counts] stay
        # ON DEVICE until one stacked fetch at the end: every device->host
        # readback is a full host<->device round trip (~100ms over a
        # tunneled chip), so the loop stays fully async and the tracker
        # costs one sync total, not three per bucket.
        n_codes = max(CONVERGENCE_REASON_NAMES) + 1
        n_reals: List[int] = []
        stat_vecs: List[Array] = []
        if self.mesh is None and dataset.buckets:
            # one defensive copy so the fused updates can DONATE the bank
            # (in-place scatter per bucket) while the caller's reference
            # stays valid
            bank = jnp.array(bank, copy=True)
        residual_offsets, routed, router = self._route_residuals(
            dataset, residual_offsets
        )
        var_bank = jnp.zeros_like(bank) if with_variances else None
        if with_variances:
            from photon_ml_tpu.optim.problem import _VARIANCE_EPSILON
        # Same-shape bucket RUNS fold into one lax.scan dispatch (the
        # profiled ~125 ms of host gaps between per-bucket dispatches at
        # the config-4 shape, PERF_NOTES round 5); per-bucket paths keep
        # handling the mesh / values_override / variances cases.
        fold_eligible = (
            self.mesh is None
            and values_override is None
            and not with_variances
            and len(dataset.buckets) > 1
        )
        groups = self._bucket_groups(
            bank.shape[1], dataset, fold_eligible=fold_eligible
        )
        if self.mesh is None and dataset.buckets:
            self._warm_solvers(self._bucket_plans(
                bank, dataset,
                has_values_override=values_override is not None,
                has_residual_offsets=residual_offsets is not None,
                l1_d=l1_d, l2_d=l2_d,
                groups=groups if fold_eligible else None,
            ))
        for sig, members in groups:
            if len(members) > 1:
                kind = sig[0]
                (
                    codes_s, ix_s, v_s, lab_s, off_s, w_s, rows_s,
                ) = self._stacked_group_args(
                    dataset, members,
                    with_residuals=residual_offsets is not None,
                )
                if residual_offsets is not None:
                    off_s = jnp.where(
                        rows_s >= 0,
                        residual_offsets[jnp.maximum(rows_s, 0)],
                        0.0,
                    )
                fused_scan = self._aot_cache.get(
                    ("scan", kind, bank.shape, ix_s.shape)
                ) or getattr(self._solvers, f"fused_scan_{kind}")
                bank, it_sum, it_max, counts = fused_scan(
                    bank, codes_s, ix_s, v_s, lab_s, off_s, w_s, l1_d, l2_d
                )
                n_reals.append(
                    sum(dataset.buckets[bi].num_entities for bi in members)
                )
                stat_vecs.append(
                    jnp.concatenate([jnp.stack([it_sum, it_max]), counts])
                )
                continue
            bi = members[0]
            bucket = dataset.buckets[bi]
            (
                ix_d, v_d, lab_d, w_d, off_d, rows_d, codes_d,
            ) = self._bucket_device_args(
                bucket, with_values=values_override is None
            )
            if values_override is not None:
                # entries may be callables: the gather for bucket i is
                # then dispatched only when its solve runs, capping the
                # override's extra HBM at one bucket's values
                v_d = values_override[bi]
                if callable(v_d):
                    v_d = v_d()
                if self.mesh is not None:
                    (v_d,), _ = self._shard_entity_axis([v_d])
            if residual_offsets is not None:
                off_d = self._bucket_offsets(
                    bi, bucket, rows_d, residual_offsets, routed, router
                )
            n_real = bucket.num_entities
            kind = self._bucket_kind(bucket, bank.shape[1])
            if self.mesh is None:
                # fused path: gather + solve + scatter + tracker reductions
                # in one dispatch; AOT-warmed programs run their compiled
                # executable directly
                fused = self._aot_cache.get(
                    (kind, bank.shape, bucket.indices.shape)
                ) or getattr(self._solvers, f"fused_{kind}")
                bank, it_sum, it_max, counts = fused(
                    bank, codes_d, ix_d, v_d, lab_d, off_d, w_d, l1_d, l2_d
                )
            else:
                # padded entities carry zero data: their solve converges at
                # iteration 0 on a zero gradient — inert and cheap
                sl = bank[codes_d]
                (sl,), _ = self._shard_entity_axis([sl])
                solver = getattr(self._solvers, kind)
                new_sl, iters, reasons = solver(
                    sl, ix_d, v_d, lab_d, off_d, w_d, l1_d, l2_d
                )
                new_sl = new_sl[:n_real]
                iters = iters[:n_real]
                reasons = reasons[:n_real]
                bank = bank.at[codes_d].set(new_sl)
                it_sum = jnp.sum(iters)
                it_max = jnp.max(iters)
                counts = jnp.bincount(reasons, length=n_codes)
            if with_variances:
                # Hdiag at the just-solved rows, same off_d — no re-route
                sl_new = jnp.take(bank, codes_d, axis=0)
                if self.mesh is not None:
                    (sl_new,), _ = self._shard_entity_axis([sl_new])
                hd = self._solvers.hdiag(
                    sl_new, ix_d, v_d, lab_d, off_d, w_d, l2_d
                )
                var_bank = var_bank.at[codes_d].set(
                    1.0 / (hd[:n_real] + _VARIANCE_EPSILON)
                )
            n_reals.append(n_real)
            stat_vecs.append(
                jnp.concatenate([jnp.stack([it_sum, it_max]), counts])
            )
        if stat_vecs:
            from photon_ml_tpu.parallel import overlap

            total = sum(n_reals)

            def _finalize(all_stats, total=total):
                iter_sum = int(all_stats[:, 0].sum())
                iter_max = int(all_stats[:, 1].max())
                count_vec = all_stats[:, 2:].sum(axis=0)
                counts_dict: Dict[str, int] = {
                    CONVERGENCE_REASON_NAMES.get(code, "?"): int(cnt)
                    for code, cnt in enumerate(count_vec)
                    if cnt
                }
                return RandomEffectTracker(
                    num_entities=total,
                    iterations_mean=iter_sum / total,
                    iterations_max=iter_max,
                    reason_counts=counts_dict,
                )

            deferred = overlap.Deferred(jnp.stack(stat_vecs), _finalize)
            if defer_tracker and not deferred.done:
                # stats stay device-resident; the CD loop batch-fetches
                tracker = LazyRandomEffectTracker(deferred)
            else:
                # ONE explicit readback (transfer-guard safe)
                tracker = deferred.result()
        else:
            tracker = RandomEffectTracker(0, 0.0, 0, {})
        if with_variances:
            return bank, tracker, var_bank
        return bank, tracker

    def bank_variances(
        self,
        bank: Array,  # [E, D]
        dataset: RandomEffectDataset,
        residual_offsets: Optional[Array] = None,
    ) -> Array:
        """Per-entity coefficient variances 1/(Hdiag + eps) at the bank
        solution, [E, D] aligned with the bank (isComputingVariance:
        RandomEffectOptimizationProblem.scala:106-127 plumbs variance
        computation into every per-entity solve; the per-entity Bayesian
        models save them via ModelProcessingUtils.scala:44-189). One
        vmapped Hdiag pass per bucket — no solve."""
        from photon_ml_tpu.optim.problem import _VARIANCE_EPSILON

        _, l2 = self.regularization.split(self.reg_weight)
        l2_d = jnp.float32(l2)
        residual_offsets, routed, router = self._route_residuals(
            dataset, residual_offsets
        )
        variances = jnp.zeros_like(bank)
        for bi, bucket in enumerate(dataset.buckets):
            (
                ix_d, v_d, lab_d, w_d, off_d, rows_d, codes_d,
            ) = self._bucket_device_args(bucket)
            if residual_offsets is not None:
                off_d = self._bucket_offsets(
                    bi, bucket, rows_d, residual_offsets, routed, router
                )
            n_real = bucket.num_entities
            sl = bank[codes_d]
            if self.mesh is not None:
                (sl,), _ = self._shard_entity_axis([sl])
            hd = self._solvers.hdiag(sl, ix_d, v_d, lab_d, off_d, w_d, l2_d)
            variances = variances.at[codes_d].set(
                1.0 / (hd[:n_real] + _VARIANCE_EPSILON)
            )
        return variances

    def regularization_term(self, bank: Array) -> float:
        """Sum of per-entity reg terms (Coordinate.regTerm analog)."""
        from photon_ml_tpu.parallel import overlap

        return float(
            overlap.device_get(self.regularization_term_device(bank))
        )

    def regularization_term_device(self, bank: Array) -> Array:
        """The reg term as a DEVICE scalar — no readback: the overlap
        path folds it into the CD iteration's one batched fetch instead
        of two scalar pulls per coordinate per iteration."""
        l1, l2 = self.regularization.split(self.reg_weight)
        term = 0.5 * l2 * jnp.sum(bank * bank)
        if l1:
            term = term + l1 * jnp.sum(jnp.abs(bank))
        return term


def device_row_view(dataset: RandomEffectDataset):
    """Cached device copies of the row-aligned arrays (codes clamped,
    valid mask, local indices, local values). Scoring runs once per
    coordinate per CD iteration; without the cache every call re-uploads
    the whole [n, k] table (the round-2 per-iteration PCIe leak)."""
    hit = dataset.__dict__.get("_device_rows")
    if hit is None:
        hit = (
            jnp.maximum(jnp.asarray(dataset.row_entity_codes), 0),
            jnp.asarray(dataset.row_entity_codes >= 0),
            jnp.asarray(dataset.row_local_indices),
            jnp.asarray(dataset.row_local_values),
        )
        dataset.__dict__["_device_rows"] = hit
    return hit


def score_random_effect(
    bank: Array,  # [E, D]
    dataset: RandomEffectDataset,
) -> Array:
    """Row-aligned scores [n]: score_i = x_i(local) . bank[entity_i].

    Covers active AND passive rows (passive scoring with locally-projected
    features is equivalent to the reference's back-projected model scoring:
    features unseen in the entity's active data have zero coefficients,
    RandomEffectCoordinate.scala:178-199)."""
    codes, valid, ix, v = device_row_view(dataset)
    w_rows = jnp.take(bank, codes, axis=0)  # [n, D]
    score = jnp.sum(v * jnp.take_along_axis(w_rows, ix, axis=1), axis=-1)
    return jnp.where(valid, score, 0.0)


def dryrun_entity_bank(mesh) -> None:
    """Tiny entity-sharded vmapped solve for the multi-chip dry run:
    bank rows sharded over the mesh's first axis (expert-parallel analog)."""
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as P
    from photon_ml_tpu.ops.losses import LOGISTIC

    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    E, S, K, D = 2 * n_dev, 4, 4, 8
    rng = np.random.default_rng(0)
    solver = _bucket_solver(
        LOGISTIC, OptimizerConfig(max_iter=3), RegularizationContext()
    ).sparse
    sharding = NamedSharding(mesh, P(axis))
    bank = jax.device_put(jnp.zeros((E, D), jnp.float32), sharding)
    args = (
        jax.device_put(jnp.asarray(rng.integers(0, D, size=(E, S, K), dtype=np.int32)), sharding),
        jax.device_put(jnp.asarray(rng.normal(size=(E, S, K)).astype(np.float32)), sharding),
        jax.device_put(jnp.asarray((rng.uniform(size=(E, S)) > 0.5).astype(np.float32)), sharding),
        jax.device_put(jnp.zeros((E, S), jnp.float32), sharding),
        jax.device_put(jnp.ones((E, S), jnp.float32), sharding),
    )
    new_bank, iters, reasons = solver(bank, *args, jnp.float32(0.0), jnp.float32(0.1))
    # numeric oracle, not just finiteness: the sharded solve must equal
    # the same solver on unsharded (single-device) arrays
    host_args = tuple(jax.device_get(a) for a in args)
    oracle_bank, _, _ = solver(
        jnp.zeros((E, D), jnp.float32),
        *(jnp.asarray(a) for a in host_args),
        jnp.float32(0.0), jnp.float32(0.1),
    )
    np.testing.assert_allclose(
        np.asarray(new_bank), np.asarray(oracle_bank), atol=5e-3
    )
