"""Random-effect solver: per-entity GLM solves as vmapped while_loop banks.

Reference: photon-ml .../algorithm/RandomEffectCoordinate.scala:104-128 —
``activeData.join(optimizationProblems).join(modelsRDD).mapValues { local
optimizer.optimize }`` i.e. millions of independent single-node solves —
and optimization/game/RandomEffectOptimizationProblem.scala:41-130 (one
problem per entity, co-partitioned) with tracker aggregation
(RandomEffectOptimizationTracker.scala).

TPU-native: each bucket of equal-capacity entities is ONE
``jax.vmap(minimize_lbfgs)`` program over the entity axis — zero
cross-entity communication, matching the reference's key scalability
property, but with the per-entity JVM loop replaced by a single fused XLA
while_loop over [E_b, ...] blocks. Shard the entity axis over the mesh
("data" axis) for multi-chip (expert-parallel analog).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.random_effect_data import (
    RandomEffectBucket,
    RandomEffectDataset,
)
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim.common import (
    CONVERGENCE_REASON_NAMES,
    GRADIENT_WITHIN_TOLERANCE,
    MAX_ITERATIONS,
    NOT_CONVERGED,
    OptResult,
    check_convergence,
)
from photon_ml_tpu.optim.config import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
)
from photon_ml_tpu.optim.lbfgs import minimize_lbfgs, minimize_owlqn
from photon_ml_tpu.optim.tron import minimize_tron

Array = jnp.ndarray


@dataclass
class RandomEffectTracker:
    """Aggregated per-entity convergence stats
    (RandomEffectOptimizationTracker analog)."""

    num_entities: int
    iterations_mean: float
    iterations_max: int
    reason_counts: Dict[str, int]


def _bucket_solver(
    loss: PointwiseLoss,
    config: OptimizerConfig,
    regularization: RegularizationContext,
):
    """Build jit(solve)(bank_slice, bucket arrays, offsets, l1, l2)."""

    def entity_objective(ix, v, lab, off, w):
        def vg(coef):
            z = jnp.sum(v * jnp.take(coef, ix, axis=0), axis=-1) + off
            lv = loss.value(z, lab)
            ld = loss.d1(z, lab)
            c = w * ld
            val = jnp.sum(w * lv)
            grad = jnp.zeros_like(coef).at[ix.reshape(-1)].add(
                (v * c[:, None]).reshape(-1)
            )
            return val, grad

        def hvp(coef, direction):
            z = jnp.sum(v * jnp.take(coef, ix, axis=0), axis=-1) + off
            zd = jnp.sum(v * jnp.take(direction, ix, axis=0), axis=-1)
            c = w * loss.d2(z, lab) * zd
            return jnp.zeros_like(coef).at[ix.reshape(-1)].add(
                (v * c[:, None]).reshape(-1)
            )

        return vg, hvp

    use_tron = config.optimizer_type == OptimizerType.TRON
    use_owlqn = regularization.has_l1

    def _minimize(vg, hvp, coef0, l1):
        if use_tron:
            return minimize_tron(
                vg, hvp, coef0,
                max_iter=config.max_iter, tol=config.tolerance,
                max_cg=config.tron_max_cg,
            )
        if use_owlqn:
            return minimize_owlqn(
                vg, coef0, l1,
                max_iter=config.max_iter, tol=config.tolerance,
                history=config.lbfgs_history,
            )
        return minimize_lbfgs(
            vg, coef0,
            max_iter=config.max_iter, tol=config.tolerance,
            history=config.lbfgs_history,
        )

    @jax.jit
    def solve(bank, ix, v, lab, off, w, l1, l2):
        def one(coef0, ix_e, v_e, lab_e, off_e, w_e):
            vg_raw, hvp_raw = entity_objective(ix_e, v_e, lab_e, off_e, w_e)

            def vg(c):
                val, g = vg_raw(c)
                return val + 0.5 * l2 * jnp.vdot(c, c), g + l2 * c

            def hvp(c, d):
                return hvp_raw(c, d) + l2 * d

            return _minimize(vg, hvp, coef0, l1)

        res = jax.vmap(one)(bank, ix, v, lab, off, w)
        return res.coefficients, res.iterations, res.reason

    def _densify(ix, v, d_local):
        """One batched scatter of each entity's [S, k] sparse rows into a
        dense X [E, S, D] block."""
        e_b, s_b, _ = ix.shape
        X = jnp.zeros((e_b, s_b, d_local), v.dtype)
        return X.at[
            jnp.arange(e_b)[:, None, None],
            jnp.arange(s_b)[None, :, None],
            ix,
        ].add(v)

    @jax.jit
    def solve_dense(bank, ix, v, lab, off, w, l1, l2):
        """DENSE per-entity layout: one batched scatter densifies each
        entity's rows into X [E, S, D] up front, then every objective
        evaluation is a pair of batched matmuls riding the MXU. TPU
        scatters serialize (~8 ns/element, PERF_NOTES.md), so paying ONE
        scatter per bank update instead of one per line-search trial is a
        ~40x gradient-path win whenever S*D is small enough to afford the
        dense block."""
        X = _densify(ix, v, bank.shape[1])

        def one(coef0, X_e, lab_e, off_e, w_e):
            def vg(c):
                z = X_e @ c + off_e
                lv = loss.value(z, lab_e)
                ld = loss.d1(z, lab_e)
                val = jnp.sum(w_e * lv) + 0.5 * l2 * jnp.vdot(c, c)
                grad = X_e.T @ (w_e * ld) + l2 * c
                return val, grad

            def hvp(c, d):
                z = X_e @ c + off_e
                zd = X_e @ d
                return X_e.T @ (w_e * loss.d2(z, lab_e) * zd) + l2 * d

            return _minimize(vg, hvp, coef0, l1)

        res = jax.vmap(one)(bank, X, lab, off, w)
        return res.coefficients, res.iterations, res.reason

    @jax.jit
    def solve_dense_newton(bank, ix, v, lab, off, w, l1, l2):
        """Damped Newton in the DUAL (sample) space — the TPU-first
        redesign of the per-entity solve.

        The reference runs L-BFGS per entity (RandomEffectCoordinate.
        scala:104-128); quasi-Newton line searches cost many objective
        evaluations, and under vmap the whole bucket pays the slowest
        lane's trials every iteration. But the reservoir cap
        (RandomEffectDataSet.scala:254-317) bounds each entity's active
        samples S by construction, so the exact Newton step is cheap in
        the sample space: H = X^T D X + l2 I has rank <= S + ridge, and
        by Woodbury

            H^-1 g = (1/l2) * (g - X^T (l2 I + D G)^-1 D X g),

        with G = X X^T ([S, S], built once). Each iteration is two X
        passes + one batched S x S solve; quadratic convergence replaces
        ~O(10) line-search evaluations per L-BFGS iteration with ~1
        halving check per Newton iteration. Requires l2 > 0 and a twice-
        differentiable loss — update_bank selects it host-side.
        """
        del l1  # smooth path only (OWL-QN handles l1)
        _, s_b, _ = ix.shape
        X = _densify(ix, v, bank.shape[1])
        eye = jnp.eye(s_b, dtype=v.dtype)
        max_iter = config.max_iter
        tol = config.tolerance

        def one(coef0, X_e, lab_e, off_e, w_e):
            G = X_e @ X_e.T  # [S, S] sample Gram, one-time

            def value(c, z):
                return jnp.sum(w_e * loss.value(z, lab_e)) + 0.5 * l2 * jnp.vdot(c, c)

            def grad_norm(z, c):
                # Exact ||X^T cd + l2 c||: the all-dual expansion
                # (cd G cd + 2 l2 cd.Xc + l2^2 ||c||^2) cancels
                # catastrophically in float32 once ||g|| is small relative
                # to the individual terms, mis-reporting convergence — so
                # spend one [D, S] matvec per call on the true norm.
                cd = w_e * loss.d1(z, lab_e)
                return jnp.linalg.norm(X_e.T @ cd + l2 * c)

            z0 = X_e @ coef0 + off_e
            f0 = value(coef0, z0)
            g0_norm = grad_norm(z0, coef0)

            # state: (c, z, f, iter, reason). z is carried incrementally
            # (z_t = z + alpha * z_step, z_step computed in dual space) —
            # the only X touches per iteration are the X^T applies that
            # materialize the step and the exact gradient norm.
            def cond(st):
                return st[4] == NOT_CONVERGED

            def body(st):
                c, z, f, it, _ = st
                cd = w_e * loss.d1(z, lab_e)  # dual gradient weights [S]
                d2 = w_e * loss.d2(z, lab_e)  # [S] >= 0 (convex)
                zp = z - off_e  # = X c
                u = G @ cd + l2 * zp  # = X g, no X pass
                A = l2 * eye + d2[:, None] * G
                t = jnp.linalg.solve(A, d2 * u)
                r = cd - t
                step = -(X_e.T @ r) / l2 - c  # = -H^-1 g, ONE X pass
                z_step = -(G @ r) / l2 - zp  # = X step, dual space

                # Halving safeguard as a while_loop: the unit step is
                # accepted almost always on a convex GLM, and trials cost
                # NO X passes (z moves along the precomputed z_step).
                def ls_cond(carry):
                    alpha, f_t, k = carry
                    bad = (f_t > f) | ~jnp.isfinite(f_t)
                    return bad & (k < 8)

                def ls_body(carry):
                    alpha, _, k = carry
                    alpha = alpha * 0.5
                    c_t = c + alpha * step
                    z_t = z + alpha * z_step
                    return alpha, value(c_t, z_t), k + 1

                f1 = value(c + step, z + z_step)
                alpha, f_t, _ = jax.lax.while_loop(
                    ls_cond, ls_body, (jnp.float32(1.0), f1, jnp.int32(0))
                )
                # <= : at the optimum the step is ~0 and f_t == f;
                # accepting it lets the function-change test converge
                # instead of mis-reporting MaxIterations.
                moved = (f_t <= f) & jnp.isfinite(f_t)
                c2 = jnp.where(moved, c + alpha * step, c)
                z2 = jnp.where(moved, z + alpha * z_step, z)
                f2 = jnp.where(moved, f_t, f)
                it2 = it + 1
                g_norm = grad_norm(z2, c2)
                reason = jnp.where(
                    moved,
                    check_convergence(
                        it2, f, f2, g_norm, f0, g0_norm,
                        max_iter=max_iter, tol=tol,
                    ),
                    MAX_ITERATIONS,  # no decreasing step exists
                ).astype(jnp.int32)
                return (c2, z2, f2, it2, reason)

            init = (
                coef0, z0, f0, jnp.zeros((), jnp.int32),
                jnp.where(
                    g0_norm == 0.0, GRADIENT_WITHIN_TOLERANCE, NOT_CONVERGED
                ).astype(jnp.int32),
            )
            c, _, _, it, reason = jax.lax.while_loop(cond, body, init)
            return c, it, reason

        coefs, iters, reasons = jax.vmap(one)(bank, X, lab, off, w)
        return coefs, iters, reasons

    return solve, solve_dense, solve_dense_newton


@dataclass
class RandomEffectOptimizationProblem:
    """One solver config shared by all entities (the reference materializes
    an RDD of identical per-entity problems; here the per-entity state is
    just the bank row).

    ``mesh``: when set, every bucket's entity axis is sharded over the
    mesh's first axis — the expert-parallel analog of the reference's
    entity co-partitioning (RandomEffectDataSetPartitioner.scala:62-95).
    Load balance is by construction: a bucket's entities share one padded
    capacity, so equal-count splits are equal-cost (the reference needs a
    greedy partitioner because its per-entity costs vary).
    """

    loss: PointwiseLoss
    config: OptimizerConfig
    regularization: RegularizationContext
    reg_weight: float = 0.0
    mesh: Optional[object] = None
    # Per-entity data layout for the solves: "auto" densifies a bucket's
    # [E, S, k] sparse rows into [E, S, D] blocks when that fits the
    # budget below (matmul gradients instead of serialized TPU scatters
    # per line-search trial); "sparse"/"dense" force a layout.
    layout: str = "auto"
    dense_bytes_budget: int = 2 << 30

    def __post_init__(self):
        if self.layout not in ("auto", "sparse", "dense"):
            raise ValueError(f"unknown layout {self.layout!r}")
        self._solver, self._solver_dense, self._solver_newton = _bucket_solver(
            self.loss, self.config, self.regularization
        )
        # Device-resident copies of each bucket's static arrays (indices/
        # values/labels/weights), keyed by id(bucket). Coordinate descent
        # calls update_bank once per iteration with identical bucket data —
        # only the bank rows and residual offsets change — and host->device
        # re-transfer of the big [E, S, k] blocks would otherwise dominate
        # the whole update (measured: ~6s transfer vs ~1ms solve at
        # E=20k, S=16, k=32 over the tunneled chip). Entries hold only a
        # weakref to the bucket: callers that rebuild buckets every call
        # (factored-RE latent views, MF ALS half-steps) get their device
        # copies freed with the bucket instead of accumulating until OOM,
        # and a recycled id cannot alias because the dead entry removes
        # itself first.
        self._device_cache: Dict[int, Tuple[object, List[Array]]] = {}

    def _newton_eligible(self) -> bool:
        """The dual-space Newton solver needs l2 > 0 (Woodbury ridge), a
        twice-differentiable loss, and no l1/TRON machinery."""
        l1, l2 = self.regularization.split(self.reg_weight)
        return (
            l2 > 0.0
            and not l1
            and self.loss.has_hessian
            and self.config.optimizer_type != OptimizerType.TRON
        )

    def _use_dense(self, bucket, d_local: int) -> bool:
        if self.layout != "auto":
            return self.layout == "dense"
        e_b, s_b, _ = bucket.indices.shape
        itemsize = np.dtype(bucket.values.dtype).itemsize
        # X [E, S, D], plus the Newton path's G and A [E, S, S] blocks when
        # that solver would actually run — when S > D those Grams, not X,
        # dominate the footprint, but charging them to a bucket that can
        # only take the plain dense solver would wrongly force the
        # serialized-scatter sparse path.
        floats = e_b * s_b * d_local
        if self._newton_eligible():
            floats += e_b * 2 * s_b * s_b
        return floats * itemsize <= self.dense_bytes_budget

    def _bucket_device_args(self, bucket) -> List[Array]:
        """Device-resident (mesh-sharded if configured) static arrays for a
        bucket, transferred once and reused across update_bank calls. The
        cache holds a weakref: device copies die with the bucket."""
        import weakref

        key = id(bucket)
        hit = self._device_cache.get(key)
        if hit is not None and hit[0]() is bucket:
            return hit[1]
        arrs = [
            jnp.asarray(bucket.indices),
            jnp.asarray(bucket.values),
            jnp.asarray(bucket.labels),
            jnp.asarray(bucket.weights),
        ]
        if self.mesh is not None:
            arrs, _ = self._shard_entity_axis(arrs)
        cache = self._device_cache
        ref = weakref.ref(bucket, lambda _, k=key, c=cache: c.pop(k, None))
        self._device_cache[key] = (ref, arrs)
        return arrs

    def _shard_entity_axis(self, arrays):
        """Pad arrays' leading (entity) dim to the mesh axis size and place
        them entity-sharded; returns (padded arrays, real length)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        axis = mesh.axis_names[0]
        n_dev = int(mesh.shape[axis])
        sharding = NamedSharding(mesh, P(axis))
        e = arrays[0].shape[0]
        e_pad = ((e + n_dev - 1) // n_dev) * n_dev
        out = []
        for a in arrays:
            if e_pad != e:
                pad = jnp.zeros((e_pad - e,) + a.shape[1:], a.dtype)
                a = jnp.concatenate([a, pad])
            out.append(jax.device_put(a, sharding))
        return out, e

    def update_bank(
        self,
        bank: Array,  # [E, D]
        dataset: RandomEffectDataset,
        residual_offsets: Optional[np.ndarray] = None,  # [n] replaces offsets
    ) -> Tuple[Array, RandomEffectTracker]:
        """Solve every entity against its active data; returns the new bank
        and an aggregated tracker."""
        l1, l2 = self.regularization.split(self.reg_weight)
        iters_all: List[np.ndarray] = []
        reasons_all: List[np.ndarray] = []
        for bucket in dataset.buckets:
            ix_d, v_d, lab_d, w_d = self._bucket_device_args(bucket)
            off = bucket.offsets
            if residual_offsets is not None:
                safe_rows = np.maximum(bucket.row_index, 0)
                off = residual_offsets[safe_rows].astype(np.float32)
                off = np.where(bucket.row_index >= 0, off, 0.0)
            sl = bank[jnp.asarray(bucket.entity_codes)]
            dynamic = [sl, jnp.asarray(off)]
            n_real = sl.shape[0]
            if self.mesh is not None:
                # padded entities carry zero data: their solve converges at
                # iteration 0 on a zero gradient — inert and cheap
                dynamic, n_real = self._shard_entity_axis(dynamic)
            args = [dynamic[0], ix_d, v_d, lab_d, dynamic[1], w_d]
            if self._use_dense(bucket, bank.shape[1]):
                solver = (
                    self._solver_newton
                    if self._newton_eligible()
                    else self._solver_dense
                )
            else:
                solver = self._solver
            new_sl, iters, reasons = solver(
                *args,
                jnp.float32(l1),
                jnp.float32(l2),
            )
            new_sl = new_sl[:n_real]
            iters = iters[:n_real]
            reasons = reasons[:n_real]
            bank = bank.at[jnp.asarray(bucket.entity_codes)].set(new_sl)
            iters_all.append(np.asarray(iters))
            reasons_all.append(np.asarray(reasons))
        if iters_all:
            iters = np.concatenate(iters_all)
            reasons = np.concatenate(reasons_all)
            counts: Dict[str, int] = {}
            for code, cnt in zip(*np.unique(reasons, return_counts=True)):
                counts[CONVERGENCE_REASON_NAMES.get(int(code), "?")] = int(cnt)
            tracker = RandomEffectTracker(
                num_entities=len(iters),
                iterations_mean=float(iters.mean()),
                iterations_max=int(iters.max()),
                reason_counts=counts,
            )
        else:
            tracker = RandomEffectTracker(0, 0.0, 0, {})
        return bank, tracker

    def regularization_term(self, bank: Array) -> float:
        """Sum of per-entity reg terms (Coordinate.regTerm analog)."""
        l1, l2 = self.regularization.split(self.reg_weight)
        term = 0.5 * l2 * float(jnp.sum(bank * bank))
        if l1:
            term += l1 * float(jnp.sum(jnp.abs(bank)))
        return term


def score_random_effect(
    bank: Array,  # [E, D]
    dataset: RandomEffectDataset,
) -> Array:
    """Row-aligned scores [n]: score_i = x_i(local) . bank[entity_i].

    Covers active AND passive rows (passive scoring with locally-projected
    features is equivalent to the reference's back-projected model scoring:
    features unseen in the entity's active data have zero coefficients,
    RandomEffectCoordinate.scala:178-199)."""
    codes = jnp.maximum(jnp.asarray(dataset.row_entity_codes), 0)
    valid = jnp.asarray(dataset.row_entity_codes >= 0)
    w_rows = jnp.take(bank, codes, axis=0)  # [n, D]
    ix = jnp.asarray(dataset.row_local_indices)
    v = jnp.asarray(dataset.row_local_values)
    score = jnp.sum(v * jnp.take_along_axis(w_rows, ix, axis=1), axis=-1)
    return jnp.where(valid, score, 0.0)


def dryrun_entity_bank(mesh) -> None:
    """Tiny entity-sharded vmapped solve for the multi-chip dry run:
    bank rows sharded over the mesh's first axis (expert-parallel analog)."""
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as P
    from photon_ml_tpu.ops.losses import LOGISTIC

    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    E, S, K, D = 2 * n_dev, 4, 4, 8
    rng = np.random.default_rng(0)
    solver, _, _ = _bucket_solver(
        LOGISTIC, OptimizerConfig(max_iter=3), RegularizationContext()
    )
    sharding = NamedSharding(mesh, P(axis))
    bank = jax.device_put(jnp.zeros((E, D), jnp.float32), sharding)
    args = (
        jax.device_put(jnp.asarray(rng.integers(0, D, size=(E, S, K), dtype=np.int32)), sharding),
        jax.device_put(jnp.asarray(rng.normal(size=(E, S, K)).astype(np.float32)), sharding),
        jax.device_put(jnp.asarray((rng.uniform(size=(E, S)) > 0.5).astype(np.float32)), sharding),
        jax.device_put(jnp.zeros((E, S), jnp.float32), sharding),
        jax.device_put(jnp.ones((E, S), jnp.float32), sharding),
    )
    new_bank, iters, reasons = solver(bank, *args, jnp.float32(0.0), jnp.float32(0.1))
    assert bool(jnp.all(jnp.isfinite(new_bank)))
