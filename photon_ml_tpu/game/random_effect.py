"""Random-effect solver: per-entity GLM solves as vmapped while_loop banks.

Reference: photon-ml .../algorithm/RandomEffectCoordinate.scala:104-128 —
``activeData.join(optimizationProblems).join(modelsRDD).mapValues { local
optimizer.optimize }`` i.e. millions of independent single-node solves —
and optimization/game/RandomEffectOptimizationProblem.scala:41-130 (one
problem per entity, co-partitioned) with tracker aggregation
(RandomEffectOptimizationTracker.scala).

TPU-native: each bucket of equal-capacity entities is ONE
``jax.vmap(minimize_lbfgs)`` program over the entity axis — zero
cross-entity communication, matching the reference's key scalability
property, but with the per-entity JVM loop replaced by a single fused XLA
while_loop over [E_b, ...] blocks. Shard the entity axis over the mesh
("data" axis) for multi-chip (expert-parallel analog).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.random_effect_data import (
    RandomEffectBucket,
    RandomEffectDataset,
)
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim.common import (
    CONVERGENCE_REASON_NAMES,
    OptResult,
)
from photon_ml_tpu.optim.config import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
)
from photon_ml_tpu.optim.lbfgs import minimize_lbfgs, minimize_owlqn
from photon_ml_tpu.optim.tron import minimize_tron

Array = jnp.ndarray


@dataclass
class RandomEffectTracker:
    """Aggregated per-entity convergence stats
    (RandomEffectOptimizationTracker analog)."""

    num_entities: int
    iterations_mean: float
    iterations_max: int
    reason_counts: Dict[str, int]


def _bucket_solver(
    loss: PointwiseLoss,
    config: OptimizerConfig,
    regularization: RegularizationContext,
):
    """Build jit(solve)(bank_slice, bucket arrays, offsets, l1, l2)."""

    def entity_objective(ix, v, lab, off, w):
        def vg(coef):
            z = jnp.sum(v * jnp.take(coef, ix, axis=0), axis=-1) + off
            lv = loss.value(z, lab)
            ld = loss.d1(z, lab)
            c = w * ld
            val = jnp.sum(w * lv)
            grad = jnp.zeros_like(coef).at[ix.reshape(-1)].add(
                (v * c[:, None]).reshape(-1)
            )
            return val, grad

        def hvp(coef, direction):
            z = jnp.sum(v * jnp.take(coef, ix, axis=0), axis=-1) + off
            zd = jnp.sum(v * jnp.take(direction, ix, axis=0), axis=-1)
            c = w * loss.d2(z, lab) * zd
            return jnp.zeros_like(coef).at[ix.reshape(-1)].add(
                (v * c[:, None]).reshape(-1)
            )

        return vg, hvp

    use_tron = config.optimizer_type == OptimizerType.TRON
    use_owlqn = regularization.has_l1

    @jax.jit
    def solve(bank, ix, v, lab, off, w, l1, l2):
        def one(coef0, ix_e, v_e, lab_e, off_e, w_e):
            vg_raw, hvp_raw = entity_objective(ix_e, v_e, lab_e, off_e, w_e)

            def vg(c):
                val, g = vg_raw(c)
                return val + 0.5 * l2 * jnp.vdot(c, c), g + l2 * c

            if use_tron:
                def hvp(c, d):
                    return hvp_raw(c, d) + l2 * d

                return minimize_tron(
                    vg, hvp, coef0,
                    max_iter=config.max_iter, tol=config.tolerance,
                    max_cg=config.tron_max_cg,
                )
            if use_owlqn:
                return minimize_owlqn(
                    vg, coef0, l1,
                    max_iter=config.max_iter, tol=config.tolerance,
                    history=config.lbfgs_history,
                )
            return minimize_lbfgs(
                vg, coef0,
                max_iter=config.max_iter, tol=config.tolerance,
                history=config.lbfgs_history,
            )

        res = jax.vmap(one)(bank, ix, v, lab, off, w)
        return res.coefficients, res.iterations, res.reason

    return solve


@dataclass
class RandomEffectOptimizationProblem:
    """One solver config shared by all entities (the reference materializes
    an RDD of identical per-entity problems; here the per-entity state is
    just the bank row).

    ``mesh``: when set, every bucket's entity axis is sharded over the
    mesh's first axis — the expert-parallel analog of the reference's
    entity co-partitioning (RandomEffectDataSetPartitioner.scala:62-95).
    Load balance is by construction: a bucket's entities share one padded
    capacity, so equal-count splits are equal-cost (the reference needs a
    greedy partitioner because its per-entity costs vary).
    """

    loss: PointwiseLoss
    config: OptimizerConfig
    regularization: RegularizationContext
    reg_weight: float = 0.0
    mesh: Optional[object] = None

    def __post_init__(self):
        self._solver = _bucket_solver(self.loss, self.config, self.regularization)

    def _shard_entity_axis(self, arrays):
        """Pad arrays' leading (entity) dim to the mesh axis size and place
        them entity-sharded; returns (padded arrays, real length)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        axis = mesh.axis_names[0]
        n_dev = int(mesh.shape[axis])
        sharding = NamedSharding(mesh, P(axis))
        e = arrays[0].shape[0]
        e_pad = ((e + n_dev - 1) // n_dev) * n_dev
        out = []
        for a in arrays:
            if e_pad != e:
                pad = jnp.zeros((e_pad - e,) + a.shape[1:], a.dtype)
                a = jnp.concatenate([a, pad])
            out.append(jax.device_put(a, sharding))
        return out, e

    def update_bank(
        self,
        bank: Array,  # [E, D]
        dataset: RandomEffectDataset,
        residual_offsets: Optional[np.ndarray] = None,  # [n] replaces offsets
    ) -> Tuple[Array, RandomEffectTracker]:
        """Solve every entity against its active data; returns the new bank
        and an aggregated tracker."""
        l1, l2 = self.regularization.split(self.reg_weight)
        iters_all: List[np.ndarray] = []
        reasons_all: List[np.ndarray] = []
        for bucket in dataset.buckets:
            off = bucket.offsets
            if residual_offsets is not None:
                safe_rows = np.maximum(bucket.row_index, 0)
                off = residual_offsets[safe_rows].astype(np.float32)
                off = np.where(bucket.row_index >= 0, off, 0.0)
            sl = bank[jnp.asarray(bucket.entity_codes)]
            args = [
                sl,
                jnp.asarray(bucket.indices),
                jnp.asarray(bucket.values),
                jnp.asarray(bucket.labels),
                jnp.asarray(off),
                jnp.asarray(bucket.weights),
            ]
            n_real = sl.shape[0]
            if self.mesh is not None:
                # padded entities carry zero data: their solve converges at
                # iteration 0 on a zero gradient — inert and cheap
                args, n_real = self._shard_entity_axis(args)
            new_sl, iters, reasons = self._solver(
                *args,
                jnp.float32(l1),
                jnp.float32(l2),
            )
            new_sl = new_sl[:n_real]
            iters = iters[:n_real]
            reasons = reasons[:n_real]
            bank = bank.at[jnp.asarray(bucket.entity_codes)].set(new_sl)
            iters_all.append(np.asarray(iters))
            reasons_all.append(np.asarray(reasons))
        if iters_all:
            iters = np.concatenate(iters_all)
            reasons = np.concatenate(reasons_all)
            counts: Dict[str, int] = {}
            for code, cnt in zip(*np.unique(reasons, return_counts=True)):
                counts[CONVERGENCE_REASON_NAMES.get(int(code), "?")] = int(cnt)
            tracker = RandomEffectTracker(
                num_entities=len(iters),
                iterations_mean=float(iters.mean()),
                iterations_max=int(iters.max()),
                reason_counts=counts,
            )
        else:
            tracker = RandomEffectTracker(0, 0.0, 0, {})
        return bank, tracker

    def regularization_term(self, bank: Array) -> float:
        """Sum of per-entity reg terms (Coordinate.regTerm analog)."""
        l1, l2 = self.regularization.split(self.reg_weight)
        term = 0.5 * l2 * float(jnp.sum(bank * bank))
        if l1:
            term += l1 * float(jnp.sum(jnp.abs(bank)))
        return term


def score_random_effect(
    bank: Array,  # [E, D]
    dataset: RandomEffectDataset,
) -> Array:
    """Row-aligned scores [n]: score_i = x_i(local) . bank[entity_i].

    Covers active AND passive rows (passive scoring with locally-projected
    features is equivalent to the reference's back-projected model scoring:
    features unseen in the entity's active data have zero coefficients,
    RandomEffectCoordinate.scala:178-199)."""
    codes = jnp.maximum(jnp.asarray(dataset.row_entity_codes), 0)
    valid = jnp.asarray(dataset.row_entity_codes >= 0)
    w_rows = jnp.take(bank, codes, axis=0)  # [n, D]
    ix = jnp.asarray(dataset.row_local_indices)
    v = jnp.asarray(dataset.row_local_values)
    score = jnp.sum(v * jnp.take_along_axis(w_rows, ix, axis=1), axis=-1)
    return jnp.where(valid, score, 0.0)


def dryrun_entity_bank(mesh) -> None:
    """Tiny entity-sharded vmapped solve for the multi-chip dry run:
    bank rows sharded over the mesh's first axis (expert-parallel analog)."""
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as P
    from photon_ml_tpu.ops.losses import LOGISTIC

    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    E, S, K, D = 2 * n_dev, 4, 4, 8
    rng = np.random.default_rng(0)
    solver = _bucket_solver(
        LOGISTIC, OptimizerConfig(max_iter=3), RegularizationContext()
    )
    sharding = NamedSharding(mesh, P(axis))
    bank = jax.device_put(jnp.zeros((E, D), jnp.float32), sharding)
    args = (
        jax.device_put(jnp.asarray(rng.integers(0, D, size=(E, S, K), dtype=np.int32)), sharding),
        jax.device_put(jnp.asarray(rng.normal(size=(E, S, K)).astype(np.float32)), sharding),
        jax.device_put(jnp.asarray((rng.uniform(size=(E, S)) > 0.5).astype(np.float32)), sharding),
        jax.device_put(jnp.zeros((E, S), jnp.float32), sharding),
        jax.device_put(jnp.ones((E, S), jnp.float32), sharding),
    )
    new_bank, iters, reasons = solver(bank, *args, jnp.float32(0.0), jnp.float32(0.1))
    assert bool(jnp.all(jnp.isfinite(new_bank)))
