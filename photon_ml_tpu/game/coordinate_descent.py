"""Block coordinate descent over GAME coordinates.

Reference: photon-ml .../algorithm/CoordinateDescent.scala:50-262 —
init models + scores per coordinate (:82-119); per iteration, per
coordinate: residual = sum of OTHER coordinates' scores -> updateModel ->
rescore -> objective = loss(sum scores) + sum regTerms -> optional
per-iteration validation; tracks the best full model by the first
validation evaluator (:130-262). `run(numIterations, gameModel)` accepts a
warm-start model (:82-87).

The fullOuterJoin score algebra (KeyValueScore.scala:62-82) is plain
row-aligned vector arithmetic on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.coordinate import Coordinate
from photon_ml_tpu.game.data import GameDataset
from photon_ml_tpu.game.model import GameModel
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.obs.trace import start_span
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.utils.logging_util import PhotonLogger

Array = jnp.ndarray


@dataclass
class CoordinateDescentResult:
    model: GameModel
    objective_history: List[float]
    trackers: Dict[str, List[object]]
    validation_history: List[Dict[str, float]] = field(default_factory=list)
    best_model: Optional[GameModel] = None
    best_metric: Optional[float] = None
    # True when the run stopped early on a preemption signal; the last
    # completed iteration is checkpointed, so a restarted job resumes.
    preempted: bool = False


class CoordinateDescent:
    """run() drives the blocks in `update_sequence` order."""

    def __init__(
        self,
        coordinates: Dict[str, Coordinate],
        dataset: GameDataset,
        task: TaskType,
        *,
        update_sequence: Optional[List[str]] = None,
        validation_fn: Optional[Callable[[GameModel], Dict[str, float]]] = None,
        validation_metric: Optional[str] = None,
        validation_maximize: bool = True,
        logger: Optional[PhotonLogger] = None,
        checkpointer=None,  # photon_ml_tpu.utils.checkpoint.TrainingCheckpointer
        preemption_guard=None,  # photon_ml_tpu.utils.preemption.PreemptionGuard
    ):
        self.coordinates = coordinates
        self.dataset = dataset
        self.task = task
        self.update_sequence = update_sequence or list(coordinates)
        unknown = set(self.update_sequence) - set(coordinates)
        if unknown:
            raise ValueError(f"update sequence references unknown coordinates {unknown}")
        self.validation_fn = validation_fn
        self.validation_metric = validation_metric
        self.validation_maximize = validation_maximize
        self.logger = logger or PhotonLogger()
        self.checkpointer = checkpointer
        self.preemption_guard = preemption_guard

    def _preemption_agreed(self) -> bool:
        """Whether to stop for preemption — agreed ACROSS processes.

        Eviction may deliver SIGTERM to only some hosts; a per-process
        decision would desync the next iteration's collectives (stopped
        hosts leave the others blocking in psum forever). Every process
        polls at the same iteration boundary and an any-process OR via
        allgather makes the stop unanimous. Single-process runs skip the
        collective.
        """
        if self.preemption_guard is None:
            return False
        requested = self.preemption_guard.requested
        import jax

        if jax.process_count() == 1:
            return requested
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([requested], dtype=np.int32)
        )
        return bool(np.max(flags))

    def _objective(self, total_score: Array, models: Dict[str, object]) -> float:
        return self._objective_deferred(total_score, models).result()

    def _objective_deferred(
        self, total_score: Array, models: Dict[str, object]
    ) -> overlap.Deferred:
        """loss(sum of scores + offsets) + sum of reg terms
        (CoordinateDescent.scala:196-243) as a DEFERRED device scalar:
        the loss term and every coordinate's regularization term stay on
        device and the value joins the iteration's single batched
        readback (overlap.fetch_all) instead of 1 + 2-per-coordinate
        scalar pulls."""
        loss = loss_for_task(self.task)
        cached = self.__dict__.get("_device_cols")
        if cached is None:
            cached = (
                jnp.asarray(self.dataset.offsets),
                jnp.asarray(self.dataset.labels),
                jnp.asarray(self.dataset.weights),
            )
            self._device_cols = cached
        off, lab, w = cached
        z = total_score + off
        value = jnp.sum(w * loss.value(z, lab))
        for name, coord in self.coordinates.items():
            value = value + coord.regularization_term_device(models[name])
        return overlap.Deferred(value, float)

    def run(
        self,
        num_iterations: int,
        initial_model: Optional[GameModel] = None,
    ) -> CoordinateDescentResult:
        seq = self.update_sequence
        models: Dict[str, object] = {}
        scores: Dict[str, Array] = {}
        for name in seq:
            coord = self.coordinates[name]
            if initial_model is not None and initial_model.get_model(name) is not None:
                models[name] = initial_model.get_model(name)
            else:
                models[name] = coord.initialize_model()

        start_iteration = 0
        restored_meta = None
        if self.checkpointer is not None:
            latest = self.checkpointer.latest_step()
            if latest is not None:
                models = self.checkpointer.restore(latest, models)
                start_iteration = latest
                restored_meta = self.checkpointer.load_meta()
                self.logger.info(
                    "resumed coordinate descent from checkpoint step %d", latest
                )
        for name in seq:
            scores[name] = self.coordinates[name].score(models[name])

        objective_history: List[float] = []
        trackers: Dict[str, List[object]] = {name: [] for name in seq}
        validation_history: List[Dict[str, float]] = []
        best_model = None
        best_metric = None
        best_step = None
        preempted = False

        if (
            restored_meta is not None
            and restored_meta.get("best_step")
            and restored_meta.get("metric_name") == self.validation_metric
        ):
            # Resume keeps the ORIGINAL run's best-iteration selection
            # instead of silently re-judging the final model: metric from
            # the sidecar; weights from that step's checkpoint when orbax
            # still retains it (max_to_keep window). The sidecar is only
            # trusted when it tracked the SAME validation metric. If the
            # best step was pruned, the metric is dropped too — a stale
            # metric paired with different weights would corrupt both grid
            # selection and later best-iteration comparisons.
            step = int(restored_meta["best_step"])
            if step == start_iteration:
                best_model = GameModel(dict(models), self.task)
                best_metric = restored_meta.get("best_metric")
                best_step = step
            elif step in self.checkpointer.available_steps():
                best_model = GameModel(
                    self.checkpointer.restore(step, models), self.task
                )
                best_metric = restored_meta.get("best_metric")
                best_step = step
            else:
                self.logger.warning(
                    "best iteration %d checkpoint was pruned; re-judging "
                    "from the restored final model",
                    step,
                )

        for it in range(start_iteration, num_iterations):
            # obs/trace.py training span: one per CD iteration, with
            # per-coordinate children below — host wall-clock only (the
            # async dispatch window, not device time; --profile-dir
            # carries the device side)
            it_span = start_span("cd.iteration", iteration=it + 1)
            # Fresh O(C) score sum once per iteration; inside the sweep the
            # residual for each coordinate is total - own score (the
            # KeyValueScore `-` of the reference) and the total is patched
            # incrementally — O(1) adds per coordinate instead of the
            # O(C^2) sum-of-others join chain.
            total = jnp.zeros((self.dataset.num_rows,), jnp.float32)
            for name in seq:
                total = total + scores[name]
            # Prefetched dispatch (overlap lever 3): coordinate k+1's
            # host prep — bucket stacking/device transfer, layout builds,
            # AOT warming — runs on the background worker UNDER coordinate
            # k's device solves instead of as a serial gap between their
            # dispatches. The worker only ever touches the coordinate
            # being prefetched; the main thread wait()s before updating
            # it, so cache mutations never race.
            prefetched: Dict[str, object] = {}
            for j, name in enumerate(seq):
                coord = self.coordinates[name]
                overlap.wait(prefetched.pop(name, None))
                if overlap.overlap_enabled() and j + 1 < len(seq):
                    nxt = seq[j + 1]
                    if nxt != name and nxt not in prefetched:
                        prefetched[nxt] = overlap.submit(
                            self.coordinates[nxt].prepare, models[nxt]
                        )
                residual = total - scores[name] if len(seq) > 1 else None
                with obs_span(
                    "cd.update", parent_id=it_span.span_id,
                    trace_id=it_span.trace_id, coordinate=name,
                ):
                    models[name], tracker = coord.update_model(
                        models[name], residual
                    )
                    trackers[name].append(tracker)
                    new_score = coord.score(models[name])
                total = (
                    residual + new_score
                    if residual is not None
                    else new_score
                )
                scores[name] = new_score
            for fut in prefetched.values():  # surface prep failures
                overlap.wait(fut)

            # Deferred-readback discipline: the objective (loss + every
            # reg term) and every coordinate's tracker stats come back in
            # ONE batched device_get per iteration — not per-bucket, not
            # per-coordinate (each pull is a ~100 ms round trip over a
            # relay-attached chip).
            objective_d = self._objective_deferred(total, models)
            overlap.fetch_all(
                [objective_d]
                + [
                    getattr(trackers[name][-1], "deferred", None)
                    for name in seq
                ]
            )
            objective = objective_d.result()
            it_span.end(objective=objective)
            objective_history.append(objective)
            self.logger.info(
                "coordinate descent iter %d: objective=%g", it + 1, objective
            )
            if self.checkpointer is not None:
                # async artifact IO: the write leaves the critical path;
                # drain_io() below is the barrier before any stop
                overlap.submit_io(
                    self.checkpointer.save, it + 1, dict(models),
                    artifact=f"checkpoint step {it + 1}",
                )

            if self.validation_fn is not None:
                game_model = GameModel(
                    {name: models[name] for name in seq}, self.task
                )
                metrics = self.validation_fn(game_model)
                validation_history.append(metrics)
                self.logger.info("iter %d validation: %s", it + 1, metrics)
                if self.validation_metric is not None:
                    m = metrics[self.validation_metric]
                    better = (
                        best_metric is None
                        or (self.validation_maximize and m > best_metric)
                        or (not self.validation_maximize and m < best_metric)
                    )
                    if better:
                        best_metric = m
                        best_model = game_model
                        best_step = it + 1

            if self.checkpointer is not None:
                overlap.submit_io(
                    self.checkpointer.save_meta,
                    {
                        "best_step": best_step,
                        "best_metric": best_metric,
                        "metric_name": self.validation_metric,
                    },
                    artifact="checkpoint meta",
                )

            if self._preemption_agreed():
                # Iteration it+1 is complete (and checkpointed above when a
                # checkpointer is set) — stop at the safe boundary; a
                # restarted run resumes from this step. Flag even on the
                # final iteration so a multi-run caller (the grid sweep)
                # stops instead of starting more work in the grace window.
                preempted = True
                self.logger.warning(
                    "preemption requested: stopping after iteration %d/%d",
                    it + 1,
                    num_iterations,
                )
                break

        # IO barrier: every queued checkpoint/meta write is on disk before
        # the run returns — a preempted (or completed) run's restart
        # contract must not depend on a still-in-flight write.
        overlap.drain_io()

        if (
            self.validation_fn is not None
            and not validation_history
            and best_metric is None
            and start_iteration >= num_iterations
        ):
            # Fast-forwarded resume with no best-iteration sidecar (legacy
            # checkpoint): re-establish the restored model's validation
            # metrics so grid selection doesn't treat the combo as
            # metric-less.
            game_model = GameModel(
                {name: models[name] for name in seq}, self.task
            )
            metrics = self.validation_fn(game_model)
            validation_history.append(metrics)
            if self.validation_metric is not None:
                best_metric = metrics[self.validation_metric]
                best_model = game_model

        final = GameModel({name: models[name] for name in seq}, self.task)
        return CoordinateDescentResult(
            model=final,
            objective_history=objective_history,
            trackers=trackers,
            validation_history=validation_history,
            best_model=best_model if best_model is not None else final,
            best_metric=best_metric,
            preempted=preempted,
        )
